// C++ frontend demo: LeNet through the SYMBOLIC API — generated op.h
// wrappers build the graph, Symbol::SimpleBind allocates and binds an
// Executor, and the training loop runs Forward/Backward with SGD updates
// through the imperative waist (reference parity:
// cpp-package/example/lenet.cpp riding Symbol + Executor + op.h).
//
// Trains on a synthetic 10-class digit-blob problem (each class lights a
// different 2x2 patch region).  Exits 0 iff accuracy exceeds 80%.
#include <mxnet-cpp/MxNetCpp.h>

#include <cmath>
#include <cstdio>
#include <map>
#include <random>
#include <string>
#include <vector>

using mxnet::cpp::Context;
using mxnet::cpp::Executor;
using mxnet::cpp::NDArray;
using mxnet::cpp::Operator;
using mxnet::cpp::Symbol;

static Symbol LeNet() {
  Symbol data = Symbol::Variable("data");
  Symbol c1 = mxnet::cpp::op::Convolution(
      "conv1", data, Symbol::Variable("conv1_weight"),
      Symbol::Variable("conv1_bias"), "(3, 3)", 8, "(1, 1)", "()",
      "(1, 1)");
  Symbol a1 = mxnet::cpp::op::Activation("relu1", c1, "relu");
  Symbol p1 = mxnet::cpp::op::Pooling("pool1", a1, "(2, 2)", "max",
                                      false, false, "valid", "(2, 2)");
  Symbol c2 = mxnet::cpp::op::Convolution(
      "conv2", p1, Symbol::Variable("conv2_weight"),
      Symbol::Variable("conv2_bias"), "(3, 3)", 16, "(1, 1)", "()",
      "(1, 1)");
  Symbol a2 = mxnet::cpp::op::Activation("relu2", c2, "relu");
  Symbol p2 = mxnet::cpp::op::Pooling("pool2", a2, "(2, 2)", "max",
                                      false, false, "valid", "(2, 2)");
  Symbol flat = mxnet::cpp::op::Flatten("flat", p2);
  Symbol fc1 = mxnet::cpp::op::FullyConnected(
      "fc1", flat, Symbol::Variable("fc1_weight"),
      Symbol::Variable("fc1_bias"), 64);
  Symbol a3 = mxnet::cpp::op::Activation("relu3", fc1, "relu");
  Symbol fc2 = mxnet::cpp::op::FullyConnected(
      "fc2", a3, Symbol::Variable("fc2_weight"),
      Symbol::Variable("fc2_bias"), 10);
  return mxnet::cpp::op::SoftmaxOutput("softmax", fc2,
                                       Symbol::Variable("label"), 1.0,
                                       -1.0, false, false, false, "batch");
}

int main() {
  const int kBatch = 64, kPx = 16, kClasses = 10, kIters = 120;
  Context ctx = Context::cpu(0);

  Symbol net = LeNet();

  // JSON round-trip exercises save/load of the composed graph
  Symbol net2 = Symbol::FromJSON(net.ToJSON());
  if (net2.ListArguments() != net.ListArguments()) {
    std::fprintf(stderr, "JSON round-trip changed arguments\n");
    return 1;
  }

  std::map<std::string, std::vector<mx_uint>> shapes = {
      {"data", {kBatch, 1, kPx, kPx}}, {"label", {kBatch}}};
  Executor *exec = net.SimpleBind(ctx, shapes);
  std::vector<std::string> arg_names = net.ListArguments();

  // init weights uniform(-0.1, 0.1); data/label filled per batch
  std::mt19937 rng(0);
  std::uniform_real_distribution<float> uni(-0.1f, 0.1f);
  for (size_t i = 0; i < arg_names.size(); ++i) {
    if (arg_names[i] == "data" || arg_names[i] == "label") continue;
    std::vector<mx_uint> shp = exec->arg_arrays[i].GetShape();
    size_t n = 1;
    for (mx_uint d : shp) n *= d;
    std::vector<float> w(n);
    for (auto &v : w) v = uni(rng);
    exec->arg_arrays[i].SyncCopyFromCPU(w.data(), n);
  }

  // synthetic digits: class c lights a bright 4x4 block at position c
  std::normal_distribution<float> noise(0.f, 0.2f);
  auto make_batch = [&](std::vector<float> *xs, std::vector<float> *ys) {
    xs->assign(kBatch * kPx * kPx, 0.f);
    ys->assign(kBatch, 0.f);
    for (int i = 0; i < kBatch; ++i) {
      int c = static_cast<int>(rng() % kClasses);
      (*ys)[i] = static_cast<float>(c);
      int r0 = (c / 5) * 8, c0 = (c % 5) * 3;
      for (int r = 0; r < 4; ++r) {
        for (int cc = 0; cc < 4; ++cc) {
          (*xs)[i * kPx * kPx + (r0 + r) * kPx + (c0 + cc)] = 1.0f;
        }
      }
      for (int j = 0; j < kPx * kPx; ++j) {
        (*xs)[i * kPx * kPx + j] += noise(rng);
      }
    }
  };

  int data_idx = -1, label_idx = -1;
  for (size_t i = 0; i < arg_names.size(); ++i) {
    if (arg_names[i] == "data") data_idx = static_cast<int>(i);
    if (arg_names[i] == "label") label_idx = static_cast<int>(i);
  }

  std::vector<float> xs, ys, probs(kBatch * kClasses);
  float acc = 0.f;
  for (int it = 0; it < kIters; ++it) {
    make_batch(&xs, &ys);
    exec->arg_arrays[data_idx].SyncCopyFromCPU(xs.data(), xs.size());
    exec->arg_arrays[label_idx].SyncCopyFromCPU(ys.data(), ys.size());
    exec->Forward(true);
    exec->Backward();   // SoftmaxOutput head: ones head-grad contract
    for (size_t i = 0; i < arg_names.size(); ++i) {
      if (static_cast<int>(i) == data_idx ||
          static_cast<int>(i) == label_idx) {
        continue;
      }
      Operator("sgd_update")
          .SetParam("lr", 0.5)
          .SetInput(exec->arg_arrays[i])
          .SetInput(exec->grad_arrays[i])
          .Invoke(exec->arg_arrays[i]);
    }
    // accuracy over the last 10 iterations
    if (it >= kIters - 10) {
      exec->outputs[0].SyncCopyToCPU(probs.data(), probs.size());
      int hit = 0;
      for (int i = 0; i < kBatch; ++i) {
        int best = 0;
        for (int c = 1; c < kClasses; ++c) {
          if (probs[i * kClasses + c] > probs[i * kClasses + best]) best = c;
        }
        hit += (best == static_cast<int>(ys[i]));
      }
      acc += static_cast<float>(hit) / kBatch / 10.f;
    }
  }
  delete exec;

  std::printf("final accuracy %.3f\n", acc);
  if (acc > 0.8f) {
    std::printf("LENET SYMBOLIC TRAIN OK\n");
    return 0;
  }
  return 1;
}
