// C++ frontend demo: train a 2-layer MLP on a synthetic two-class problem,
// imperatively with autograd (reference parity: cpp-package/example/mlp.cpp,
// modernized to the Gluon-style imperative path the TPU runtime favors).
//
// Build/run: see cpp_package/example/Makefile.  Exits 0 iff the loss drops
// and final accuracy exceeds 90%.
#include <mxnet-cpp/MxNetCpp.h>

#include <cmath>
#include <cstdio>
#include <random>
#include <vector>

using mxnet::cpp::AutogradRecord;
using mxnet::cpp::Context;
using mxnet::cpp::NDArray;
using mxnet::cpp::Operator;

int main() {
  const int kSamples = 256, kIn = 8, kHidden = 32, kOut = 2;
  const float kLr = 0.1f;
  Context ctx = Context::cpu(0);

  // synthetic separable data: label = sum(x) > 0
  std::mt19937 rng(0);
  std::normal_distribution<float> dist(0.f, 1.f);
  std::vector<float> xs(kSamples * kIn), ys(kSamples);
  for (int i = 0; i < kSamples; ++i) {
    float s = 0.f;
    for (int j = 0; j < kIn; ++j) {
      xs[i * kIn + j] = dist(rng);
      s += xs[i * kIn + j];
    }
    ys[i] = s > 0.f ? 1.f : 0.f;
  }
  NDArray x(xs, {kSamples, kIn}, ctx);
  NDArray y(ys, {kSamples}, ctx);

  // parameters (uniform init, gluon Dense layout: W is (out, in))
  auto init = [&](mx_uint rows, mx_uint cols) {
    std::vector<float> w(cols == 0 ? rows : rows * cols);
    std::uniform_real_distribution<float> u(-0.3f, 0.3f);
    for (auto &v : w) v = u(rng);
    return NDArray(w, cols == 0 ? std::vector<mx_uint>{rows}
                                : std::vector<mx_uint>{rows, cols}, ctx);
  };
  std::vector<NDArray> params = {init(kHidden, kIn), init(kHidden, 0),
                                 init(kOut, kHidden), init(kOut, 0)};
  for (auto &p : params) p.AttachGrad();

  float first_loss = -1.f, last_loss = -1.f;
  for (int epoch = 0; epoch < 60; ++epoch) {
    NDArray loss;
    {
      AutogradRecord record;
      NDArray h1 = Operator("FullyConnected")
                       .SetParam("num_hidden", kHidden)
                       .SetInput(x).SetInput(params[0]).SetInput(params[1])
                       .Invoke();
      NDArray a1 = Operator("Activation")
                       .SetParam("act_type", "relu").SetInput(h1).Invoke();
      NDArray logits = Operator("FullyConnected")
                           .SetParam("num_hidden", kOut)
                           .SetInput(a1).SetInput(params[2])
                           .SetInput(params[3]).Invoke();
      NDArray ce = Operator("softmax_cross_entropy")
                       .SetInput(logits).SetInput(y).Invoke();
      loss = Operator("_div_scalar")
                 .SetParam("scalar", kSamples).SetInput(ce).Invoke();
    }
    loss.Backward();
    for (auto &p : params) {
      Operator("sgd_update")
          .SetParam("lr", kLr)
          .SetInput(p).SetInput(p.Grad())
          .Invoke(p);          // out=p: update lands in the parameter
    }
    float l = loss.CopyToVector()[0];
    if (epoch == 0) first_loss = l;
    last_loss = l;
    if (epoch % 20 == 0) std::printf("epoch %d loss %.4f\n", epoch, l);
  }

  // accuracy
  NDArray h1 = Operator("FullyConnected").SetParam("num_hidden", kHidden)
                   .SetInput(x).SetInput(params[0]).SetInput(params[1])
                   .Invoke();
  NDArray a1 = Operator("Activation").SetParam("act_type", "relu")
                   .SetInput(h1).Invoke();
  NDArray logits = Operator("FullyConnected").SetParam("num_hidden", kOut)
                       .SetInput(a1).SetInput(params[2]).SetInput(params[3])
                       .Invoke();
  std::vector<float> lg = logits.CopyToVector();
  int correct = 0;
  for (int i = 0; i < kSamples; ++i) {
    int pred = lg[i * kOut + 1] > lg[i * kOut] ? 1 : 0;
    if (pred == static_cast<int>(ys[i])) ++correct;
  }
  float acc = static_cast<float>(correct) / kSamples;
  std::printf("first_loss %.4f last_loss %.4f acc %.3f\n", first_loss,
              last_loss, acc);
  if (!(last_loss < first_loss * 0.5f) || !(acc > 0.9f)) {
    std::fprintf(stderr, "TRAINING FAILED\n");
    return 1;
  }
  std::printf("MLP TRAIN OK\n");
  return 0;
}
