// Header-only C++ frontend: Symbol (reference parity: cpp-package/
// include/mxnet-cpp/symbol.h — declarative graph construction over the C
// waist's MXSymbol* section, SURVEY.md §2.4).  Build graphs with
// Symbol::Variable + Operator-style composition (or the generated op.h
// wrappers), inspect them, round-trip JSON, infer shapes, and Bind into
// an Executor for training.
#ifndef MXNET_CPP_SYMBOL_HPP_
#define MXNET_CPP_SYMBOL_HPP_

#include <mxnet_tpu/c_api.h>

#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <vector>

#include "ndarray.hpp"

namespace mxnet {
namespace cpp {

class Executor;  // executor.hpp

class Symbol {
 public:
  Symbol() = default;
  explicit Symbol(SymbolHandle handle) : handle_(handle, &Symbol::Release) {}

  static Symbol Variable(const std::string &name) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateVariable(name.c_str(), &h));
    return Symbol(h);
  }

  SymbolHandle GetHandle() const { return handle_.get(); }
  bool IsNone() const { return handle_ == nullptr; }

  std::string GetName() const {
    const char *out = nullptr;
    int ok = 0;
    Check(MXSymbolGetName(handle_.get(), &out, &ok));
    return ok ? std::string(out) : std::string();
  }

  std::vector<std::string> ListArguments() const {
    return List(&MXSymbolListArguments);
  }
  std::vector<std::string> ListOutputs() const {
    return List(&MXSymbolListOutputs);
  }
  std::vector<std::string> ListAuxiliaryStates() const {
    return List(&MXSymbolListAuxiliaryStates);
  }

  std::string ToJSON() const {
    const char *js = nullptr;
    Check(MXSymbolSaveToJSON(handle_.get(), &js));
    return std::string(js);
  }

  static Symbol FromJSON(const std::string &json) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateFromJSON(json.c_str(), &h));
    return Symbol(h);
  }

  Symbol Copy() const {
    SymbolHandle h = nullptr;
    Check(MXSymbolCopy(handle_.get(), &h));
    return Symbol(h);
  }

  // Shape inference from named input shapes; fills the three sections in
  // ListArguments / ListOutputs / ListAuxiliaryStates order.
  void InferShape(
      const std::map<std::string, std::vector<mx_uint>> &arg_shapes,
      std::vector<std::vector<mx_uint>> *in_shape,
      std::vector<std::vector<mx_uint>> *out_shape,
      std::vector<std::vector<mx_uint>> *aux_shape) const {
    std::vector<const char *> keys;
    std::vector<mx_uint> ind_ptr{0};
    std::vector<mx_uint> data;
    for (const auto &kv : arg_shapes) {
      keys.push_back(kv.first.c_str());
      data.insert(data.end(), kv.second.begin(), kv.second.end());
      ind_ptr.push_back(static_cast<mx_uint>(data.size()));
    }
    mx_uint in_sz = 0, out_sz = 0, aux_sz = 0;
    const mx_uint *in_nd = nullptr, *out_nd = nullptr, *aux_nd = nullptr;
    const mx_uint **in_sh = nullptr, **out_sh = nullptr, **aux_sh = nullptr;
    int complete = 0;
    Check(MXSymbolInferShape(handle_.get(),
                             static_cast<mx_uint>(keys.size()), keys.data(),
                             ind_ptr.data(), data.data(), &in_sz, &in_nd,
                             &in_sh, &out_sz, &out_nd, &out_sh, &aux_sz,
                             &aux_nd, &aux_sh, &complete));
    auto fill = [](std::vector<std::vector<mx_uint>> *dst, mx_uint n,
                   const mx_uint *nd, const mx_uint **sh) {
      if (dst == nullptr) return;
      dst->clear();
      for (mx_uint i = 0; i < n; ++i) {
        dst->emplace_back(sh[i], sh[i] + nd[i]);
      }
    };
    fill(in_shape, in_sz, in_nd, in_sh);
    fill(out_shape, out_sz, out_nd, out_sh);
    fill(aux_shape, aux_sz, aux_nd, aux_sh);
  }

  // Bind with positional arrays (ListArguments order).  Gradients land in
  // grad_arrays in place after Executor::Backward.  Defined in
  // executor.hpp (needs the full Executor type).
  inline Executor *Bind(const Context &ctx,
                        const std::vector<NDArray> &arg_arrays,
                        const std::vector<NDArray> &grad_arrays,
                        const std::vector<mx_uint> &grad_reqs,
                        const std::vector<NDArray> &aux_arrays =
                            std::vector<NDArray>()) const;

  // SimpleBind: infer every shape from the given inputs, allocate args /
  // grads / aux, bind.  Defined in executor.hpp.
  inline Executor *SimpleBind(
      const Context &ctx,
      const std::map<std::string, std::vector<mx_uint>> &input_shapes,
      mx_uint grad_req = 1) const;

 private:
  using ListFn = int (*)(SymbolHandle, mx_uint *, const char ***);
  std::vector<std::string> List(ListFn fn) const {
    mx_uint n = 0;
    const char **arr = nullptr;
    Check(fn(handle_.get(), &n, &arr));
    return std::vector<std::string>(arr, arr + n);
  }
  static void Release(SymbolHandle h) {
    if (h != nullptr) MXSymbolFree(h);
  }
  std::shared_ptr<void> handle_;
};

// Builder for symbolic op nodes (the cpp-package Operator::CreateSymbol
// role): Op("Convolution").SetParam("kernel", ...).SetInput("data", x)
// .CreateSymbol("conv1").  The generated op.h wrappers ride this.
class SymbolBuilder {
 public:
  explicit SymbolBuilder(const std::string &op_name) : op_name_(op_name) {}

  template <typename T>
  SymbolBuilder &SetParam(const std::string &key, const T &value) {
    std::ostringstream os;
    os << value;
    param_keys_.push_back(key);
    param_vals_.push_back(os.str());
    return *this;
  }

  SymbolBuilder &SetInput(const std::string &arg_name, const Symbol &s) {
    if (!s.IsNone()) {
      input_keys_.push_back(arg_name);
      inputs_.push_back(s.GetHandle());
    }
    return *this;
  }

  SymbolBuilder &AddInput(const Symbol &s) {   // positional (variadic ops)
    inputs_.push_back(s.GetHandle());
    return *this;
  }

  Symbol CreateSymbol(const std::string &name = "") {
    // creator lookup by name (the table is interned in the library)
    mx_uint n = 0;
    AtomicSymbolCreator *cs = nullptr;
    Check(MXSymbolListAtomicSymbolCreators(&n, &cs));
    AtomicSymbolCreator creator = nullptr;
    for (mx_uint i = 0; i < n; ++i) {
      const char *nm = nullptr;
      MXSymbolGetAtomicSymbolName(cs[i], &nm);
      if (nm != nullptr && op_name_ == nm) {
        creator = cs[i];
        break;
      }
    }
    if (creator == nullptr) {
      throw std::runtime_error("unknown operator " + op_name_);
    }
    std::vector<const char *> pk, pv;
    for (auto &s : param_keys_) pk.push_back(s.c_str());
    for (auto &s : param_vals_) pv.push_back(s.c_str());
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateAtomicSymbol(
        creator, static_cast<mx_uint>(pk.size()), pk.data(), pv.data(), &h));
    Symbol sym(h);
    std::vector<const char *> ik;
    for (auto &s : input_keys_) ik.push_back(s.c_str());
    bool keyword = input_keys_.size() == inputs_.size() &&
                   !input_keys_.empty();
    Check(MXSymbolCompose(h, name.empty() ? nullptr : name.c_str(),
                          static_cast<mx_uint>(inputs_.size()),
                          keyword ? ik.data() : nullptr, inputs_.data()));
    return sym;
  }

 private:
  std::string op_name_;
  std::vector<std::string> param_keys_, param_vals_, input_keys_;
  std::vector<SymbolHandle> inputs_;
};

}  // namespace cpp
}  // namespace mxnet

#endif  // MXNET_CPP_SYMBOL_HPP_
