// Header-only C++ frontend: Executor (reference parity: cpp-package/
// include/mxnet-cpp/executor.h — bound computation over the C waist's
// MXExecutor* section).  Forward/Backward with gradients written into the
// bound grad arrays in place (GraphExecutor contract).
#ifndef MXNET_CPP_EXECUTOR_HPP_
#define MXNET_CPP_EXECUTOR_HPP_

#include <mxnet_tpu/c_api.h>

#include <map>
#include <string>
#include <vector>

#include "ndarray.hpp"
#include "symbol.hpp"

namespace mxnet {
namespace cpp {

class Executor {
 public:
  Executor(const Symbol &symbol, const Context &ctx,
           const std::vector<NDArray> &arg_arrays,
           const std::vector<NDArray> &grad_arrays,
           const std::vector<mx_uint> &grad_reqs,
           const std::vector<NDArray> &aux_arrays)
      : arg_arrays(arg_arrays), grad_arrays(grad_arrays),
        aux_arrays(aux_arrays) {
    std::vector<NDArrayHandle> args, grads, auxs;
    for (const auto &a : arg_arrays) args.push_back(a.GetHandle());
    for (const auto &g : grad_arrays) {
      grads.push_back(g.IsNone() ? nullptr : g.GetHandle());
    }
    for (const auto &a : aux_arrays) auxs.push_back(a.GetHandle());
    std::vector<mx_uint> reqs = grad_reqs;
    reqs.resize(args.size(), 0);
    if (grads.size() < args.size()) grads.resize(args.size(), nullptr);
    Check(MXExecutorBind(symbol.GetHandle(), ctx.dev_type, ctx.dev_id,
                         static_cast<mx_uint>(args.size()), args.data(),
                         grads.data(), reqs.data(),
                         static_cast<mx_uint>(auxs.size()), auxs.data(),
                         &handle_));
  }

  ~Executor() {
    if (handle_ != nullptr) MXExecutorFree(handle_);
  }
  Executor(const Executor &) = delete;
  Executor &operator=(const Executor &) = delete;

  void Forward(bool is_train) {
    Check(MXExecutorForward(handle_, is_train ? 1 : 0));
    RefreshOutputs();
  }

  // head_grads empty: ones-like head gradients (loss heads).
  void Backward(const std::vector<NDArray> &head_grads =
                    std::vector<NDArray>()) {
    std::vector<NDArrayHandle> hs;
    for (const auto &h : head_grads) hs.push_back(h.GetHandle());
    Check(MXExecutorBackward(handle_, static_cast<mx_uint>(hs.size()),
                             hs.data()));
  }

  // Outputs of the last Forward (refreshed per call).
  std::vector<NDArray> outputs;
  std::vector<NDArray> arg_arrays;
  std::vector<NDArray> grad_arrays;
  std::vector<NDArray> aux_arrays;

 private:
  void RefreshOutputs() {
    mx_uint n = 0;
    NDArrayHandle *outs = nullptr;
    Check(MXExecutorOutputs(handle_, &n, &outs));
    outputs.clear();
    for (mx_uint i = 0; i < n; ++i) outputs.emplace_back(outs[i]);
  }
  ExecutorHandle handle_ = nullptr;
};

inline Executor *Symbol::Bind(const Context &ctx,
                              const std::vector<NDArray> &arg_arrays,
                              const std::vector<NDArray> &grad_arrays,
                              const std::vector<mx_uint> &grad_reqs,
                              const std::vector<NDArray> &aux_arrays) const {
  return new Executor(*this, ctx, arg_arrays, grad_arrays, grad_reqs,
                      aux_arrays);
}

inline Executor *Symbol::SimpleBind(
    const Context &ctx,
    const std::map<std::string, std::vector<mx_uint>> &input_shapes,
    mx_uint grad_req) const {
  std::vector<std::vector<mx_uint>> in_sh, out_sh, aux_sh;
  InferShape(input_shapes, &in_sh, &out_sh, &aux_sh);
  std::vector<std::string> arg_names = ListArguments();
  std::vector<NDArray> args, grads, auxs;
  std::vector<mx_uint> reqs;
  for (size_t i = 0; i < in_sh.size(); ++i) {
    args.emplace_back(in_sh[i], ctx);
    // inputs the caller feeds per batch get no gradient storage
    bool is_input = input_shapes.count(arg_names[i]) != 0;
    grads.emplace_back(is_input ? NDArray() : NDArray(in_sh[i], ctx));
    reqs.push_back(is_input ? 0 : grad_req);
  }
  for (const auto &s : aux_sh) auxs.emplace_back(s, ctx);
  return new Executor(*this, ctx, args, grads, reqs, auxs);
}

}  // namespace cpp
}  // namespace mxnet

#endif  // MXNET_CPP_EXECUTOR_HPP_
