// Header-only C++ frontend: NDArray (reference parity: cpp-package/
// include/mxnet-cpp/ndarray.h — the RAII array riding the C API waist,
// SURVEY.md §2.4).
#ifndef MXNET_CPP_NDARRAY_HPP_
#define MXNET_CPP_NDARRAY_HPP_

#include <mxnet_tpu/c_api.h>

#include <cstring>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace mxnet {
namespace cpp {

inline void Check(int rc) {
  if (rc != 0) {
    throw std::runtime_error(MXGetLastError());
  }
}

struct Context {
  int dev_type;
  int dev_id;
  Context(int type, int id) : dev_type(type), dev_id(id) {}
  static Context cpu(int id = 0) { return Context(1, id); }
  static Context gpu(int id = 0) { return Context(2, id); }
  static Context tpu(int id = 0) { return Context(4, id); }
};

class NDArray {
 public:
  NDArray() = default;

  // Takes ownership of a raw handle (e.g. an op output).
  explicit NDArray(NDArrayHandle handle)
      : handle_(handle, &NDArray::Release) {}

  NDArray(const std::vector<mx_uint> &shape, const Context &ctx,
          int dtype = 0) {
    NDArrayHandle h = nullptr;
    Check(MXNDArrayCreateEx(shape.data(),
                            static_cast<mx_uint>(shape.size()), ctx.dev_type,
                            ctx.dev_id, 0, dtype, &h));
    handle_.reset(h, &NDArray::Release);
  }

  NDArray(const std::vector<float> &data, const std::vector<mx_uint> &shape,
          const Context &ctx)
      : NDArray(shape, ctx) {
    SyncCopyFromCPU(data.data(), data.size());
  }

  NDArrayHandle GetHandle() const { return handle_.get(); }
  bool IsNone() const { return handle_ == nullptr; }

  void SyncCopyFromCPU(const float *data, size_t size) {
    Check(MXNDArraySyncCopyFromCPU(handle_.get(), data, size));
  }

  void SyncCopyToCPU(float *data, size_t size) const {
    Check(MXNDArraySyncCopyToCPU(handle_.get(), data, size));
  }

  std::vector<float> CopyToVector() const {
    std::vector<float> out(Size());
    SyncCopyToCPU(out.data(), out.size());
    return out;
  }

  std::vector<mx_uint> GetShape() const {
    mx_uint dim = 0;
    const mx_uint *pdata = nullptr;
    Check(MXNDArrayGetShape(handle_.get(), &dim, &pdata));
    return std::vector<mx_uint>(pdata, pdata + dim);
  }

  int GetDType() const {
    int dtype = -1;
    Check(MXNDArrayGetDType(handle_.get(), &dtype));
    return dtype;
  }

  Context GetContext() const {
    int t = 0, id = 0;
    Check(MXNDArrayGetContext(handle_.get(), &t, &id));
    return Context(t, id);
  }

  size_t Size() const {
    size_t n = 1;
    for (mx_uint d : GetShape()) n *= d;
    return n;
  }

  void WaitToRead() const { Check(MXNDArrayWaitToRead(handle_.get())); }

  NDArray Slice(mx_uint begin, mx_uint end) const {
    NDArrayHandle h = nullptr;
    Check(MXNDArraySlice(handle_.get(), begin, end, &h));
    return NDArray(h);
  }

  NDArray Reshape(const std::vector<int> &dims) const {
    NDArrayHandle h = nullptr;
    Check(MXNDArrayReshape(handle_.get(),
                           static_cast<int>(dims.size()),
                           const_cast<int *>(dims.data()), &h));
    return NDArray(h);
  }

  // autograd surface (gluon-style imperative training from C++)
  void AttachGrad() {
    NDArrayHandle h = handle_.get();
    Check(MXAutogradMarkVariables(1, &h));
  }

  NDArray Grad() const {
    NDArrayHandle g = nullptr;
    Check(MXNDArrayGetGrad(handle_.get(), &g));
    return NDArray(g);
  }

  void Backward(bool retain_graph = false) const {
    NDArrayHandle h = handle_.get();
    Check(MXAutogradBackward(1, &h, retain_graph ? 1 : 0));
  }

  static void Save(const std::string &fname,
                   const std::vector<NDArray> &arrays,
                   const std::vector<std::string> &names) {
    if (!names.empty() && names.size() != arrays.size()) {
      throw std::invalid_argument(
          "NDArray::Save: names.size() must equal arrays.size()");
    }
    std::vector<NDArrayHandle> handles;
    std::vector<const char *> keys;
    for (const auto &a : arrays) handles.push_back(a.GetHandle());
    for (const auto &n : names) keys.push_back(n.c_str());
    Check(MXNDArraySave(fname.c_str(),
                        static_cast<mx_uint>(handles.size()), handles.data(),
                        names.empty() ? nullptr : keys.data()));
  }

  static void Load(const std::string &fname, std::vector<NDArray> *arrays,
                   std::vector<std::string> *names) {
    mx_uint n = 0, nn = 0;
    NDArrayHandle *harr = nullptr;
    const char **hnames = nullptr;
    Check(MXNDArrayLoad(fname.c_str(), &n, &harr, &nn, &hnames));
    arrays->clear();
    for (mx_uint i = 0; i < n; ++i) arrays->emplace_back(harr[i]);
    if (names != nullptr) {
      names->assign(hnames, hnames + nn);
    }
  }

 private:
  static void Release(NDArrayHandle h) {
    if (h != nullptr) MXNDArrayFree(h);
  }
  std::shared_ptr<void> handle_;
};

// RAII autograd recording scope (mxnet::cpp analog of autograd.record()).
class AutogradRecord {
 public:
  explicit AutogradRecord(bool train_mode = true) {
    // recording is switched on LAST: if either call throws mid-construction
    // the destructor never runs, and a process stuck in recording mode
    // would silently tape every subsequent op
    Check(MXAutogradSetIsTraining(train_mode ? 1 : 0, &prev_train_));
    try {
      Check(MXAutogradSetIsRecording(1, &prev_rec_));
    } catch (...) {
      MXAutogradSetIsTraining(prev_train_, nullptr);
      throw;
    }
  }
  ~AutogradRecord() {
    MXAutogradSetIsRecording(prev_rec_, nullptr);
    MXAutogradSetIsTraining(prev_train_, nullptr);
  }

 private:
  int prev_rec_ = 0;
  int prev_train_ = 0;
};

}  // namespace cpp
}  // namespace mxnet

#endif  // MXNET_CPP_NDARRAY_HPP_
