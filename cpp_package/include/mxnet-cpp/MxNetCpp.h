// Umbrella header of the C++ frontend (reference parity:
// cpp-package/include/mxnet-cpp/MxNetCpp.h).  Header-only over the C API
// waist (include/mxnet_tpu/c_api.h, libmxnet_tpu_c.so).
#ifndef MXNET_CPP_MXNETCPP_H_
#define MXNET_CPP_MXNETCPP_H_

#include "ndarray.hpp"
#include "operator.hpp"
#include "symbol.hpp"
#include "executor.hpp"
#include "op.h"

#endif  // MXNET_CPP_MXNETCPP_H_
