// Header-only C++ frontend: Operator builder (reference parity:
// cpp-package/include/mxnet-cpp/operator.h — Operator("Conv")
// .SetParam(...).SetInput(...).Invoke() riding MXImperativeInvoke).
#ifndef MXNET_CPP_OPERATOR_HPP_
#define MXNET_CPP_OPERATOR_HPP_

#include <mxnet_tpu/c_api.h>

#include <sstream>
#include <string>
#include <vector>

#include "ndarray.hpp"

namespace mxnet {
namespace cpp {

class Operator {
 public:
  explicit Operator(const std::string &op_name) : op_name_(op_name) {}

  template <typename T>
  Operator &SetParam(const std::string &key, const T &value) {
    std::ostringstream os;
    os << value;
    keys_.push_back(key);
    vals_.push_back(os.str());
    return *this;
  }

  Operator &SetInput(const NDArray &array) {
    inputs_.push_back(array.GetHandle());
    return *this;
  }

  Operator &operator()(const NDArray &array) { return SetInput(array); }

  // Write results into an existing array (the ABI's out= contract — how
  // sgd_update(w, g, out=w) updates a parameter in place).
  Operator &SetOutput(const NDArray &array) {
    outputs_.push_back(array.GetHandle());
    return *this;
  }

  // Run the op; returns all (allocated) outputs, or the supplied outputs.
  std::vector<NDArray> InvokeMulti() {
    std::vector<const char *> k, v;
    for (auto &s : keys_) k.push_back(s.c_str());
    for (auto &s : vals_) v.push_back(s.c_str());
    int num_outputs = static_cast<int>(outputs_.size());
    NDArrayHandle *outputs = outputs_.empty() ? nullptr : outputs_.data();
    Check(MXImperativeInvokeByName(
        op_name_.c_str(), static_cast<int>(inputs_.size()), inputs_.data(),
        &num_outputs, &outputs, static_cast<int>(k.size()), k.data(),
        v.data()));
    std::vector<NDArray> out;
    if (!outputs_.empty()) return out;  // results landed in SetOutput arrays
    out.reserve(num_outputs);
    for (int i = 0; i < num_outputs; ++i) out.emplace_back(outputs[i]);
    return out;
  }

  void Invoke(const NDArray &out) {
    SetOutput(out);
    InvokeMulti();
  }

  NDArray Invoke() { return InvokeMulti().at(0); }

  static std::vector<std::string> ListAll() {
    mx_uint n = 0;
    const char **names = nullptr;
    Check(MXListAllOpNames(&n, &names));
    return std::vector<std::string>(names, names + n);
  }

 private:
  std::string op_name_;
  std::vector<std::string> keys_, vals_;
  std::vector<NDArrayHandle> inputs_, outputs_;
};

}  // namespace cpp
}  // namespace mxnet

#endif  // MXNET_CPP_OPERATOR_HPP_
