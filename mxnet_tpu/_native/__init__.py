"""Native runtime loader: builds (once) and loads the C++ shared library.

The C++ core (``src/engine.cc`` threaded dependency engine,
``src/recordio.cc`` RecordIO) is the native half of the runtime (SURVEY.md
N1/N14/N17).  Built lazily with ``make`` on first import — a laptop-style
`pip install -e` flow — and cached; if no toolchain is available the Python
fallbacks take over transparently (``lib() -> None``).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB = None
_TRIED = False
_LOCK = threading.Lock()

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libmxnet_tpu_native.so")
_SRC = os.path.normpath(os.path.join(_HERE, "..", "..", "src"))


def _needs_build() -> bool:
    if not os.path.exists(_SO):
        return True
    so_m = os.path.getmtime(_SO)
    try:
        return any(
            os.path.getmtime(os.path.join(_SRC, f)) > so_m
            for f in os.listdir(_SRC) if f.endswith(".cc"))
    except OSError:
        return False


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    # engine
    lib.MXNativeEngineCreate.restype = c.c_void_p
    lib.MXNativeEngineCreate.argtypes = [c.c_int]
    lib.MXNativeEngineFree.argtypes = [c.c_void_p]
    lib.MXNativeEngineNewVar.restype = c.c_void_p
    lib.MXNativeEngineNewVar.argtypes = [c.c_void_p]
    lib.MXNativeEngineDeleteVar.argtypes = [c.c_void_p, c.c_void_p]
    lib.MXNativeEnginePush.argtypes = [
        c.c_void_p, c.c_void_p, c.c_void_p,
        c.POINTER(c.c_void_p), c.c_int,
        c.POINTER(c.c_void_p), c.c_int, c.c_int]
    lib.MXNativeEngineWaitForVar.restype = c.c_int64
    lib.MXNativeEngineWaitForVar.argtypes = [c.c_void_p, c.c_void_p]
    lib.MXNativeEngineWaitForAll.argtypes = [c.c_void_p]
    # recordio
    lib.MXNativeRecordIOGetLastError.restype = c.c_char_p
    lib.MXNativeRecordIOWriterCreate.restype = c.c_void_p
    lib.MXNativeRecordIOWriterCreate.argtypes = [c.c_char_p]
    lib.MXNativeRecordIOWriterWrite.restype = c.c_int
    lib.MXNativeRecordIOWriterWrite.argtypes = [c.c_void_p, c.c_char_p,
                                                c.c_uint64]
    lib.MXNativeRecordIOWriterTell.restype = c.c_int64
    lib.MXNativeRecordIOWriterTell.argtypes = [c.c_void_p]
    lib.MXNativeRecordIOWriterClose.argtypes = [c.c_void_p]
    lib.MXNativeRecordIOReaderCreate.restype = c.c_void_p
    lib.MXNativeRecordIOReaderCreate.argtypes = [c.c_char_p]
    lib.MXNativeRecordIOReaderRead.restype = c.c_int
    # out pointer declared void* so ctypes doesn't NUL-truncate the buffer
    lib.MXNativeRecordIOReaderRead.argtypes = [
        c.c_void_p, ctypes.POINTER(c.c_void_p), ctypes.POINTER(c.c_uint64)]
    lib.MXNativeRecordIOReaderSeek.restype = c.c_int
    lib.MXNativeRecordIOReaderSeek.argtypes = [c.c_void_p, c.c_uint64]
    lib.MXNativeRecordIOReaderTell.restype = c.c_int64
    lib.MXNativeRecordIOReaderTell.argtypes = [c.c_void_p]
    lib.MXNativeRecordIOReaderClose.argtypes = [c.c_void_p]


def lib():
    """The loaded native library, or None when unavailable."""
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("MXNET_NO_NATIVE", "") in ("1", "true"):
            return None
        try:
            if _needs_build():
                subprocess.run(["make", "-C", _SRC,
                                "OUT=" + _SO], check=True,
                               capture_output=True, timeout=120)
            loaded = ctypes.CDLL(_SO)
            _declare(loaded)
            _LIB = loaded
        except (OSError, subprocess.SubprocessError, AttributeError):
            # AttributeError: stale .so missing newly added symbols — try
            # one forced rebuild, else fall back to pure Python
            try:
                subprocess.run(["make", "-C", _SRC, "clean"],
                               capture_output=True, timeout=30)
                subprocess.run(["make", "-C", _SRC, "OUT=" + _SO],
                               check=True, capture_output=True, timeout=120)
                loaded = ctypes.CDLL(_SO)
                _declare(loaded)
                _LIB = loaded
            except (OSError, subprocess.SubprocessError, AttributeError):
                _LIB = None
        return _LIB
