"""``sym.image`` namespace (parity: python/mxnet/symbol/image.py, generated
from the ``_image_`` op prefix)."""
from __future__ import annotations

from ..ops.registry import OPS
from . import register as _register

_PREFIX = "_image_"

for _name in list(OPS):
    if _name.startswith(_PREFIX):
        _short = _name[len(_PREFIX):]
        _fn = _register._make_fn(_name)
        _fn.__name__ = _short
        _fn.__qualname__ = _short
        globals()[_short] = _fn
