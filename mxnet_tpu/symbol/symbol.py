"""Symbol: declarative graph construction, composition, inference, binding.

Reference analog: ``python/mxnet/symbol/symbol.py`` over the NNVM graph IR
(``3rdparty/tvm`` nnvm: Node/NodeEntry/Symbol; passes Gradient/PlanMemory —
SURVEY.md N6/N19).  TPU-native design: the graph is a lightweight Python DAG
over the op registry; *binding* lowers it to a pure JAX function that XLA
compiles whole (fusion + memory planning + layout all delegated to XLA — the
PlanMemory/AttachOpExecs pass pipeline of graph_executor.cc:514-905 collapses
into one jit).  Gradient graphs come from jax.vjp of that function rather than
an nnvm Gradient pass.  JSON (de)serialization keeps the reference's
``nodes/arg_nodes/heads`` format so checkpoints interchange.
"""
from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..base import MXNetError, AttrDict
from ..context import Context, current_context
from ..ops.registry import get_op, Operator, OPS

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json",
           "zeros", "ones", "arange"]


class _Node:
    __slots__ = ("op", "name", "attrs", "inputs", "__weakref__")

    def __init__(self, op: Optional[Operator], name: str,
                 attrs: Dict[str, Any], inputs: List[Tuple["_Node", int]]):
        self.op = op
        self.name = name
        self.attrs = attrs          # raw user attrs (JSON-serializable)
        self.inputs = inputs

    @property
    def is_var(self):
        return self.op is None

    def parsed_attrs(self) -> AttrDict:
        a = {k: v for k, v in self.attrs.items() if not k.startswith("__")}
        return self.op.parse_attrs(a)

    def num_outputs(self):
        return 1 if self.is_var else self.op.num_outputs(self.parsed_attrs())

    def num_visible(self):
        return 1 if self.is_var else \
            self.op.num_visible_outputs(self.parsed_attrs())


def _auto_name(prefix: str) -> str:
    from ..name import current_scope
    return current_scope().get(None, prefix)


class Symbol:
    """A set of output entries of a graph (parity: mxnet.symbol.Symbol)."""

    __slots__ = ("_outputs",)

    def __init__(self, outputs: List[Tuple[_Node, int]]):
        self._outputs = outputs

    # ---- basic info -----------------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __repr__(self):
        return "<Symbol %s>" % (self.name or "group [%d]" % len(self._outputs))

    def __iter__(self):
        for i in range(len(self.list_outputs())):
            yield self[i]

    def __len__(self):
        return len(self._outputs)

    def _topo(self) -> List[_Node]:
        order, seen = [], set()
        stack = [(n, False) for n, _ in reversed(self._outputs)]
        while stack:
            node, done = stack.pop()
            if done:
                order.append(node)
                continue
            if id(node) in seen:
                continue
            seen.add(id(node))
            stack.append((node, True))
            for parent, _ in reversed(node.inputs):
                if id(parent) not in seen:
                    stack.append((parent, False))
        return order

    def _aux_var_ids(self) -> set:
        aux = set()
        for node in self._topo():
            if node.is_var or not node.op.aux_inputs:
                continue
            for i in node.op.aux_inputs:
                if i < len(node.inputs) and node.inputs[i][0].is_var:
                    aux.add(id(node.inputs[i][0]))
        return aux

    def list_arguments(self) -> List[str]:
        aux = self._aux_var_ids()
        return [n.name for n in self._topo() if n.is_var and id(n) not in aux]

    def list_auxiliary_states(self) -> List[str]:
        aux = self._aux_var_ids()
        return [n.name for n in self._topo() if n.is_var and id(n) in aux]

    def list_outputs(self) -> List[str]:
        names = []
        for node, idx in self._outputs:
            if node.is_var:
                names.append(node.name)
            elif node.num_visible() > 1 or node.num_outputs() > 1:
                names.append("%s_output%d" % (node.name, idx))
            else:
                names.append("%s_output" % node.name)
        return names

    def list_inputs(self):
        return [n.name for n in self._topo() if n.is_var]

    @property
    def outputs(self):
        return self.list_outputs()

    def get_internals(self) -> "Symbol":
        outs = []
        for node in self._topo():
            for i in range(node.num_visible()):
                outs.append((node, i))
        return Symbol(outs)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                # also allow internals lookup by name
                internals = self.get_internals()
                inames = internals.list_outputs()
                if index in inames:
                    return internals[inames.index(index)]
                raise MXNetError("output %r not found; have %s" % (index, names))
            index = names.index(index)
        if isinstance(index, slice):
            return Symbol(self._outputs[index])
        return Symbol([self._outputs[index]])

    def attr(self, key):
        if len(self._outputs) == 1:
            return self._outputs[0][0].attrs.get(key)
        return None

    def attr_dict(self):
        out = {}
        for node in self._topo():
            if node.attrs:
                out[node.name] = {k: str(v) for k, v in node.attrs.items()}
        return out

    def _set_attr(self, **kwargs):
        for node, _ in self._outputs:
            node.attrs.update(kwargs)

    # ---- composition / arithmetic --------------------------------------
    def __call__(self, *args, **kwargs):
        """Compose: replace free variables with the given symbols."""
        self._compose(*args, **kwargs)
        return self

    def _compose(self, *args, **kwargs):
        mapping = {}
        if args:
            arg_names = self.list_arguments()
            for name_, s in zip(arg_names, args):
                mapping[name_] = s
        mapping.update(kwargs)
        replace = {}
        for node in self._topo():
            if node.is_var and node.name in mapping:
                rep = mapping[node.name]
                if len(rep._outputs) != 1:
                    raise MXNetError("can only compose with single-output symbols")
                replace[id(node)] = rep._outputs[0]
        for node in self._topo():
            node.inputs = [replace.get(id(p), (p, i)) for p, i in node.inputs]
        self._outputs = [replace.get(id(n), (n, i)) for n, i in self._outputs]

    def _binary(self, other, op, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _create(op, [a, b], {})
        if isinstance(other, (int, float)):
            return _create(scalar_op, [self], {"scalar": float(other)})
        raise TypeError("unsupported operand type %s" % type(other))

    def __add__(self, other):
        return self._binary(other, "elemwise_add" if isinstance(other, Symbol)
                            else "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        if isinstance(other, (int, float)):
            return _create("_rminus_scalar", [self], {"scalar": float(other)})
        return self._binary(other, "broadcast_sub", None, reverse=True)

    def __mul__(self, other):
        return self._binary(other, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        if isinstance(other, (int, float)):
            return _create("_rdiv_scalar", [self], {"scalar": float(other)})
        return self._binary(other, "broadcast_div", None, reverse=True)

    def __pow__(self, other):
        return self._binary(other, "broadcast_power", "_power_scalar")

    def __neg__(self):
        return _create("negative", [self], {})

    # method forms mirroring NDArray
    def reshape(self, shape, **kw):
        return _create("Reshape", [self], {"shape": shape, **kw})

    def flatten(self):
        return _create("Flatten", [self], {})

    def transpose(self, axes=()):
        return _create("transpose", [self], {"axes": axes})

    def sum(self, axis=None, keepdims=False):
        return _create("sum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return _create("mean", [self], {"axis": axis, "keepdims": keepdims})

    def slice_axis(self, axis, begin, end):
        return _create("slice_axis", [self],
                       {"axis": axis, "begin": begin, "end": end})

    def astype(self, dtype):
        return _create("Cast", [self], {"dtype": np.dtype(dtype).name})

    # ---- inference ------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        """Two-phase inference (the InferShape pass, SURVEY.md N6):
        forward-fill via jax.eval_shape + per-op shape hints for unknown
        parameter shapes."""
        arg_names = self.list_arguments()
        known: Dict[str, tuple] = {}
        if args:
            for n, s in zip(arg_names, args):
                if s is not None:
                    known[n] = tuple(s)
        known.update({k: tuple(v) for k, v in kwargs.items() if v is not None})

        topo = self._topo()
        shapes: Dict[Tuple[int, int], Optional[tuple]] = {}
        for node in topo:
            if not node.is_var:
                continue
            if node.name in known:
                shapes[(id(node), 0)] = known[node.name]
            elif node.attrs.get("__shape__") is not None:
                # declared shape on the Variable (reference symbol.py var
                # shape attr participates in InferShape); 0-dims mean
                # "unknown, infer me" (gluon deferred init) — don't seed those.
                # After a tojson round-trip the attr arrives as its string
                # repr ("(1, 2)"), so parse before iterating.
                declared = node.attrs["__shape__"]
                if isinstance(declared, str):
                    import ast
                    declared = ast.literal_eval(declared)
                declared = tuple(declared)
                if all(d > 0 for d in declared):
                    shapes[(id(node), 0)] = declared

        import jax

        for _pass in range(3):
            changed = False
            for node in topo:
                if node.is_var:
                    continue
                attrs = node.parsed_attrs()
                in_sh = [shapes.get((id(p), i)) for p, i in node.inputs]
                if node.op.shape_hint is not None and any(
                        s is None for s in in_sh):
                    filled = node.op.shape_hint(attrs, in_sh)
                    for (p, pi), s in zip(node.inputs, filled):
                        if s is not None and shapes.get((id(p), pi)) is None:
                            shapes[(id(p), pi)] = tuple(s)
                            changed = True
                    in_sh = [shapes.get((id(p), i)) for p, i in node.inputs]
                if all(s is not None for s in in_sh) and \
                        shapes.get((id(node), 0)) is None:
                    out_sh = _abstract_node(node, attrs, in_sh)
                    for i, s in enumerate(out_sh):
                        shapes[(id(node), i)] = s
                    changed = True
            if not changed:
                break

        aux_names = self.list_auxiliary_states()
        var_shapes = {n.name: shapes.get((id(n), 0))
                      for n in topo if n.is_var}
        arg_shapes = [var_shapes.get(n) for n in arg_names]
        aux_shapes = [var_shapes.get(n) for n in aux_names]
        out_shapes = [shapes.get((id(n), i)) for n, i in self._outputs]
        if not partial and (any(s is None for s in arg_shapes) or
                            any(s is None for s in out_shapes)):
            missing = [n for n, s in zip(arg_names, arg_shapes) if s is None]
            raise MXNetError("infer_shape incomplete; unknown args: %s"
                             % missing)
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        """Default-everything-float32 type inference (the reference's
        InferType pass); explicit dtypes propagate forward."""
        arg_names = self.list_arguments()
        known = {}
        if args:
            for n, t in zip(arg_names, args):
                if t is not None:
                    known[n] = np.dtype(t)
        known.update({k: np.dtype(v) for k, v in kwargs.items()
                      if v is not None})
        arg_types = [known.get(n, np.float32) for n in arg_names]
        out_types = [np.float32] * len(self._outputs)
        aux_types = [np.float32] * len(self.list_auxiliary_states())
        return arg_types, out_types, aux_types

    # ---- serialization --------------------------------------------------
    def tojson(self) -> str:
        """Reference-compatible graph JSON (nodes/arg_nodes/heads —
        the format Symbol.save writes and legacy_json_util.cc upgrades)."""
        topo = self._topo()
        nid = {id(n): i for i, n in enumerate(topo)}
        nodes = []
        for n in topo:
            nodes.append({
                "op": "null" if n.is_var else n.op.name,
                "name": n.name,
                "attrs": {k: str(v) for k, v in n.attrs.items()},
                "inputs": [[nid[id(p)], i, 0] for p, i in n.inputs],
            })
        arg_nodes = [i for i, n in enumerate(topo) if n.is_var]
        heads = [[nid[id(n)], i, 0] for n, i in self._outputs]
        return json.dumps({"nodes": nodes, "arg_nodes": arg_nodes,
                           "node_row_ptr": list(range(len(topo) + 1)),
                           "heads": heads,
                           "attrs": {"mxnet_version": ["int", 10200]}},
                          indent=2)

    def save(self, fname: str):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # ---- binding --------------------------------------------------------
    def simple_bind(self, ctx: Optional[Context] = None, grad_req="write",
                    type_dict=None, stype_dict=None, group2ctx=None,
                    shared_arg_names=None, shared_exec=None,
                    shared_buffer=None, **kwargs):
        """Infer shapes from the given input shapes, allocate all arrays,
        return a bound Executor (ref: symbol.py:1552 → GraphExecutor::Init)."""
        from ..executor import Executor
        from .. import ndarray as nd
        ctx = ctx or current_context()
        arg_shapes, _, aux_shapes = self.infer_shape(**kwargs)
        arg_types, _, aux_types = self.infer_type(**(type_dict or {}))
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        args = {n: nd.zeros(s, ctx=ctx, dtype=t)
                for n, s, t in zip(arg_names, arg_shapes, arg_types)}
        auxs = {n: nd.zeros(s, ctx=ctx, dtype=t)
                for n, s, t in zip(aux_names, aux_shapes, aux_types)}
        req = _norm_grad_req(grad_req, arg_names)
        grads = {n: nd.zeros(s, ctx=ctx, dtype=t)
                 for n, s, t in zip(arg_names, arg_shapes, arg_types)
                 if req.get(n, "null") != "null"}
        return Executor(self, ctx, args, grads, req, auxs,
                        group2ctx=group2ctx)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        """Bind with user-provided arrays (ref: symbol.py:1288)."""
        from ..executor import Executor
        from .. import ndarray as nd
        ctx = ctx or current_context()
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(aux_names, aux_states))
        req = _norm_grad_req(grad_req, arg_names)
        args_grad = args_grad or {}
        aux_states = aux_states or {}
        return Executor(self, ctx, dict(args or {}), dict(args_grad), req,
                        dict(aux_states), group2ctx=group2ctx)

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx, args=kwargs, grad_req="null")
        return ex.forward()

    # gradient: reference Symbol.gradient is rarely used directly; the
    # Executor's backward covers training.  Provided for API parity.
    def simple_eval(self, ctx=None, **kwargs):
        return self.eval(ctx, **kwargs)


def _abstract_node(node: _Node, attrs, in_shapes):
    """Output shapes of one node via jax.eval_shape (FInferShape analog)."""
    import jax

    op = node.op
    if op.train_aware:
        attrs = AttrDict({**attrs, "__train__": False})
    avals = [jax.ShapeDtypeStruct(s, np.float32) for s in in_shapes]
    if op.needs_rng:
        avals = [jax.ShapeDtypeStruct((2,), np.uint32)] + avals
    out = jax.eval_shape(lambda *xs: op.fn(attrs, *xs), *avals)
    if not isinstance(out, (tuple, list)):
        out = (out,)
    return [tuple(o.shape) for o in out]


def _norm_grad_req(grad_req, arg_names):
    if isinstance(grad_req, str):
        return {n: grad_req for n in arg_names}
    if isinstance(grad_req, (list, tuple)):
        return dict(zip(arg_names, grad_req))
    out = {n: "null" for n in arg_names}
    out.update(grad_req or {})
    return out


# --------------------------------------------------------------------------
# symbol creation
# --------------------------------------------------------------------------
def _create(op_name: str, sym_inputs: Sequence[Symbol],
            kwargs: Dict[str, Any], name: Optional[str] = None,
            attr: Optional[Dict[str, str]] = None) -> Symbol:
    op = get_op(op_name)
    kwargs = {k: v for k, v in kwargs.items() if v is not None}
    name = name or kwargs.pop("name", None)
    kwargs.pop("name", None)
    # explicit names also route through the scope manager so a Prefix scope
    # (gluon name_scope) prepends its prefix (reference _ctypes/symbol.py)
    from ..name import current_scope as _cs
    name = _cs().get(name, op.name.lower())

    entries: List[Tuple[Optional[_Node], int]] = []
    for s in sym_inputs:
        if s is None:
            # interior gap from keyword placement: auto-create a variable
            # named after the (scope-resolved) node name + arg name
            argname = op.arg_names[len(entries)] if op.arg_names and \
                len(entries) < len(op.arg_names) else "arg%d" % len(entries)
            entries.append((_Node(None, "%s_%s" % (name, argname), {}, []), 0))
            continue
        if len(s._outputs) != 1:
            raise MXNetError("op inputs must be single-output symbols")
        entries.append(s._outputs[0])

    # auto-create missing parameter variables (reference behavior: calling
    # sym.Convolution(data=x, name='c1') creates c1_weight / c1_bias)
    if op.arg_names:
        needed = len(op.arg_names)
        if op.name in ("Convolution", "Deconvolution", "FullyConnected",
                       "AttentionConvolution") and \
                op.parse_attrs(dict(kwargs)).get("no_bias"):
            needed -= 1
        if op.name == "LeakyReLU" and \
                op.parse_attrs(dict(kwargs)).get("act_type",
                                                 "leaky") != "prelu":
            needed -= 1    # gamma exists only for the prelu variant
        while len(entries) < needed:
            argname = op.arg_names[len(entries)]
            v = _Node(None, "%s_%s" % (name, argname), {}, [])
            entries.append((v, 0))

    # AttrScope defaults (ctx_group, __lr_mult__, ...) apply to EVERY node
    # created in scope — including operator-overload nodes (a * b) that
    # don't route through the generated functions (reference: AttrScope
    # applied in symbol creation C API).  Precedence: op kwargs > explicit
    # attr dict > scope defaults.
    from ..attribute import current_attrs
    attrs = current_attrs()
    if attr:
        attrs.update(attr)
    attrs.update(kwargs)
    node = _Node(op, name, attrs, entries)
    nvis = node.num_visible()
    return Symbol([(node, i) for i in range(nvis)])


def Variable(name: str, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs) -> Symbol:
    # scope defaults apply to variables too (reference AttrScope behavior:
    # a var created in AttrScope(__lr_mult__=...) carries the attr)
    from ..attribute import current_attrs
    attrs = current_attrs()
    attrs.update(attr or {})
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if dtype is not None:
        attrs["__dtype__"] = np.dtype(dtype).name
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if init is not None:
        # store full init spec (class + kwargs) as the reference does
        # (symbol.py:2484-2486 stores init.dumps() JSON)
        attrs["__init__"] = init if isinstance(init, str) else init.dumps()
    attrs.update(kwargs)
    return Symbol([(_Node(None, name, attrs, []), 0)])


var = Variable


def Group(symbols: Sequence[Symbol]) -> Symbol:
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str: str) -> Symbol:
    """Load reference-format graph JSON (both 'attrs' and legacy 'param'
    keys accepted — the legacy_json_util.cc upgrade path)."""
    g = json.loads(json_str)
    nodes_js = g["nodes"]
    built: List[_Node] = []
    for nj in nodes_js:
        # 'attrs' (1.x), 'attr' (0.x-era), 'param' (pre-NNVM) — the
        # legacy_json_util.cc upgrade chain collapsed into one lookup
        attrs = dict(nj.get("attrs") or nj.get("attr")
                     or nj.get("param") or {})
        inputs = [(built[int(e[0])], int(e[1])) for e in nj.get("inputs", [])]
        if nj["op"] == "null":
            built.append(_Node(None, nj["name"], attrs, []))
        else:
            built.append(_Node(get_op(nj["op"]), nj["name"], attrs, inputs))
    heads = g.get("heads") or [[len(built) - 1, 0, 0]]
    return Symbol([(built[int(h[0])], int(h[1])) for h in heads])


# convenience creators mirroring mx.sym.zeros/ones
def zeros(shape, dtype="float32", name=None):
    return _create("_zeros", [], {"shape": shape, "dtype": dtype}, name)


def ones(shape, dtype="float32", name=None):
    return _create("_ones", [], {"shape": shape, "dtype": dtype}, name)


def arange(start, stop=None, step=1.0, name=None, dtype="float32"):
    return _create("_arange", [], {"start": start, "stop": stop,
                                   "step": step, "dtype": dtype}, name)
