"""Auto-generate ``sym.<op>`` construction functions from the op registry.

Reference analog: ``python/mxnet/symbol/register.py`` code-gen from C-API
introspection.  Symbol-valued arguments (positional or keyword) become graph
inputs; everything else becomes node attrs.
"""
from __future__ import annotations

from ..ops.registry import OPS
from .symbol import Symbol, _create


def _make_fn(op_name):
    op = OPS[op_name]

    def fn(*args, **kwargs):
        name = kwargs.pop("name", None)
        attr = kwargs.pop("attr", None)
        sym_inputs = []
        for a in args:
            if isinstance(a, Symbol):
                sym_inputs.append(a)
            elif isinstance(a, (list, tuple)) and a and \
                    isinstance(a[0], Symbol):
                sym_inputs.extend(a)
            else:
                # positional scalar params fill declared params in order
                for k in op.params:
                    if k not in kwargs and not k.startswith("__"):
                        kwargs[k] = a
                        break
        # keyword symbol inputs are placed by declared arg name
        kw_syms = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
        for k in kw_syms:
            kwargs.pop(k)
        if kw_syms:
            if op.arg_names:
                slots = {n: i for i, n in enumerate(op.arg_names)}
                total = max((slots.get(k, -1) for k in kw_syms), default=-1)
                ins = list(sym_inputs) + [None] * (
                    max(0, total + 1 - len(sym_inputs)))
                for k, v in kw_syms.items():
                    i = slots.get(k)
                    if i is None:
                        ins.append(v)
                    elif ins[i] is not None:
                        raise TypeError(
                            "op %s: input %r given both positionally and "
                            "by keyword" % (op_name, k))
                    else:
                        ins[i] = v
                # interior None gaps become auto-created variables inside
                # _create (named after the scope-resolved node name)
                sym_inputs = ins
            else:
                sym_inputs.extend(kw_syms.values())
        # attr precedence handled inside _create: op kwargs > explicit
        # attr dict > AttrScope defaults
        return _create(op_name, sym_inputs, kwargs, name, attr=attr)

    fn.__name__ = op_name
    fn.__qualname__ = op_name
    fn.__doc__ = op.doc
    return fn


def populate(module_dict):
    for name in list(OPS):
        if name not in module_dict:
            module_dict[name] = _make_fn(name)
