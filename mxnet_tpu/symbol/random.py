"""sym.random namespace (parity: python/mxnet/symbol/random.py)."""
from __future__ import annotations

from .symbol import _create


def uniform(low=0.0, high=1.0, shape=(), dtype=None, name=None, **kw):
    return _create("_random_uniform", [],
                   {"low": low, "high": high, "shape": shape, "dtype": dtype},
                   name)


def normal(loc=0.0, scale=1.0, shape=(), dtype=None, name=None, **kw):
    return _create("_random_normal", [],
                   {"loc": loc, "scale": scale, "shape": shape, "dtype": dtype},
                   name)


def gamma(alpha=1.0, beta=1.0, shape=(), dtype=None, name=None, **kw):
    return _create("_random_gamma", [],
                   {"alpha": alpha, "beta": beta, "shape": shape,
                    "dtype": dtype}, name)
