"""Symbol package (parity: python/mxnet/symbol/)."""
from .symbol import (Symbol, Variable, var, Group, load, load_json,
                     zeros, ones, arange)
from . import register as _register

_register.populate(globals())

from . import random  # noqa: F401
from . import contrib  # noqa: F401
from . import image  # noqa: F401
