"""``sym.contrib`` namespace: experimental/contrib operators (symbolic).

Parity target: ``python/mxnet/symbol/contrib.py``.
"""
from __future__ import annotations

from ..ops.registry import OPS
from . import register as _register

_PREFIX = "_contrib_"


def populate(module_dict):
    for name in list(OPS):
        if name.startswith(_PREFIX):
            short = name[len(_PREFIX):]
            if short not in module_dict:
                fn = _register._make_fn(name)
                fn.__name__ = short
                fn.__qualname__ = short
                module_dict[short] = fn


populate(globals())


def foreach(body, data, init_states, name="foreach"):
    """Symbolic scan (parity: python/mxnet/symbol/contrib.py:157): builds a
    ``_foreach`` node whose body subgraph lowers to ``lax.scan``.

    ``body(data_sym, states) -> (outs, new_states)``; free variables of the
    body (weights etc.) are detected from the subgraph and wired as extra
    loop-invariant inputs.
    """
    from .symbol import Symbol, Group, var, _create
    single_data = not isinstance(data, (list, tuple))
    single_state = not isinstance(init_states, (list, tuple))
    data_list = [data] if single_data else list(data)
    state_list = [init_states] if single_state else list(init_states)

    data_vars = [var("%s_data%d" % (name, i)) for i in range(len(data_list))]
    state_vars = [var("%s_state%d" % (name, i))
                  for i in range(len(state_list))]
    outs, new_states = body(data_vars[0] if single_data else data_vars,
                            state_vars[0] if single_state else state_vars)
    single_out = not isinstance(outs, (list, tuple))
    out_list = [outs] if single_out else list(outs)
    ns_list = [new_states] if not isinstance(new_states, (list, tuple)) \
        else list(new_states)
    if len(ns_list) != len(state_list):
        raise ValueError("foreach: body must return as many states as "
                         "init_states")
    sub = Group(out_list + ns_list)

    data_names = tuple(s.name for s in data_vars)
    state_names = tuple(s.name for s in state_vars)
    placeholders = set(data_names) | set(state_names)
    free_nodes = [n for n in sub._topo()
                  if n.is_var and n.name not in placeholders]
    free_names = tuple(n.name for n in free_nodes)
    free_syms = [Symbol([(n, 0)]) for n in free_nodes]

    node = _create("_foreach", data_list + state_list + free_syms,
                   {"num_data": len(data_list),
                    "num_states": len(state_list),
                    "num_out_data": len(out_list),
                    "num_outputs": len(out_list) + len(ns_list),
                    "data_names": list(data_names),
                    "state_names": list(state_names),
                    "free_names": list(free_names),
                    "subgraph": sub.tojson()}, name=name)
    outputs = [node[i] for i in range(len(out_list))]
    states_out = [node[len(out_list) + i] for i in range(len(ns_list))]
    return ((outputs[0] if single_out else outputs),
            (states_out[0] if single_state else states_out))
