"""``sym.contrib`` namespace: experimental/contrib operators (symbolic).

Parity target: ``python/mxnet/symbol/contrib.py``.
"""
from __future__ import annotations

from ..ops.registry import OPS
from . import register as _register

_PREFIX = "_contrib_"


def populate(module_dict):
    for name in list(OPS):
        if name.startswith(_PREFIX):
            short = name[len(_PREFIX):]
            if short not in module_dict:
                fn = _register._make_fn(name)
                fn.__name__ = short
                fn.__qualname__ = short
                module_dict[short] = fn


populate(globals())
