"""ModelRegistry: N named models behind one serving gateway.

Each registered model is a full :class:`~mxnet_tpu.serving.server.
ModelServer` — its own bucket ladder, warmup, SLO scheduler, admission
control, and atomic hot-swap — so models are isolated: swapping or
unregistering model A never pauses model B's batches, and one model's
saturation sheds *its* low-class traffic without touching its neighbors.
Per-model cost attribution comes for free from the program-name
namespace (``serving:<model>:b<bucket>:forward`` on ``/programz``) and
the ``serving_model_requests_total{model,outcome}`` counter.

Registration order of operations matters: the server is built **and
warmed** before it becomes routable, so a request can never reach a
model whose bucket ladder is still compiling (the same
no-compile-under-traffic contract warmup gives a single server).

The registry lock only guards the name → server map (dict ops); warmup,
drain, and thread joins all happen outside it (graftlint GL003).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .batcher import Request, ServingError
from .server import ModelServer, ServingConfig

__all__ = ["UnknownModelError", "ModelRegistry"]


class UnknownModelError(ServingError):
    """Request named a model this registry does not host (HTTP 404)."""


class ModelRegistry:
    """Name → :class:`ModelServer` map with routed submit/predict.

    ``register`` builds + warms a server, then publishes it; ``submit`` /
    ``predict`` route by model name (optional while exactly one model is
    registered).  ``stats()`` / ``health()`` aggregate across models —
    the registry is degraded iff any model is.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._models: Dict[str, ModelServer] = {}

    # -- membership --------------------------------------------------------
    def register(self, name, symbol_json, params, example_shapes,
                 ctx=None, mesh=None, sharding_rules=None,
                 config: Optional[ServingConfig] = None, start: bool = True,
                 **config_kwargs) -> ModelServer:
        """Build, warm, and publish a model.  All compilation happens
        before the name becomes routable."""
        name = str(name)
        with self._lock:
            if name in self._models:
                raise ServingError("model %r already registered" % name)
        srv = ModelServer(symbol_json, params, example_shapes, ctx=ctx,
                          config=config, name=name, mesh=mesh,
                          sharding_rules=sharding_rules, **config_kwargs)
        if start:
            srv.start()          # warmup: compiles the ladder pre-publish
        published = False
        with self._lock:
            if name not in self._models:
                self._models[name] = srv
                published = True
        if not published:
            srv.stop(drain=False)
            raise ServingError("model %r already registered" % name)
        from .. import runlog as _runlog
        _runlog.event("model_registered", model=name,
                      buckets=list(srv.config.batch_buckets),
                      mesh=srv._mesh_axes(), started=bool(start))
        return srv

    def unregister(self, name, drain: bool = True):
        """Remove a model and stop its server (drain by default: queued
        requests finish; the name stops routing immediately)."""
        with self._lock:
            srv = self._models.pop(str(name), None)
        if srv is None:
            raise UnknownModelError("unknown model %r" % (name,))
        srv.stop(drain=drain)
        from .. import runlog as _runlog
        _runlog.event("model_unregistered", model=str(name),
                      drained=bool(drain))
        return srv

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def get(self, name=None) -> ModelServer:
        """Resolve a model name; ``None`` routes to the single registered
        model (explicit names required once there are several)."""
        with self._lock:
            if name is None:
                if len(self._models) == 1:
                    return next(iter(self._models.values()))
                raise UnknownModelError(
                    "model name required (%d models registered)"
                    % len(self._models))
            srv = self._models.get(str(name))
        if srv is None:
            raise UnknownModelError(
                "unknown model %r (have %s)" % (name, self.models()))
        return srv

    def __contains__(self, name):
        with self._lock:
            return str(name) in self._models

    def __len__(self):
        with self._lock:
            return len(self._models)

    # -- routed request path -----------------------------------------------
    def submit(self, inputs, model=None, deadline_ms=None,
               slo_class: str = "standard") -> Request:
        return self.get(model).submit(inputs, deadline_ms=deadline_ms,
                                      slo_class=slo_class)

    def predict(self, inputs, model=None, deadline_ms=None,
                slo_class: str = "standard", timeout: float = 30.0):
        return self.get(model).predict(inputs, deadline_ms=deadline_ms,
                                       slo_class=slo_class, timeout=timeout)

    def swap_params(self, name, params, aux_params=None):
        """Atomic hot-swap of one model's weights; other models keep
        serving uninterrupted (per-model swap locks)."""
        self.get(name).swap_params(params, aux_params)

    # -- lifecycle / introspection ------------------------------------------
    def stop_all(self, drain: bool = True):
        with self._lock:
            models = list(self._models.values())
            self._models.clear()
        for srv in models:
            srv.stop(drain=drain)

    def stats(self) -> Dict[str, object]:
        with self._lock:
            models = dict(self._models)
        return {"models": {n: s.stats() for n, s in models.items()}}

    def health(self) -> Dict[str, object]:
        """Aggregate verdict: degraded iff any model is, with causes
        namespaced ``<model>:<cause>``."""
        with self._lock:
            models = dict(self._models)
        per = {n: s.health() for n, s in models.items()}
        causes = sorted("%s:%s" % (n, c)
                        for n, doc in per.items() for c in doc["causes"])
        return {
            "status": "degraded" if causes else "serving",
            "causes": causes,
            "models": per,
        }
