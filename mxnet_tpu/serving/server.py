"""ModelServer: dynamic-batching inference over a forward-only program.

The server owns one :class:`~mxnet_tpu.predictor.Predictor` **per declared
batch bucket**, all sharing the same symbol and parameter objects (cheap
``Predictor.reshape``).  Each bucket predictor is bound to one fixed input
shape, so each is exactly one XLA program; ``warmup()`` runs every bucket
once at startup so all compilation happens before traffic (AOT — a cold
bucket compiling under load would blow every deadline in the batch).

Request path: ``submit`` validates + admits into the
:class:`~mxnet_tpu.serving.batcher.DynamicBatcher` (bounded queue —
explicit :class:`QueueFullError` on overload); a worker thread forms a
batch, drops expired-deadline requests *before* execution, concatenates
the survivors' rows, zero-pads to the bucket size, runs the bucket's
predictor under the swap lock, and slices each request's rows back out.
``swap_params`` takes the same lock, so every batch executes against
exactly one weight set — hot-swap is atomic at batch granularity.

Telemetry (gated by ``telemetry.enabled``, same discipline as the rest of
the runtime): ``serving_requests_total{outcome}``, ``serving_queue_depth``,
queue-wait / execute / end-to-end latency histograms,
``serving_batch_rows`` (realized batch size) and
``serving_padding_rows_total`` (bucket padding waste).  Tracing (gated by
``tracing.enabled``): a ``Serving::Submit`` span per request whose flow
event lands on the ``Serving::ExecuteBatch`` span that served it.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..base import get_env
from .. import telemetry as _telemetry
from .. import tracing as _tracing
from .. import program_cache as _program_cache
from .batcher import (DeadlineExceededError, DynamicBatcher, QueueFullError,
                      Request, ServerClosedError, ServingError, pow2_buckets)
from .scheduler import SLO_CLASSES, AdmissionError, SloScheduler

__all__ = ["ServingConfig", "ModelServer"]

_REQS = _telemetry.counter(
    "serving_requests_total",
    "Serving requests by final outcome (ok|rejected|deadline|error)",
    ("outcome",))
_QUEUE_DEPTH = _telemetry.gauge(
    "serving_queue_depth", "Requests waiting in the serving queue")
_QUEUE_WAIT = _telemetry.histogram(
    "serving_queue_wait_seconds", "Request wait from admit to dequeue")
_EXEC_TIME = _telemetry.histogram(
    "serving_execute_seconds", "Batch execution wall time (pad+forward)")
_E2E_TIME = _telemetry.histogram(
    "serving_request_seconds", "Request wall time from submit to completion")
_BATCH_ROWS = _telemetry.histogram(
    "serving_batch_rows", "Realized rows per executed batch (pre-padding)",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
_PAD_ROWS = _telemetry.counter(
    "serving_padding_rows_total",
    "Zero rows executed to pad batches up to their bucket")
_SWAPS = _telemetry.counter(
    "serving_hot_swaps_total", "Atomic weight hot-swaps applied")
_WARMUP_TIME = _telemetry.gauge(
    "serving_warmup_seconds",
    "Wall time of the last warmup(): bucket-ladder trace+compile (cold) "
    "or program-cache restore (warm deploy)")
_SHED = _telemetry.counter(
    "serving_shed_total",
    "Requests shed by SLO admission control (429), by class",
    ("slo_class",))
_ADMISSION_LEVEL = _telemetry.gauge(
    "serving_admission_level",
    "Current shed level: 0 admit all, 1 shed batch, 2 shed standard too")
_SLO_REQS = _telemetry.counter(
    "serving_slo_requests_total",
    "Serving requests by SLO class and final outcome",
    ("slo_class", "outcome"))
_MODEL_REQS = _telemetry.counter(
    "serving_model_requests_total",
    "Serving requests by model and final outcome",
    ("model", "outcome"))


class ServingConfig:
    """Server knobs; constructor arguments override ``MXNET_SERVING_*``
    environment defaults (see docs/serving.md)."""

    def __init__(self, max_batch_size=None, batch_buckets=None,
                 batch_timeout_ms=None, queue_depth=None,
                 default_deadline_ms=None, num_workers=None,
                 shed_batch_at=None, shed_standard_at=None,
                 retry_after_ms=None):
        if max_batch_size is None:
            max_batch_size = get_env("MXNET_SERVING_MAX_BATCH", 8, int)
        if batch_timeout_ms is None:
            batch_timeout_ms = get_env(
                "MXNET_SERVING_BATCH_TIMEOUT_MS", 2.0, float)
        if queue_depth is None:
            queue_depth = get_env("MXNET_SERVING_QUEUE_DEPTH", 256, int)
        if default_deadline_ms is None:
            default_deadline_ms = get_env(
                "MXNET_SERVING_DEADLINE_MS", 0.0, float)
        if num_workers is None:
            num_workers = get_env("MXNET_SERVING_WORKERS", 1, int)
        if batch_buckets is None:
            env_buckets = get_env("MXNET_SERVING_BUCKETS", None)
            if env_buckets:
                batch_buckets = tuple(
                    int(b) for b in env_buckets.split(",") if b.strip())
            else:
                batch_buckets = pow2_buckets(int(max_batch_size))
        if shed_batch_at is None:
            shed_batch_at = get_env("MXNET_SERVING_SHED_BATCH_AT", 0.5,
                                    float)
        if shed_standard_at is None:
            shed_standard_at = get_env("MXNET_SERVING_SHED_STANDARD_AT",
                                       0.8, float)
        if retry_after_ms is None:
            retry_after_ms = get_env("MXNET_SERVING_RETRY_AFTER_MS", 50.0,
                                     float)
        self.max_batch_size = int(max_batch_size)
        self.batch_buckets = tuple(sorted(set(int(b) for b in batch_buckets)))
        self.batch_timeout_ms = float(batch_timeout_ms)
        self.queue_depth = int(queue_depth)
        self.default_deadline_ms = float(default_deadline_ms)
        self.num_workers = max(1, int(num_workers))
        self.shed_batch_at = float(shed_batch_at)
        self.shed_standard_at = float(shed_standard_at)
        self.retry_after_ms = float(retry_after_ms)


class ModelServer:
    """Dynamic-batching model server over a forward-only Predictor.

    Parameters
    ----------
    symbol_json, params, ctx
        Forwarded to :class:`~mxnet_tpu.predictor.Predictor`.
    example_shapes : dict of name -> per-example shape (NO batch dim)
        e.g. ``{"data": (3, 224, 224)}``; the server prepends the bucket
        batch dimension itself.
    config : ServingConfig, optional
        Extra keyword arguments build one (``max_batch_size=...`` etc.).
    name : str
        Model name — namespaces this server's /programz program entries
        (``serving:<name>:b<bucket>:forward``) and its per-model metrics;
        the key it registers under in a :class:`ModelRegistry`.
    mesh, sharding_rules
        Forwarded to :class:`~mxnet_tpu.predictor.Predictor`: shard this
        model's parameters across the mesh (GSPMD tensor parallel); one
        program per (model, bucket, mesh) — the mesh signature joins the
        forward cache key.
    """

    def __init__(self, symbol_json, params, example_shapes,
                 ctx=None, config: Optional[ServingConfig] = None,
                 name: str = "default", mesh=None, sharding_rules=None,
                 **config_kwargs):
        from ..predictor import Predictor

        if config is None:
            config = ServingConfig(**config_kwargs)
        elif config_kwargs:
            raise ServingError("pass either config= or config kwargs, "
                               "not both")
        self.config = config
        self.name = str(name)
        self._mesh = mesh
        self._example_shapes = {k: tuple(int(d) for d in s)
                                for k, s in dict(example_shapes).items()}
        if not self._example_shapes:
            raise ServingError("example_shapes must name at least one input")
        self._batcher = SloScheduler(
            config.batch_buckets, config.max_batch_size,
            config.batch_timeout_ms, config.queue_depth,
            shed_batch_at=config.shed_batch_at,
            shed_standard_at=config.shed_standard_at,
            retry_after_ms=config.retry_after_ms)
        self._batcher.on_level_change = self._on_admission_level
        self._admission_checked_at = 0.0

        # one predictor per bucket, sharing symbol/params via reshape
        buckets = self._batcher.buckets
        base = Predictor(symbol_json, params, ctx=ctx, input_shapes={
            k: (buckets[-1],) + s for k, s in self._example_shapes.items()},
            mesh=mesh, sharding_rules=sharding_rules)
        self._predictors = {buckets[-1]: base}
        for b in buckets[:-1]:
            self._predictors[b] = base.reshape(
                {k: (b,) + s for k, s in self._example_shapes.items()})
        for b, pred in self._predictors.items():
            # distinct health/atlas program names per (model, bucket):
            # N models on one process attribute cost side by side on
            # /programz instead of overwriting one "forward" entry
            pred._executor._program_prefix = "serving:%s:b%d:" \
                % (self.name, b)

        self._swap_lock = threading.Lock()
        self._workers: List[threading.Thread] = []
        self._started = False
        self._stopped = False
        self._warmed = False
        # health inputs: recent request outcomes (deque append/iteration
        # are thread-safe) + the per-predictor compile-count snapshot
        # taken at the end of warmup
        self._recent_outcomes: collections.deque = collections.deque(
            maxlen=256)
        self._warm_compile_counts: Optional[int] = None
        self.warmup_seconds: Optional[float] = None

    # -- lifecycle ---------------------------------------------------------
    def start(self, warmup: bool = True):
        """Spawn the worker thread(s); ``warmup`` AOT-compiles every
        declared bucket first so no request ever waits on XLA."""
        if self._stopped:
            raise ServerClosedError("server already stopped")
        if self._started:
            return self
        if warmup:
            self.warmup()
        for i in range(self.config.num_workers):
            t = threading.Thread(target=self._worker_loop,
                                 name="mxtpu-serving-worker-%d" % i,
                                 daemon=True)
            t.start()
            self._workers.append(t)
        self._started = True
        return self

    def warmup(self):
        """Run every bucket once on zeros: all tracing + XLA compilation
        happens here, bounded by the declared bucket set.  With the
        persistent program cache enabled (MXNET_PROGRAM_CACHE_DIR) and
        prefilled (tools/cache_prefill.py), "compilation" is a disk
        restore and ``warmup_seconds`` collapses from minutes to ms."""
        if self._warmed:
            return
        _program_cache.ensure_enabled()
        t0 = time.perf_counter()
        with self._swap_lock:
            for b, pred in sorted(self._predictors.items()):
                feed = {k: np.zeros((b,) + s, np.float32)
                        for k, s in self._example_shapes.items()}
                pred.forward(**feed)
        self.warmup_seconds = time.perf_counter() - t0
        if _telemetry.enabled:
            _WARMUP_TIME.set(self.warmup_seconds)
        from .. import runlog as _runlog
        _runlog.event("serving_warmup",
                      model=self.name,
                      seconds=round(self.warmup_seconds, 6),
                      buckets=list(self._batcher.buckets),
                      mesh=self._mesh_axes(),
                      program_cache=_program_cache.stats())
        # per-server baseline, not the global op_jit_cache counters (other
        # executors in the process would pollute a global delta): anything
        # beyond this after warmup is a silent recompile
        self._warm_compile_counts = self._compile_count()
        self._warmed = True
        self._tag_memory()

    def stop(self, drain: bool = True, timeout: Optional[float] = None):
        """Shut down.  ``drain=True`` (graceful): stop admitting, execute
        everything already queued, then join the workers.  ``drain=False``:
        fail queued requests with :class:`ServerClosedError` immediately."""
        if self._stopped:
            return
        self._stopped = True
        self._batcher.close()
        if not drain:
            self._batcher.drop_all(
                lambda: ServerClosedError("server shut down before "
                                          "this request executed"))
            if _telemetry.enabled:
                _QUEUE_DEPTH.set(0)
        for t in self._workers:
            t.join(timeout)
        self._workers = []

    # -- request admission -------------------------------------------------
    def _validate(self, inputs):
        """Normalize to {name: (rows, *example)} float arrays; returns
        (feed, rows)."""
        feed, rows = {}, None
        if set(inputs) != set(self._example_shapes):
            raise ServingError(
                "inputs %s do not match declared %s"
                % (sorted(inputs), sorted(self._example_shapes)))
        for name, value in inputs.items():
            eshape = self._example_shapes[name]
            arr = value.asnumpy() if hasattr(value, "asnumpy") \
                else np.asarray(value)
            if arr.shape == eshape:            # single example: add row dim
                arr = arr[None]
            elif arr.shape[1:] != eshape:
                raise ServingError(
                    "input %r has shape %s; want (rows,)+%s or %s"
                    % (name, arr.shape, eshape, eshape))
            if rows is None:
                rows = arr.shape[0]
            elif arr.shape[0] != rows:
                raise ServingError(
                    "inputs disagree on rows: %d vs %d for %r"
                    % (rows, arr.shape[0], name))
            feed[name] = arr
        if rows < 1:
            raise ServingError("request carries zero rows")
        return feed, rows

    def _update_admission(self):
        """Rate-limited re-evaluation of the health verdict into the
        scheduler's shed floor: a degraded server (post-warmup compiles,
        deadline misses, saturation) sheds ``batch`` traffic even before
        occupancy alone would."""
        now = time.monotonic()
        if now - self._admission_checked_at < 0.2:
            return
        self._admission_checked_at = now
        causes = [c for c in self.health()["causes"] if c != "stopped"]
        self._batcher.set_shed_floor(1 if causes else 0)

    def _on_admission_level(self, level, prev, occupancy):
        """Scheduler shed-level transition observer (called outside the
        scheduler lock): gauge + a durable admission_state ledger event,
        edge-triggered like the healthz flips it sits next to in
        ``runlog merge`` timelines."""
        if _telemetry.enabled:
            _ADMISSION_LEVEL.set(level)
        from .. import runlog as _runlog
        _runlog.event("admission_state", model=self.name,
                      level=int(level), prev_level=int(prev),
                      occupancy=round(float(occupancy), 4),
                      shedding=list(SLO_CLASSES[3 - level:]) if level else [])

    def submit(self, inputs, deadline_ms: Optional[float] = None,
               slo_class: str = "standard") -> Request:
        """Admit one request; returns a :class:`Request` future.

        Raises :class:`QueueFullError` when the bounded queue is full,
        :class:`AdmissionError` when admission control is shedding
        ``slo_class`` (HTTP 429; carries ``retry_after_s``),
        :class:`ServerClosedError` after shutdown, :class:`ServingError`
        on malformed inputs.  ``deadline_ms`` (or the configured
        ``MXNET_SERVING_DEADLINE_MS`` default) bounds end-to-end latency:
        requests still queued past the deadline are dropped unexecuted
        (and order execution within a class — EDF).
        """
        feed, rows = self._validate(inputs)
        if slo_class not in SLO_CLASSES:
            raise ServingError("unknown slo_class %r (one of %s)"
                               % (slo_class, list(SLO_CLASSES)))
        if deadline_ms is None and self.config.default_deadline_ms > 0:
            deadline_ms = self.config.default_deadline_ms
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        req = Request(feed, rows, deadline, slo_class=slo_class)
        if _tracing.enabled:
            with _tracing.span("Serving::Submit", "serving",
                               args={"rows": rows,
                                     "slo_class": slo_class}) as sp:
                req.flow_id = sp.span_id
                sp.flow_out("serving_flow")
        self._update_admission()
        try:
            self._batcher.put(req)
        except AdmissionError as e:
            req._fail(e, "shed")
            if _telemetry.enabled:
                _REQS.labels(outcome="shed").inc()
                _SHED.labels(slo_class=slo_class).inc()
                self._count_slo(req, "shed")
            raise
        except (QueueFullError, ServerClosedError) as e:
            req._fail(e, "rejected")
            if _telemetry.enabled:
                _REQS.labels(outcome="rejected").inc()
                self._count_slo(req, "rejected")
            raise
        if _telemetry.enabled:
            _QUEUE_DEPTH.set(len(self._batcher))
        return req

    def predict(self, inputs, deadline_ms=None, timeout=30.0,
                slo_class: str = "standard"):
        """Synchronous convenience: submit + wait; returns the list of
        per-output arrays, each ``(rows, *out_shape)``."""
        return self.submit(inputs, deadline_ms=deadline_ms,
                           slo_class=slo_class).result(timeout)

    # -- hot swap ----------------------------------------------------------
    def swap_params(self, params, aux_params=None):
        """Atomically replace the served weights between batches.

        ``params`` is a {name: array} dict (``arg:``/``aux:`` prefixes
        accepted, checkpoint convention).  Shapes must match the bound
        graph — a swap never re-binds or recompiles.  The swap lock
        excludes batch execution, so every request's batch runs against
        exactly one weight set (old or new, never a mix).
        """
        args, auxs = {}, dict(aux_params or {})
        for k, v in dict(params).items():
            if k.startswith("arg:"):
                args[k[4:]] = v
            elif k.startswith("aux:"):
                auxs[k[4:]] = v
            else:
                args[k] = v
        with self._swap_lock:
            for pred in self._predictors.values():
                # predictor-level copy: re-pins mesh shardings so a swap
                # on a mesh model can't shift a layout and force a
                # post-warmup recompile
                pred.copy_params_from(args, auxs or None,
                                      allow_extra_params=True)
        self._tag_memory()
        if _telemetry.enabled:
            _SWAPS.inc()
        from .. import runlog as _runlog
        _runlog.event("model_hot_swap", model=self.name,
                      params=len(args), aux=len(auxs))

    # -- execution ---------------------------------------------------------
    def _worker_loop(self):
        while True:
            reqs = self._batcher.get_batch()
            if reqs is None:
                return
            if _telemetry.enabled:
                _QUEUE_DEPTH.set(len(self._batcher))
            now = time.monotonic()
            live = []
            for r in reqs:
                if r.expired(now):
                    self._finish(r, DeadlineExceededError(
                        "deadline expired %.1fms before execution"
                        % ((now - r.deadline) * 1e3)), "deadline")
                else:
                    live.append(r)
            if not live:
                continue
            try:
                self._execute(live)
            except Exception as e:  # noqa: BLE001 - a batch failure must
                # fail its requests, never kill the worker loop
                err = e if isinstance(e, ServingError) else ServingError(
                    "batch execution failed: %s: %s" % (type(e).__name__, e))
                for r in live:
                    self._finish(r, err, "error")

    def _execute(self, live):
        rows = sum(r.rows for r in live)
        bucket = self._batcher.bucket_for(rows)
        t0 = time.monotonic()
        if _telemetry.enabled:
            for r in live:
                _QUEUE_WAIT.observe(r.dequeue_t - r.submit_t)
            _BATCH_ROWS.observe(rows)
            _PAD_ROWS.inc(bucket - rows)
        feed = {}
        for name, eshape in self._example_shapes.items():
            mats = [r.inputs[name] for r in live]
            arr = mats[0] if len(mats) == 1 else np.concatenate(mats, axis=0)
            if rows < bucket:
                arr = np.concatenate(
                    [arr, np.zeros((bucket - rows,) + eshape, arr.dtype)],
                    axis=0)
            feed[name] = arr
        if _tracing.enabled:
            with _tracing.span("Serving::ExecuteBatch", "serving",
                               args={"bucket": bucket, "rows": rows,
                                     "requests": len(live)}):
                for r in live:
                    if r.flow_id:
                        _tracing._emit_flow("f", r.flow_id, "serving_flow",
                                            "serving", bind_enclosing=True)
                outs = self._forward(bucket, feed)
        else:
            outs = self._forward(bucket, feed)
        if _telemetry.enabled:
            _EXEC_TIME.observe(time.monotonic() - t0)
        offset = 0
        for r in live:
            r._outputs = [o[offset:offset + r.rows] for o in outs]
            offset += r.rows
            self._finish(r, None, "ok")

    def _forward(self, bucket, feed):
        """One padded-bucket forward under the swap lock; returns host
        arrays (sliced per request by the caller)."""
        pred = self._predictors[bucket]
        try:
            with self._swap_lock:
                outs = pred.forward(**feed)
            # the host transfer blocks on device completion — an async
            # dispatch OOM surfaces here, inside the forensics catch
            return [o.asnumpy() for o in outs]
        except Exception as e:
            from .. import memwatch as _memwatch
            if _memwatch.enabled and _memwatch.is_oom(e):
                _memwatch.on_oom(
                    e, site="serving",
                    program="serving:%s:b%d:forward" % (self.name, bucket))
            raise

    def _count_slo(self, req, outcome):
        _SLO_REQS.labels(slo_class=getattr(req, "slo_class", "standard"),
                         outcome=outcome).inc()
        _MODEL_REQS.labels(model=self.name, outcome=outcome).inc()

    def _finish(self, req, error, outcome):
        self._recent_outcomes.append(outcome)
        if _telemetry.enabled:
            _REQS.labels(outcome=outcome).inc()
            self._count_slo(req, outcome)
            _E2E_TIME.observe(time.monotonic() - req.submit_t)
        if error is None:
            req.outcome = "ok"
            req._event.set()
        else:
            req._fail(error, outcome)

    # -- introspection -----------------------------------------------------
    def _compile_count(self) -> int:
        """Per-input-shape forward programs across this server's
        predictors (the executor records one ("fwdsig", ...) key per
        compiled shape signature when telemetry is on)."""
        total = 0
        for pred in set(self._predictors.values()):
            ex = getattr(pred, "_executor", None)
            if ex is not None:
                total += sum(1 for k in ex._jitted
                             if isinstance(k, tuple) and k
                             and k[0] == "fwdsig")
        return total

    def health(self) -> Dict[str, object]:
        """Health verdict for /healthz: degraded on queue saturation,
        post-warmup compiles, or a high deadline-miss rate."""
        causes = []
        qcap = self.config.queue_depth
        saturation = (len(self._batcher) / float(qcap)) if qcap else 0.0
        if saturation >= 0.9:
            causes.append("queue_saturated")
        compiles = None
        if self._warmed and self._warm_compile_counts is not None:
            compiles = self._compile_count() - self._warm_compile_counts
            if compiles > 0:
                causes.append("post_warmup_compiles")
        recent = list(self._recent_outcomes)
        misses = sum(1 for o in recent if o == "deadline")
        miss_rate = (misses / float(len(recent))) if recent else 0.0
        if len(recent) >= 20 and miss_rate > 0.5:
            causes.append("deadline_misses")
        if self._stopped:
            causes.append("stopped")
        status = "degraded" if causes else "serving"
        prev = getattr(self, "_last_health_status", None)
        if status != prev:
            # durable trail of every serving/degraded flip; edge-triggered
            # so a /healthz poll loop doesn't flood the ledger
            self._last_health_status = status
            try:
                from .. import runlog as _runlog
                _runlog.event("healthz", status=status, prev_status=prev,
                              causes=causes,
                              queue_saturation=round(saturation, 4),
                              post_warmup_compiles=compiles,
                              deadline_miss_rate=round(miss_rate, 4))
            except Exception:
                pass
        return {
            "status": status,
            "causes": causes,
            "queue_saturation": saturation,
            "post_warmup_compiles": compiles,
            "deadline_miss_rate": miss_rate,
            "recent_requests": len(recent),
            **self.stats(),
        }

    def _mesh_axes(self):
        if self._mesh is None:
            return None
        return {str(a): int(s) for a, s in self._mesh.shape.items()}

    def program_names(self) -> List[str]:
        """This model's registered /programz entries
        (``serving:<name>:b<bucket>:forward``) — per-model cost
        attribution when N models share one process."""
        from .. import health as _health
        prefix = "serving:%s:" % self.name
        return sorted(n for n in _health.programs() if n.startswith(prefix))

    def _tag_memory(self):
        """Ledger the currently bound weight generation of every bucket as
        serving-owned (detail = model name) — warmup and each hot swap
        re-tag so ``owner_bytes("serving", detail=name)`` tracks the live
        generation only."""
        from .. import memwatch as _memwatch
        if not _memwatch.enabled:
            return
        for pred in set(self._predictors.values()):
            ex = getattr(pred, "_executor", None)
            if ex is not None:
                _memwatch.tag("serving", (ex.arg_dict, ex.aux_dict),
                              detail=self.name)

    def memory(self) -> Dict[str, object]:
        """Per-model ledger block for /stats and /statusz: live bytes of
        this model's bound weight generation (weakref walk — no global
        live-array census on the request path)."""
        from .. import memwatch as _memwatch
        return {
            "enabled": _memwatch.enabled,
            "serving_bytes": _memwatch.owner_bytes("serving",
                                                   detail=self.name),
        }

    def stats(self) -> Dict[str, object]:
        return {
            "model": self.name,
            "memory": self.memory(),
            "buckets": list(self._batcher.buckets),
            "max_batch_size": self.config.max_batch_size,
            "batch_timeout_ms": self.config.batch_timeout_ms,
            "queue_depth": len(self._batcher),
            "queue_capacity": self.config.queue_depth,
            "rows_queued": self._batcher.rows_queued,
            "queued_by_class": self._batcher.queued_by_class(),
            "admission_level": self._batcher.level,
            "mesh": self._mesh_axes(),
            "programs": self.program_names(),
            "workers": len(self._workers),
            "started": self._started,
            "stopped": self._stopped,
            "warmed": self._warmed,
        }
