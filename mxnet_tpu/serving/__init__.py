"""Inference serving: dynamic batching model server with backpressure,
deadlines, and hot-swap.

The training side compiles one whole-step XLA program; this package is
the inference mirror of that discipline.  A :class:`ModelServer` wraps a
forward-only :class:`~mxnet_tpu.predictor.Predictor` per declared batch
bucket (power-of-two padded batch sizes), coalesces concurrent requests
in a bounded queue (:mod:`~mxnet_tpu.serving.batcher`), pads each batch
to its bucket, and slices results back per request — so the steady-state
compiled-program count is ``len(batch_buckets)``, not one per observed
traffic shape.  Overload rejects at admission (backpressure), expired
deadlines drop before execution, weights hot-swap atomically between
batches, and a stdlib JSON endpoint (:mod:`~mxnet_tpu.serving.http`)
serves it over HTTP.  See docs/serving.md.

    from mxnet_tpu import serving
    srv = serving.ModelServer(sym.tojson(), params,
                              example_shapes={"data": (3, 224, 224)},
                              max_batch_size=8).start()
    out = srv.predict({"data": image})          # batched under the hood
    port = serving.start_http_server(srv, port=8080)
"""
from __future__ import annotations

from .batcher import (DeadlineExceededError, DynamicBatcher, QueueFullError,
                      Request, ServerClosedError, ServingError, pow2_buckets)
from .server import ModelServer, ServingConfig
from .http import start_http_server, stop_http_server

__all__ = ["ModelServer", "ServingConfig", "DynamicBatcher", "Request",
           "ServingError", "QueueFullError", "DeadlineExceededError",
           "ServerClosedError", "pow2_buckets", "start_http_server",
           "stop_http_server"]
