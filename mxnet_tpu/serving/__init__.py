"""Inference serving: a multi-model, SLO-aware gateway with dynamic
batching, backpressure, deadlines, and hot-swap.

The training side compiles one whole-step XLA program; this package is
the inference mirror of that discipline.  A :class:`ModelServer` wraps a
forward-only :class:`~mxnet_tpu.predictor.Predictor` per declared batch
bucket (power-of-two padded batch sizes), coalesces concurrent requests
in a bounded queue, pads each batch to its bucket, and slices results
back per request — so the steady-state compiled-program count is
``len(batch_buckets)``, not one per observed traffic shape.  Scheduling
is SLO-aware (:mod:`~mxnet_tpu.serving.scheduler`): requests carry a
class (``realtime`` > ``standard`` > ``batch``), batches form by class
priority with EDF inside a class, and admission control sheds the
lowest class first as the queue saturates or health degrades (HTTP 429
+ Retry-After).  A :class:`ModelRegistry`
(:mod:`~mxnet_tpu.serving.registry`) hosts N named models — independent
ladders, warmup, and hot-swap — and a mesh-sharded Predictor
(``mesh=``) spans one large model across local chips via GSPMD.
Weights hot-swap atomically between batches, and a stdlib JSON endpoint
(:mod:`~mxnet_tpu.serving.http`) serves it all over HTTP.  See
docs/serving.md.

    from mxnet_tpu import serving
    reg = serving.ModelRegistry()
    reg.register("m1", sym.tojson(), params,
                 example_shapes={"data": (3, 224, 224)}, max_batch_size=8)
    out = reg.predict({"data": image}, model="m1", slo_class="realtime",
                      deadline_ms=50)
    port = serving.start_http_server(reg, port=8080)
"""
from __future__ import annotations

from .batcher import (DeadlineExceededError, DynamicBatcher, QueueFullError,
                      Request, ServerClosedError, ServingError, pow2_buckets)
from .scheduler import SLO_CLASSES, AdmissionError, SloScheduler
from .server import ModelServer, ServingConfig
from .registry import ModelRegistry, UnknownModelError
from .http import start_http_server, stop_http_server

__all__ = ["ModelServer", "ModelRegistry", "ServingConfig",
           "DynamicBatcher", "SloScheduler", "Request", "SLO_CLASSES",
           "ServingError", "QueueFullError", "DeadlineExceededError",
           "ServerClosedError", "AdmissionError", "UnknownModelError",
           "pow2_buckets", "start_http_server", "stop_http_server"]
