"""Stdlib JSON inference endpoint over a :class:`ModelServer`.

Same pattern as ``telemetry/export.py``: ``http.server`` on daemon
threads, loopback bind by default (the wire is unauthenticated JSON —
exposing it wider is an explicit operator choice via
``MXNET_SERVING_HOST``).

Routes::

    POST /predict        {"inputs": {name: nested list}, "deadline_ms": n?}
                         -> 200 {"outputs": [...], "rows": n}
    GET  /healthz        -> 200 {"status": "serving", ...verdict} when
                         healthy; 503 {"status": "degraded",
                         "causes": [...]} on queue saturation, post-warmup
                         compiles, or a high deadline-miss rate
    GET  /stats          -> 200 server stats JSON

Overload maps to status codes a load balancer understands: 503 for
queue-full rejection and shutdown (retryable elsewhere), 504 for an
expired deadline, 400 for malformed requests.
"""
from __future__ import annotations

import json
import threading

from ..base import get_env
from .batcher import (DeadlineExceededError, QueueFullError,
                      ServerClosedError, ServingError)

__all__ = ["start_http_server", "stop_http_server"]

_server = None
_server_thread = None
_server_lock = threading.Lock()


def start_http_server(model_server, port=None, host=None):
    """Serve the inference endpoint for ``model_server`` on a daemon
    thread; returns the bound port (``port=0`` picks a free one)."""
    import http.server

    if port is None:
        port = get_env("MXNET_SERVING_PORT", 0, int)
    if host is None:
        host = get_env("MXNET_SERVING_HOST", "127.0.0.1")

    class Handler(http.server.BaseHTTPRequestHandler):
        def _reply(self, code, doc):
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - stdlib API
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                doc = model_server.health()
                self._reply(
                    503 if doc.get("status") == "degraded" else 200, doc)
            elif path == "/stats":
                self._reply(200, model_server.stats())
            else:
                self.send_error(404)

        def do_POST(self):  # noqa: N802 - stdlib API
            path = self.path.split("?", 1)[0]
            if path != "/predict":
                self.send_error(404)
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(length) or b"{}")
                inputs = doc["inputs"]
                if not isinstance(inputs, dict):
                    raise ValueError("inputs must be an object")
                deadline_ms = doc.get("deadline_ms")
            except (ValueError, KeyError, TypeError) as e:
                self._reply(400, {"error": "bad request: %s" % e})
                return
            try:
                outs = model_server.predict(inputs, deadline_ms=deadline_ms)
            except (QueueFullError, ServerClosedError) as e:
                self._reply(503, {"error": str(e), "outcome": "rejected"})
            except DeadlineExceededError as e:
                self._reply(504, {"error": str(e), "outcome": "deadline"})
            except ServingError as e:
                self._reply(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 - surface, don't kill
                self._reply(500, {"error": "%s: %s" % (type(e).__name__, e)})
            else:
                self._reply(200, {"outputs": [o.tolist() for o in outs],
                                  "rows": int(outs[0].shape[0]) if outs
                                  else 0})

        def log_message(self, *args):  # keep request lines out of stderr
            pass

    global _server, _server_thread
    with _server_lock:
        if _server is not None:
            return _server.server_address[1]
        srv = http.server.ThreadingHTTPServer((host, int(port)), Handler)
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever,
                             name="mxtpu-serving-http", daemon=True)
        t.start()
        _server, _server_thread = srv, t
        return srv.server_address[1]


def stop_http_server():
    global _server, _server_thread
    with _server_lock:
        if _server is None:
            return
        _server.shutdown()
        _server.server_close()
        _server = None
        _server_thread = None
