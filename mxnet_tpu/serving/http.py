"""Stdlib JSON inference endpoint over a :class:`ModelServer` or a
multi-model :class:`~mxnet_tpu.serving.registry.ModelRegistry`.

Same pattern as ``telemetry/export.py``: ``http.server`` on daemon
threads, loopback bind by default (the wire is unauthenticated JSON —
exposing it wider is an explicit operator choice via
``MXNET_SERVING_HOST``).

Routes::

    POST /predict        {"inputs": {name: nested list}, "deadline_ms": n?,
                          "model": "name"?, "slo_class": "realtime|standard|batch"?}
                         -> 200 {"outputs": [...], "rows": n}
    GET  /healthz        -> 200 {"status": "serving", ...verdict} when
                         healthy; 503 {"status": "degraded",
                         "causes": [...]} on queue saturation, post-warmup
                         compiles, or a high deadline-miss rate
    GET  /stats          -> 200 server (or per-model registry) stats JSON
    GET  /models         -> 200 {"models": [names]} (registry only)

Overload maps to status codes a load balancer understands: 503 for
queue-full rejection and shutdown (retryable elsewhere), **429 +
Retry-After** when admission control sheds the request's SLO class, 504
for an expired deadline, **404** for an unknown model name, **413** for
a request body over ``MXNET_SERVING_MAX_BODY_BYTES`` (default 8 MiB —
an unbounded read would let one client buffer arbitrary memory in the
server), 400 for malformed requests.
"""
from __future__ import annotations

import json
import threading

from ..base import get_env
from .. import telemetry as _telemetry
from .batcher import (DeadlineExceededError, QueueFullError,
                      ServerClosedError, ServingError)
from .registry import ModelRegistry, UnknownModelError
from .scheduler import AdmissionError
from .server import _REQS

__all__ = ["start_http_server", "stop_http_server"]

_server = None
_server_thread = None
_server_lock = threading.Lock()


def start_http_server(model_server, port=None, host=None,
                      max_body_bytes=None):
    """Serve the inference endpoint for ``model_server`` (a ModelServer
    or a ModelRegistry) on a daemon thread; returns the bound port
    (``port=0`` picks a free one)."""
    import http.server

    if port is None:
        port = get_env("MXNET_SERVING_PORT", 0, int)
    if host is None:
        host = get_env("MXNET_SERVING_HOST", "127.0.0.1")
    if max_body_bytes is None:
        max_body_bytes = get_env("MXNET_SERVING_MAX_BODY_BYTES",
                                 8 << 20, int)
    max_body_bytes = int(max_body_bytes)
    is_registry = isinstance(model_server, ModelRegistry)

    class Handler(http.server.BaseHTTPRequestHandler):
        def _reply(self, code, doc, headers=None):
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 - stdlib API
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                doc = model_server.health()
                self._reply(
                    503 if doc.get("status") == "degraded" else 200, doc)
            elif path == "/stats":
                self._reply(200, model_server.stats())
            elif path == "/models" and is_registry:
                self._reply(200, {"models": model_server.models()})
            else:
                self.send_error(404)

        def do_POST(self):  # noqa: N802 - stdlib API
            path = self.path.split("?", 1)[0]
            if path != "/predict":
                self.send_error(404)
                return
            try:
                length = int(self.headers.get("Content-Length", 0) or 0)
            except (TypeError, ValueError):
                self._reply(400, {"error": "bad Content-Length"})
                return
            if length > max_body_bytes:
                # reject BEFORE reading: the bound is the whole point.
                # The unread body makes the connection unreusable.
                if _telemetry.enabled:
                    _REQS.labels(outcome="too_large").inc()
                self.close_connection = True
                self._reply(413, {
                    "error": "request body %d bytes > limit %d "
                             "(MXNET_SERVING_MAX_BODY_BYTES)"
                             % (length, max_body_bytes),
                    "outcome": "too_large"})
                return
            try:
                doc = json.loads(self.rfile.read(length) or b"{}")
                inputs = doc["inputs"]
                if not isinstance(inputs, dict):
                    raise ValueError("inputs must be an object")
                deadline_ms = doc.get("deadline_ms")
                slo_class = doc.get("slo_class") or "standard"
                model = doc.get("model")
            except (ValueError, KeyError, TypeError) as e:
                self._reply(400, {"error": "bad request: %s" % e})
                return
            try:
                if is_registry:
                    outs = model_server.predict(
                        inputs, model=model, deadline_ms=deadline_ms,
                        slo_class=slo_class)
                else:
                    if model is not None and \
                            model != getattr(model_server, "name", model):
                        raise UnknownModelError(
                            "unknown model %r (serving %r)"
                            % (model, model_server.name))
                    outs = model_server.predict(
                        inputs, deadline_ms=deadline_ms,
                        slo_class=slo_class)
            except UnknownModelError as e:
                self._reply(404, {"error": str(e)})
            except AdmissionError as e:
                self._reply(429, {"error": str(e), "outcome": "shed"},
                            headers={"Retry-After":
                                     "%.3f" % e.retry_after_s})
            except (QueueFullError, ServerClosedError) as e:
                self._reply(503, {"error": str(e), "outcome": "rejected"})
            except DeadlineExceededError as e:
                self._reply(504, {"error": str(e), "outcome": "deadline"})
            except ServingError as e:
                self._reply(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 - surface, don't kill
                self._reply(500, {"error": "%s: %s" % (type(e).__name__, e)})
            else:
                self._reply(200, {"outputs": [o.tolist() for o in outs],
                                  "rows": int(outs[0].shape[0]) if outs
                                  else 0})

        def log_message(self, *args):  # keep request lines out of stderr
            pass

    global _server, _server_thread
    with _server_lock:
        if _server is not None:
            return _server.server_address[1]
        srv = http.server.ThreadingHTTPServer((host, int(port)), Handler)
        srv.daemon_threads = True
        t = threading.Thread(target=srv.serve_forever,
                             name="mxtpu-serving-http", daemon=True)
        t.start()
        _server, _server_thread = srv, t
        return srv.server_address[1]


def stop_http_server():
    global _server, _server_thread
    with _server_lock:
        if _server is None:
            return
        _server.shutdown()
        _server.server_close()
        _server = None
        _server_thread = None
