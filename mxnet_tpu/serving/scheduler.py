"""SLO-aware request scheduler: priority classes, EDF, admission shedding.

Replaces the FIFO pop of :class:`~mxnet_tpu.serving.batcher.DynamicBatcher`
with service-level-objective scheduling (the Clipper/INFaaS lineage):

* every request carries an **SLO class** — ``realtime`` > ``standard`` >
  ``batch`` — and batches are formed strictly by class priority;
* **within** a class requests are ordered earliest-deadline-first (EDF,
  the classic single-resource optimum for feasible deadline sets);
  deadline-less requests keep submission order, so a default-class,
  deadline-less workload degenerates to exactly the old FIFO behaviour;
* **admission control sheds lowest class first**: as queue occupancy
  crosses ``shed_batch_at`` / ``shed_standard_at`` (or when the server's
  ``health()`` verdict degrades — the server raises the *shed floor*),
  ``batch`` then ``standard`` submissions are rejected with
  :class:`AdmissionError` (HTTP 429 + Retry-After) while ``realtime``
  traffic is admitted until the queue is genuinely full.  A degraded
  server thus sacrifices its cheapest traffic instead of blowing every
  deadline a little.

Lock discipline (graftlint GL003): everything under ``self._nonempty``
is O(queued requests) pure-python bookkeeping — no device sync, no I/O;
the level-transition callback fires after the lock is released.
"""
from __future__ import annotations

import heapq
import itertools
import threading
import time
from typing import Optional, Sequence

from ..base import get_env
from .batcher import (DynamicBatcher, QueueFullError, Request,
                      ServerClosedError, ServingError)

__all__ = ["SLO_CLASSES", "AdmissionError", "SloScheduler"]

#: priority order, highest first; index == priority value (lower = better)
SLO_CLASSES = ("realtime", "standard", "batch")
_PRIORITY = {c: i for i, c in enumerate(SLO_CLASSES)}


class AdmissionError(ServingError):
    """Admission control shed this request (HTTP 429): the server is
    saturated/degraded and the request's SLO class is below the current
    admission floor.  ``retry_after_s`` is the client backoff hint."""

    def __init__(self, msg, retry_after_s: float = 0.05):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class SloScheduler(DynamicBatcher):
    """Drop-in DynamicBatcher replacement with SLO classes.

    Storage is one EDF heap per class (entries ``(deadline|inf, seq,
    req)``) instead of the single deque; ``put``/``get_batch``/
    ``drop_all`` are overridden, the bucket/window/close plumbing is
    inherited.  Batch formation pops the highest-priority class first
    and never lets a lower-priority request overtake a higher-priority
    head that doesn't fit (the no-starvation rule the FIFO batcher had,
    now per class).

    Shed levels: 0 admit all, 1 shed ``batch``, 2 shed ``standard`` too.
    The effective level is ``max(occupancy-derived level, shed floor)``
    where the floor is set by the owning server from its health verdict
    (:meth:`set_shed_floor`).  ``on_level_change(level, prev, occupancy)``
    fires outside the lock on every transition (both directions).
    """

    def __init__(self, batch_buckets: Sequence[int], max_batch_size: int,
                 batch_timeout_ms: float, queue_depth: int,
                 shed_batch_at: Optional[float] = None,
                 shed_standard_at: Optional[float] = None,
                 retry_after_ms: Optional[float] = None):
        super().__init__(batch_buckets, max_batch_size, batch_timeout_ms,
                         queue_depth)
        if shed_batch_at is None:
            shed_batch_at = get_env("MXNET_SERVING_SHED_BATCH_AT", 0.5, float)
        if shed_standard_at is None:
            shed_standard_at = get_env(
                "MXNET_SERVING_SHED_STANDARD_AT", 0.8, float)
        if retry_after_ms is None:
            retry_after_ms = get_env(
                "MXNET_SERVING_RETRY_AFTER_MS", 50.0, float)
        self.shed_batch_at = float(shed_batch_at)
        self.shed_standard_at = float(shed_standard_at)
        self.retry_after_s = float(retry_after_ms) / 1e3
        self._heaps = {c: [] for c in SLO_CLASSES}
        self._count = 0
        self._seq = itertools.count()
        self._shed_floor = 0
        self._level = 0
        #: callable(level, prev_level, occupancy) or None; called OUTSIDE
        #: the scheduler lock on every shed-level transition
        self.on_level_change = None

    # -- introspection -----------------------------------------------------
    def __len__(self):
        with self._lock:
            return self._count

    @property
    def level(self) -> int:
        """Current effective shed level (0..2)."""
        with self._lock:
            return max(self._level, self._shed_floor)

    def queued_by_class(self):
        with self._lock:
            return {c: len(h) for c, h in self._heaps.items()}

    # -- admission control -------------------------------------------------
    def set_shed_floor(self, floor: int):
        """Minimum shed level, driven by the server's health verdict: a
        degraded server sheds ``batch`` (floor 1) even before the queue
        saturates."""
        transition = None
        with self._nonempty:
            floor = max(0, min(2, int(floor)))
            if floor == self._shed_floor:
                return
            prev = max(self._level, self._shed_floor)
            self._shed_floor = floor
            level = max(self._level, floor)
            occ = (self._count / float(self.queue_depth)
                   if self.queue_depth else 1.0)
            if level != prev:
                transition = (level, prev, occ)
        self._fire_level_change(transition)

    def _fire_level_change(self, transition):
        if transition is not None and self.on_level_change is not None:
            try:
                self.on_level_change(*transition)
            except Exception:   # noqa: BLE001 - observers must not break
                pass            # admission

    # -- producer side -----------------------------------------------------
    def put(self, req: Request):
        """Admit or shed; never blocks.  Raises :class:`AdmissionError`
        when the request's class is currently shed, :class:`QueueFullError`
        when the queue is full outright (any class)."""
        if req.rows > self.max_batch_size:
            raise ServingError(
                "request carries %d rows > max_batch_size %d (split it)"
                % (req.rows, self.max_batch_size))
        cls = getattr(req, "slo_class", None) or "standard"
        if cls not in _PRIORITY:
            raise ServingError("unknown slo_class %r (one of %s)"
                               % (cls, list(SLO_CLASSES)))
        transition, exc = None, None
        with self._nonempty:
            if self._closed:
                raise ServerClosedError("server is shut down")
            occ = (self._count / float(self.queue_depth)
                   if self.queue_depth else 1.0)
            occ_level = 0
            if occ >= self.shed_standard_at:
                occ_level = 2
            elif occ >= self.shed_batch_at:
                occ_level = 1
            prev = max(self._level, self._shed_floor)
            self._level = occ_level
            level = max(occ_level, self._shed_floor)
            if level != prev:
                transition = (level, prev, occ)
            if self._count >= self.queue_depth:
                exc = QueueFullError(
                    "serving queue full (%d requests); retry with backoff"
                    % self._count)
            elif level > 0 and _PRIORITY[cls] >= 3 - level:
                exc = AdmissionError(
                    "admission control shedding %r traffic (level %d, "
                    "queue %.0f%% full); retry after %.0f ms"
                    % (cls, level, occ * 100.0, self.retry_after_s * 1e3),
                    retry_after_s=self.retry_after_s)
            else:
                dkey = req.deadline if req.deadline is not None \
                    else float("inf")
                heapq.heappush(self._heaps[cls],
                               (dkey, next(self._seq), req))
                self._count += 1
                self._rows_queued += req.rows
                self._nonempty.notify()
        self._fire_level_change(transition)
        if exc is not None:
            raise exc

    def drop_all(self, error_factory):
        with self._nonempty:
            dropped = [entry[2] for c in SLO_CLASSES
                       for entry in self._heaps[c]]
            for c in SLO_CLASSES:
                self._heaps[c] = []
            self._count = 0
            self._rows_queued = 0
        for req in dropped:
            req._fail(error_factory(), "error")
        return len(dropped)

    # -- consumer side -----------------------------------------------------
    def get_batch(self):
        """Next batch: highest class first, EDF within class, stop at the
        first head that doesn't fit (no overtaking across or within
        classes).  None when closed and drained."""
        with self._nonempty:
            while self._count == 0:
                if self._closed:
                    return None
                self._nonempty.wait()
            window_end = time.monotonic() + self.batch_timeout
            while (self._rows_queued < self.max_batch_size
                   and not self._closed):
                remaining = window_end - time.monotonic()
                if remaining <= 0:
                    break
                self._nonempty.wait(remaining)
            reqs, rows = [], 0
            now = time.monotonic()
            for cls in SLO_CLASSES:
                heap = self._heaps[cls]
                blocked = False
                while heap:
                    nxt = heap[0][2]
                    if rows + nxt.rows > self.max_batch_size:
                        blocked = True
                        break
                    heapq.heappop(heap)
                    self._count -= 1
                    self._rows_queued -= nxt.rows
                    nxt.dequeue_t = now
                    reqs.append(nxt)
                    rows += nxt.rows
                if blocked:
                    break
            return reqs
