"""Bounded request queue + dynamic batcher with shape buckets.

Reference analog: Clipper's adaptive batching and TF-Serving's
``BatchingSession`` — concurrent single-request callers are coalesced
into one device-sized batch.  On TPU the batcher is additionally a
*compile-count* mechanism: every distinct batch shape is a distinct XLA
program, so instead of executing at the realized batch size (which would
compile a program per observed size), batches are padded up to the next
size in a small declared ``batch_buckets`` set.  Steady-state compiled
program count is then bounded by ``len(batch_buckets)`` regardless of
traffic shape.

The queue is bounded (admission control): ``put`` rejects with
:class:`QueueFullError` instead of queueing unboundedly — overload
surfaces at the edge as an explicit, cheap rejection rather than as
collapse.  Each request may carry a deadline; the server drops expired
requests *before* execution (a late answer costs a full batch slot and
is still useless to the caller).
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError

__all__ = ["Request", "DynamicBatcher", "ServingError", "QueueFullError",
           "DeadlineExceededError", "ServerClosedError", "pow2_buckets"]


class ServingError(MXNetError):
    """Base class for serving-layer failures."""


class QueueFullError(ServingError):
    """Admission control: the bounded request queue is full."""


class DeadlineExceededError(ServingError):
    """The request's deadline expired before execution."""


class ServerClosedError(ServingError):
    """The server is shut down (or draining) and not accepting work."""


def pow2_buckets(max_batch_size: int) -> Tuple[int, ...]:
    """Power-of-two bucket ladder up to (and always including)
    ``max_batch_size``: 8 -> (1, 2, 4, 8); 6 -> (1, 2, 4, 6)."""
    if max_batch_size < 1:
        raise ServingError("max_batch_size must be >= 1, got %r"
                           % (max_batch_size,))
    out = []
    b = 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(max_batch_size)
    return tuple(out)


class Request:
    """One in-flight inference request: inputs, deadline, and a
    one-shot completion event the caller blocks on.

    ``inputs`` maps input name -> np.ndarray of shape ``(rows, *example)``;
    a request may carry several examples (``rows`` >= 1).  ``deadline``
    is an absolute ``time.monotonic()`` instant or None.  ``slo_class``
    is the scheduling class (see :mod:`~mxnet_tpu.serving.scheduler`);
    the plain FIFO batcher ignores it.
    """

    __slots__ = ("inputs", "rows", "deadline", "slo_class", "submit_t",
                 "dequeue_t", "outcome", "flow_id", "_event", "_outputs",
                 "_error")

    def __init__(self, inputs: Dict[str, np.ndarray], rows: int,
                 deadline: Optional[float] = None,
                 slo_class: str = "standard"):
        self.inputs = inputs
        self.rows = int(rows)
        self.deadline = deadline
        self.slo_class = slo_class
        self.submit_t = time.monotonic()
        self.dequeue_t = None
        self.outcome = None          # ok | rejected | deadline | error
        self.flow_id = None          # tracing flow id (submit -> batch exec)
        self._event = threading.Event()
        self._outputs = None
        self._error = None

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (time.monotonic() if now is None else now) >= self.deadline

    # -- completion (server side) ------------------------------------------
    def _complete(self, outputs):
        self._outputs = outputs
        self.outcome = "ok"
        self._event.set()

    def _fail(self, error: Exception, outcome: str):
        self._error = error
        self.outcome = outcome
        self._event.set()

    # -- waiting (caller side) ---------------------------------------------
    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until completion; returns the list of per-output arrays
        (each ``(rows, *out_shape)``) or raises the failure."""
        if not self._event.wait(timeout):
            raise ServingError("request not completed within %.3fs"
                               % (timeout,))
        if self._error is not None:
            raise self._error
        return self._outputs


class DynamicBatcher:
    """Bounded FIFO of :class:`Request` + batch formation.

    ``get_batch`` blocks for the first request, then holds the batch open
    for up to ``batch_timeout_ms`` (or until ``max_batch_size`` rows are
    queued) so concurrent callers coalesce, and dequeues a prefix of the
    queue that fits ``max_batch_size`` rows.  FIFO order is never
    reordered — a large request at the head is not overtaken by smaller
    ones behind it (no starvation).
    """

    def __init__(self, batch_buckets: Sequence[int], max_batch_size: int,
                 batch_timeout_ms: float, queue_depth: int):
        buckets = sorted(set(int(b) for b in batch_buckets))
        if not buckets or buckets[0] < 1:
            raise ServingError("batch_buckets must be positive ints, got %r"
                               % (batch_buckets,))
        if buckets[-1] != int(max_batch_size):
            raise ServingError(
                "largest bucket (%d) must equal max_batch_size (%d)"
                % (buckets[-1], max_batch_size))
        self.buckets = tuple(buckets)
        self.max_batch_size = int(max_batch_size)
        self.batch_timeout = float(batch_timeout_ms) / 1e3
        self.queue_depth = int(queue_depth)
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._queue = collections.deque()
        self._rows_queued = 0
        self._closed = False

    # -- introspection -----------------------------------------------------
    def __len__(self):
        with self._lock:
            return len(self._queue)

    @property
    def rows_queued(self) -> int:
        with self._lock:
            return self._rows_queued

    @property
    def closed(self) -> bool:
        return self._closed

    def bucket_for(self, rows: int) -> Optional[int]:
        """Smallest declared bucket >= rows, or None if rows exceeds the
        largest bucket."""
        for b in self.buckets:
            if rows <= b:
                return b
        return None

    # -- producer side -----------------------------------------------------
    def put(self, req: Request):
        """Admit a request or reject loudly (never blocks)."""
        if req.rows > self.max_batch_size:
            raise ServingError(
                "request carries %d rows > max_batch_size %d (split it)"
                % (req.rows, self.max_batch_size))
        with self._nonempty:
            if self._closed:
                raise ServerClosedError("server is shut down")
            if len(self._queue) >= self.queue_depth:
                raise QueueFullError(
                    "serving queue full (%d requests); retry with backoff"
                    % len(self._queue))
            self._queue.append(req)
            self._rows_queued += req.rows
            self._nonempty.notify()

    def close(self):
        """Stop admitting; wakes all ``get_batch`` waiters so workers can
        drain the remaining queue and exit."""
        with self._nonempty:
            self._closed = True
            self._nonempty.notify_all()

    def drop_all(self, error_factory):
        """Fail every queued request (non-draining shutdown); returns the
        number dropped."""
        with self._nonempty:
            dropped = list(self._queue)
            self._queue.clear()
            self._rows_queued = 0
        for req in dropped:
            req._fail(error_factory(), "error")
        return len(dropped)

    # -- consumer side -----------------------------------------------------
    def get_batch(self):
        """Next batch of requests (FIFO prefix fitting max_batch_size rows)
        or None when closed and fully drained."""
        with self._nonempty:
            while not self._queue:
                if self._closed:
                    return None
                self._nonempty.wait()
            # hold the window open for stragglers to coalesce
            window_end = time.monotonic() + self.batch_timeout
            while (self._rows_queued < self.max_batch_size
                   and not self._closed):
                remaining = window_end - time.monotonic()
                if remaining <= 0:
                    break
                self._nonempty.wait(remaining)
            reqs, rows = [], 0
            now = time.monotonic()
            while self._queue:
                nxt = self._queue[0]
                if rows + nxt.rows > self.max_batch_size:
                    break
                self._queue.popleft()
                self._rows_queued -= nxt.rows
                nxt.dequeue_t = now
                reqs.append(nxt)
                rows += nxt.rows
            return reqs
