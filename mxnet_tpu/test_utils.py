"""Testing utilities (parity: ``python/mxnet/test_utils.py``, 1,955 LoC in
the reference — the numeric-gradient checker, tolerance asserts, random
tensors for all stypes, and backend cross-checking used throughout
``tests/python/unittest``).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .base import MXNetError
from . import context as _context

_DEFAULT_CTX = None


def default_context():
    """The context tests run on (reference default_context(), env-switchable
    via MXNET_TEST_DEVICE)."""
    global _DEFAULT_CTX
    if _DEFAULT_CTX is None:
        import os
        dev = os.environ.get("MXNET_TEST_DEVICE", "")
        if dev.startswith("tpu"):
            _DEFAULT_CTX = _context.tpu(0)
        elif dev.startswith("gpu"):
            _DEFAULT_CTX = _context.gpu(0)
        else:
            _DEFAULT_CTX = _context.current_context()
    return _DEFAULT_CTX


def set_default_context(ctx):
    global _DEFAULT_CTX
    _DEFAULT_CTX = ctx


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20):
    return np.allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b")):
    """Tolerance assert with a useful message (reference
    assert_almost_equal)."""
    from .ndarray import NDArray
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    if not np.allclose(a, b, rtol=rtol, atol=atol):
        err = np.abs(a - b)
        rel = err / (np.abs(b) + 1e-12)
        raise AssertionError(
            "%s and %s differ: max abs err %g, max rel err %g "
            "(rtol=%g atol=%g)" % (names[0], names[1], err.max(), rel.max(),
                                   rtol, atol))


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None):
    """Random NDArray of any storage type (reference rand_ndarray)."""
    from . import ndarray as nd
    dtype = dtype or np.float32
    if stype == "default":
        return nd.array(np.random.uniform(-1, 1, shape).astype(dtype),
                        ctx=ctx)
    return rand_sparse_ndarray(shape, stype, density=density, dtype=dtype)


def rand_sparse_ndarray(shape, stype, density=None, dtype=None):
    from .ndarray import sparse
    dtype = dtype or np.float32
    density = 0.5 if density is None else density
    dense = np.random.uniform(-1, 1, shape).astype(dtype)
    mask = np.random.uniform(0, 1, (shape[0],) if stype == "row_sparse"
                             else shape) <= density
    if stype == "row_sparse":
        dense = dense * mask.reshape((-1,) + (1,) * (len(shape) - 1))
    else:
        dense = dense * mask
    from . import ndarray as nd
    return nd.array(dense).tostype(stype)


def _executor_for(sym, location, aux_states, grad_req, ctx):
    from . import ndarray as nd
    args = {k: (v if isinstance(v, nd.NDArray) else nd.array(v))
            for k, v in location.items()}
    grads = {k: nd.zeros(v.shape, dtype=v.dtype) for k, v in args.items()
             if grad_req.get(k, "write") != "null"}
    aux = {k: (v if isinstance(v, nd.NDArray) else nd.array(v))
           for k, v in (aux_states or {}).items()}
    return sym.bind(ctx, args, args_grad=grads, grad_req=grad_req,
                    aux_states=aux)


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None, ctx=None,
                           dtype=np.float64):
    """Finite-difference gradient check of a symbol's backward
    (reference check_numeric_gradient).

    location: dict arg name -> np.ndarray/NDArray.  The symbol's outputs are
    reduced with a fixed random projection to a scalar; analytic grads from
    backward are compared to central differences of the forward.
    """
    from . import ndarray as nd
    ctx = ctx or default_context()
    # dtype governs host-side perturbation/difference arithmetic; device
    # execution stays in each arg's own dtype
    location = {k: np.asarray(v.asnumpy() if isinstance(v, nd.NDArray)
                              else v, dtype)
                for k, v in location.items()}
    grad_nodes = list(grad_nodes or location.keys())
    grad_req = {k: ("write" if k in grad_nodes else "null")
                for k in location}

    ex = _executor_for(sym, location, aux_states, grad_req, ctx)
    outs = ex.forward(is_train=True)
    rng = np.random.RandomState(0)
    projections = [rng.normal(0, 1, o.shape).astype(np.float32)
                   for o in outs]

    def loss_at(loc):
        for k, v in loc.items():
            ex.arg_dict[k][:] = v
        outs = ex.forward(is_train=True)
        return sum(float((o.asnumpy().astype(np.float64) * p).sum())
                   for o, p in zip(outs, projections))

    ex.forward(is_train=True)
    ex.backward([nd.array(p) for p in projections])
    analytic = {k: ex.grad_dict[k].asnumpy().copy() for k in grad_nodes}

    atol = rtol if atol is None else atol
    for name in grad_nodes:
        base = location[name]
        num = np.zeros_like(base, np.float64)
        flat = base.ravel()
        numf = num.ravel()
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps
            fp = loss_at(location)
            flat[i] = orig - numeric_eps
            fm = loss_at(location)
            flat[i] = orig
            numf[i] = (fp - fm) / (2 * numeric_eps)
        loss_at(location)  # restore
        a, n = analytic[name], num
        denom = np.maximum(np.abs(n), np.abs(a))
        bad = np.abs(a - n) > (atol + rtol * denom)
        if bad.any():
            raise AssertionError(
                "numeric gradient check failed for %r: analytic %s vs "
                "numeric %s" % (name, a.ravel()[bad.ravel()][:5],
                                n.ravel()[bad.ravel()][:5]))


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None):
    """Compare symbol forward outputs against expected arrays
    (reference check_symbolic_forward)."""
    from . import ndarray as nd
    ctx = ctx or default_context()
    if isinstance(location, (list, tuple)):
        location = dict(zip(sym.list_arguments(), location))
    grad_req = {k: "null" for k in location}
    ex = _executor_for(sym, location, aux_states, grad_req, ctx)
    outs = ex.forward(is_train=False)
    for o, e in zip(outs, expected):
        assert_almost_equal(o.asnumpy(), e, rtol=rtol,
                            atol=rtol if atol is None else atol)
    return outs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None):
    """Compare symbol backward gradients against expected arrays
    (reference check_symbolic_backward)."""
    from . import ndarray as nd
    ctx = ctx or default_context()
    if isinstance(location, (list, tuple)):
        location = dict(zip(sym.list_arguments(), location))
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    reqs = {k: grad_req for k in location} if isinstance(grad_req, str) \
        else grad_req
    ex = _executor_for(sym, location, aux_states, reqs, ctx)
    ex.forward(is_train=True)
    ex.backward([g if isinstance(g, nd.NDArray) else nd.array(g)
                 for g in out_grads])
    for k, e in expected.items():
        if reqs.get(k) == "null":
            continue
        assert_almost_equal(ex.grad_dict[k].asnumpy(), e, rtol=rtol,
                            atol=rtol if atol is None else atol,
                            names=("grad(%s)" % k, "expected"))
    return ex


def check_consistency(sym, ctx_list, scale=1.0, rtol=1e-3, atol=1e-4):
    """Run one symbol on several contexts and require matching outputs
    (reference check_consistency — the CPU/GPU cross-check pattern, here
    CPU interpreter vs TPU)."""
    if not ctx_list:
        return
    # ctx_list entries: {'ctx': Context, <arg shapes by name>}
    arg_shapes = {k: v for k, v in ctx_list[0].items() if k != "ctx"}
    rng = np.random.RandomState(0)
    location = {k: (rng.normal(0, scale, s).astype(np.float32))
                for k, s in arg_shapes.items()}
    outputs = []
    for entry in ctx_list:
        ctx = entry["ctx"]
        grad_req = {k: "null" for k in location}
        ex = _executor_for(sym, location, None, grad_req, ctx)
        outputs.append([o.asnumpy() for o in ex.forward(is_train=False)])
    for other in outputs[1:]:
        for a, b in zip(outputs[0], other):
            assert_almost_equal(a, b, rtol=rtol, atol=atol)
    return outputs


def list_gpus():
    return []


def list_tpus():
    import jax
    try:
        return [d.id for d in jax.devices() if d.platform in ("tpu", "axon")]
    except RuntimeError:
        return []
