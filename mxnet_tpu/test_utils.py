"""Testing utilities (parity: ``python/mxnet/test_utils.py``, 1,955 LoC in
the reference — the numeric-gradient checker, tolerance asserts, random
tensors for all stypes, and backend cross-checking used throughout
``tests/python/unittest``).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .base import MXNetError
from . import context as _context

_DEFAULT_CTX = None


def default_context():
    """The context tests run on (reference default_context(), env-switchable
    via MXNET_TEST_DEVICE)."""
    global _DEFAULT_CTX
    if _DEFAULT_CTX is None:
        import os
        dev = os.environ.get("MXNET_TEST_DEVICE", "")
        if dev.startswith("tpu"):
            _DEFAULT_CTX = _context.tpu(0)
        elif dev.startswith("gpu"):
            _DEFAULT_CTX = _context.gpu(0)
        else:
            _DEFAULT_CTX = _context.current_context()
    return _DEFAULT_CTX


def set_default_context(ctx):
    global _DEFAULT_CTX
    _DEFAULT_CTX = ctx


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20, equal_nan=False):
    return np.allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol,
                       equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b"),
                        equal_nan=False):
    """Tolerance assert with a useful message (reference
    assert_almost_equal)."""
    from .ndarray import NDArray
    a = a.asnumpy() if isinstance(a, NDArray) else np.asarray(a)
    b = b.asnumpy() if isinstance(b, NDArray) else np.asarray(b)
    if not np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan):
        err = np.abs(a - b)
        rel = err / (np.abs(b) + 1e-12)
        raise AssertionError(
            "%s and %s differ: max abs err %g, max rel err %g "
            "(rtol=%g atol=%g)" % (names[0], names[1], err.max(), rel.max(),
                                   rtol, atol))


def rand_shape_2d(dim0=10, dim1=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (np.random.randint(1, dim0 + 1), np.random.randint(1, dim1 + 1),
            np.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10, allow_zero_size=False):
    """Random shape of ``num_dim`` dims, each in [1, dim] (or [0, dim] when
    zero-size edge shapes are wanted) — reference rand_shape_nd."""
    low = 0 if allow_zero_size else 1
    return tuple(np.random.randint(low, dim + 1, size=num_dim).tolist())


def rand_coord_2d(x_low, x_high, y_low, y_high):
    """A random 2-D coordinate (reference rand_coord_2d)."""
    return (np.random.randint(x_low, x_high),
            np.random.randint(y_low, y_high))


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None):
    """Random NDArray of any storage type (reference rand_ndarray)."""
    from . import ndarray as nd
    dtype = dtype or np.float32
    if stype == "default":
        return nd.array(np.random.uniform(-1, 1, shape).astype(dtype),
                        ctx=ctx)
    return rand_sparse_ndarray(shape, stype, density=density, dtype=dtype)


def rand_sparse_ndarray(shape, stype, density=None, dtype=None):
    from .ndarray import sparse
    dtype = dtype or np.float32
    density = 0.5 if density is None else density
    dense = np.random.uniform(-1, 1, shape).astype(dtype)
    mask = np.random.uniform(0, 1, (shape[0],) if stype == "row_sparse"
                             else shape) <= density
    if stype == "row_sparse":
        dense = dense * mask.reshape((-1,) + (1,) * (len(shape) - 1))
    else:
        dense = dense * mask
    from . import ndarray as nd
    return nd.array(dense).tostype(stype)


def _executor_for(sym, location, aux_states, grad_req, ctx):
    from . import ndarray as nd
    args = {k: (v if isinstance(v, nd.NDArray) else nd.array(v))
            for k, v in location.items()}
    grads = {k: nd.zeros(v.shape, dtype=v.dtype) for k, v in args.items()
             if grad_req.get(k, "write") != "null"}
    aux = {k: (v if isinstance(v, nd.NDArray) else nd.array(v))
           for k, v in (aux_states or {}).items()}
    return sym.bind(ctx, args, args_grad=grads, grad_req=grad_req,
                    aux_states=aux)


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None, ctx=None,
                           dtype=np.float64):
    """Finite-difference gradient check of a symbol's backward
    (reference check_numeric_gradient).

    location: dict arg name -> np.ndarray/NDArray.  The symbol's outputs are
    reduced with a fixed random projection to a scalar; analytic grads from
    backward are compared to central differences of the forward.
    """
    from . import ndarray as nd
    ctx = ctx or default_context()
    # dtype governs host-side perturbation/difference arithmetic; device
    # execution stays in each arg's own dtype
    location = {k: np.asarray(v.asnumpy() if isinstance(v, nd.NDArray)
                              else v, dtype)
                for k, v in location.items()}
    grad_nodes = list(grad_nodes or location.keys())
    grad_req = {k: ("write" if k in grad_nodes else "null")
                for k in location}

    ex = _executor_for(sym, location, aux_states, grad_req, ctx)
    outs = ex.forward(is_train=True)
    rng = np.random.RandomState(0)
    projections = [rng.normal(0, 1, o.shape).astype(np.float32)
                   for o in outs]

    def loss_at(loc):
        for k, v in loc.items():
            ex.arg_dict[k][:] = v
        outs = ex.forward(is_train=True)
        return sum(float((o.asnumpy().astype(np.float64) * p).sum())
                   for o, p in zip(outs, projections))

    ex.forward(is_train=True)
    ex.backward([nd.array(p) for p in projections])
    analytic = {k: ex.grad_dict[k].asnumpy().copy() for k in grad_nodes}

    atol = rtol if atol is None else atol
    for name in grad_nodes:
        base = location[name]
        num = np.zeros_like(base, np.float64)
        flat = base.ravel()
        numf = num.ravel()
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + numeric_eps
            fp = loss_at(location)
            flat[i] = orig - numeric_eps
            fm = loss_at(location)
            flat[i] = orig
            numf[i] = (fp - fm) / (2 * numeric_eps)
        loss_at(location)  # restore
        a, n = analytic[name], num
        denom = np.maximum(np.abs(n), np.abs(a))
        bad = np.abs(a - n) > (atol + rtol * denom)
        if bad.any():
            raise AssertionError(
                "numeric gradient check failed for %r: analytic %s vs "
                "numeric %s" % (name, a.ravel()[bad.ravel()][:5],
                                n.ravel()[bad.ravel()][:5]))


def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None):
    """Compare symbol forward outputs against expected arrays
    (reference check_symbolic_forward)."""
    from . import ndarray as nd
    ctx = ctx or default_context()
    if isinstance(location, (list, tuple)):
        location = dict(zip(sym.list_arguments(), location))
    grad_req = {k: "null" for k in location}
    ex = _executor_for(sym, location, aux_states, grad_req, ctx)
    outs = ex.forward(is_train=False)
    for o, e in zip(outs, expected):
        assert_almost_equal(o.asnumpy(), e, rtol=rtol,
                            atol=rtol if atol is None else atol)
    return outs


def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None):
    """Compare symbol backward gradients against expected arrays
    (reference check_symbolic_backward)."""
    from . import ndarray as nd
    ctx = ctx or default_context()
    if isinstance(location, (list, tuple)):
        location = dict(zip(sym.list_arguments(), location))
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    reqs = {k: grad_req for k in location} if isinstance(grad_req, str) \
        else grad_req
    ex = _executor_for(sym, location, aux_states, reqs, ctx)
    ex.forward(is_train=True)
    ex.backward([g if isinstance(g, nd.NDArray) else nd.array(g)
                 for g in out_grads])
    for k, e in expected.items():
        if reqs.get(k) == "null":
            continue
        assert_almost_equal(ex.grad_dict[k].asnumpy(), e, rtol=rtol,
                            atol=rtol if atol is None else atol,
                            names=("grad(%s)" % k, "expected"))
    return ex


# per-dtype default tolerances (reference check_consistency tol table:
# fp16 1e-1, fp32 1e-3, fp64 1e-5, int types exact; bfloat16 has a coarser
# mantissa than fp16 so it shares the loose tier)
_DTYPE_RTOL = {"float16": 1e-1, "bfloat16": 1e-1, "float32": 1e-3,
               "float64": 1e-5}
_DTYPE_ATOL = {"float16": 1e-1, "bfloat16": 1e-1, "float32": 1e-4,
               "float64": 1e-7}
# precision ranking by mantissa bits (bf16 < fp16 < fp32 < fp64); numpy
# reports bfloat16 (an ml_dtypes extension type) as kind 'V', so rank by
# name, not itemsize/kind
_MANTISSA_BITS = {"bfloat16": 8, "float16": 10, "float32": 23,
                  "float64": 52}


def _float_rank(dtype):
    """Mantissa bits of a float-ish dtype, or None for non-floats."""
    return _MANTISSA_BITS.get(np.dtype(dtype).name)


def _entry_dtypes(entry, names):
    td = entry.get("type_dict", {})
    return {k: np.dtype(td.get(k, np.float32)) for k in names}


def check_consistency(sym, ctx_list, scale=1.0, rtol=None, atol=None,
                      grad_req="write", equal_nan=False):
    """Run one symbol across contexts *and dtypes* and require matching
    forward outputs and backward gradients (reference check_consistency —
    the CPU/GPU + fp16-grid cross-check pattern; here contexts are CPU
    interpreter vs TPU and the dtype grid covers fp16/bf16/fp32/fp64).

    ctx_list entries: ``{'ctx': Context, <arg name>: shape, ...,
    'type_dict': {arg name: dtype}}``.  Ground truth is the
    highest-precision entry; every other entry is compared against it with
    tolerances keyed to the lower-precision dtype of the pair (overridable
    via rtol/atol).  With ``grad_req != 'null'``, backward runs with a
    fixed random head gradient and argument gradients must match too.
    """
    from . import ndarray as nd
    if not ctx_list:
        return
    arg_shapes = {k: v for k, v in ctx_list[0].items()
                  if k not in ("ctx", "type_dict")}
    names = list(arg_shapes)
    rng = np.random.RandomState(0)
    base = {k: rng.normal(0, scale, s).astype(np.float64)
            for k, s in arg_shapes.items()}
    reqs = ({k: grad_req for k in names} if isinstance(grad_req, str)
            else dict(grad_req))
    run_backward = any(r != "null" for r in reqs.values())

    results = []   # (min_dtype, outputs, grads)
    head_grads = None
    for entry in ctx_list:
        dtypes = _entry_dtypes(entry, names)
        location = {k: base[k].astype(dtypes[k]) for k in names}
        ex = _executor_for(sym, location, None, reqs, entry["ctx"])
        outs = ex.forward(is_train=run_backward)
        grads = {}
        if run_backward:
            if head_grads is None:
                head_grads = [rng.normal(0, 1, o.shape)
                              .astype(np.float64) for o in outs]
            ex.backward([nd.array(h.astype(o.dtype))
                         for h, o in zip(head_grads, outs)])
            grads = {k: ex.grad_dict[k].asnumpy()
                     for k in names if reqs.get(k) != "null"}
        ranks = [_float_rank(dt) for dt in dtypes.values()]
        ranks = [r for r in ranks if r is not None] or \
            [_MANTISSA_BITS["float32"]]
        min_rank = min(ranks)
        results.append((min_rank, [o.asnumpy() for o in outs], grads))

    # ground truth: the entry whose lowest-precision dtype is widest
    gt_idx = max(range(len(results)), key=lambda i: results[i][0])
    gt_rank, gt_outs, gt_grads = results[gt_idx]
    rank2name = {v: k for k, v in _MANTISSA_BITS.items()}
    for i, (rank, outs, grads) in enumerate(results):
        if i == gt_idx:
            continue
        pair_name = rank2name[min(rank, gt_rank)]
        r = _DTYPE_RTOL.get(pair_name, 1e-3) if rtol is None else rtol
        a = _DTYPE_ATOL.get(pair_name, 1e-4) if atol is None else atol
        for o, e in zip(outs, gt_outs):
            assert_almost_equal(np.asarray(o, np.float64),
                                np.asarray(e, np.float64), rtol=r, atol=a,
                                equal_nan=equal_nan,
                                names=("ctx[%d] output" % i, "ground truth"))
        for k in grads:
            assert_almost_equal(np.asarray(grads[k], np.float64),
                                np.asarray(gt_grads[k], np.float64),
                                rtol=r, atol=a, equal_nan=equal_nan,
                                names=("ctx[%d] grad(%s)" % (i, k),
                                       "ground truth"))
    return [outs for _, outs, _ in results]


def check_speed(sym, location=None, ctx=None, n=20, grad_req="null",
                typ="whole", **arg_shapes):
    """Median seconds per execution (reference check_speed).  ``typ``:
    'whole' = forward+backward when grad_req allows it, 'forward' =
    forward only regardless of grad_req."""
    import time
    from . import ndarray as nd
    if typ not in ("whole", "forward"):
        raise MXNetError("check_speed typ must be 'whole' or 'forward'")
    ctx = ctx or default_context()
    if location is None:
        rng = np.random.RandomState(0)
        location = {k: rng.normal(0, 1, s).astype(np.float32)
                    for k, s in arg_shapes.items()}
    reqs = {k: grad_req for k in location}
    ex = _executor_for(sym, location, None, reqs, ctx)
    run_backward = grad_req != "null" and typ == "whole"

    def once():
        outs = ex.forward(is_train=run_backward)
        if run_backward:
            ex.backward([nd.ones(o.shape, dtype=o.dtype) for o in outs])
            for g in ex.grad_dict.values():
                g.asnumpy()
        else:
            for o in outs:
                o.asnumpy()

    once()  # compile
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        once()
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def list_gpus():
    return []


def list_tpus():
    import jax
    try:
        return [d.id for d in jax.devices() if d.platform in ("tpu", "axon")]
    except RuntimeError:
        return []
