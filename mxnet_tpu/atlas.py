"""Program Atlas: per-layer flop/byte attribution inside fused XLA programs.

The fused whole-step path (the default since PR 6) collapses forward,
backward and the optimizer update into ONE opaque XLA program, so the old
per-op executor spans attribute nothing and health.py (PR 7) reports only
whole-program aggregates.  The atlas recovers the per-layer breakdown
without giving up fusion, in two halves:

**Scope annotation (trace time).**  Every traced op application is wrapped
in ``jax.named_scope`` at the single choke points — the ``_Plan`` execution
loop and segment builder in executor.py, the op-apply wrapper in
ops/registry.py, and the optimizer/grad-sync stages of the step/update
program builders (executor.py / fused_step.py / fused.py).  The scope name
contract:

- ``<OpType>:<node_name>`` — one graph node's op application (e.g.
  ``Convolution:stage1_conv1``).  Eager per-op entries use the anonymous
  node ``~``.
- ``Optimizer::<Name>`` — one optimizer's fused update stage
  (:func:`optimizer_scope`; ``Optimizer.atlas_scope_name`` overrides).
- ``GradSync`` — the in-program gradient reduce (replica sum / mesh
  all-reduce).

jax carries these names into the lowered StableHLO as MLIR location
debug info, through ``jax.vjp`` as ``jvp(...)`` / ``transpose(jvp(...))``
wrappers — so a layer's scope owns its forward AND backward instructions.

**Attribution (lowering only).**  :func:`analyze` walks the MLIR text of a
program already lowered by health.register_program — ``compiler_ir()``
serialization, never a compile; the established lowering-only discipline
(AOT ``.compile()`` does not share the jit call cache on this jax, and
deep mode stays behind ``MXNET_HEALTH_DEEP``).  Instructions are grouped
by innermost scope; per-scope FLOPs come from the op dims
(``dot_general``: 2·out·K from the contracting dims; ``convolution``:
2·out·Cin/g·kh·kw from ``dim_numbers``; elementwise ≈ 1/elem), bytes from
the operand/result tensor types.  Calls into deduplicated private funcs
are charged to the CALL SITE's scope (the shared body carries only its
first caller's location).  Known limits, documented in
docs/observability.md: control-flow region bodies (``while``/``reduce``)
count as one instruction of their scope, and the flop model is an
approximation of ``cost_analysis()`` — coverage is reported, not assumed.

Consumers: ``tools/program_atlas.py`` (CLI: ``--top-k``, ``--format
json``, ``--diff``, ``--smoke``), the ``/programz`` telemetry endpoint,
``bench.py --atlas``, and flight-recorder dumps.

Gate: ``MXNET_ATLAS`` (default on; analysis only runs inside
health.register_program, which is itself off by default).
"""
from __future__ import annotations

import re
import threading

from . import telemetry as _telemetry
from .base import get_env

__all__ = ["enabled", "GRAD_SYNC", "scope_name", "optimizer_scope",
           "analyze", "analyze_text", "atlases", "get", "snapshot",
           "diff", "reset", "ScopeStat", "ProgramAtlas"]

#: analysis gate (annotation is unconditional — named scopes are free).
enabled: bool = get_env("MXNET_ATLAS", True, bool)

_ATLAS_COVERAGE = _telemetry.gauge(
    "atlas_scope_coverage_pct",
    "share of a program's cost_analysis flops attributed to named scopes",
    ("program",))
_ATLAS_SCOPES = _telemetry.gauge(
    "atlas_scopes",
    "distinct named scopes attributed inside a registered program",
    ("program",))
_ATLAS_FAILURES = _telemetry.counter(
    "atlas_analyze_failures_total",
    "program lowerings the atlas parser could not attribute")

# --------------------------------------------------------------------------
# scope-name contract
# --------------------------------------------------------------------------
GRAD_SYNC = "GradSync"

_SAN_RE = re.compile(r"[^A-Za-z0-9_.\-~]")


def _sanitize(s):
    return _SAN_RE.sub("_", str(s)) or "_"


def scope_name(op_type, node_name="~"):
    """``<OpType>:<node_name>`` scope of one op application.

    ``~`` is the anonymous node of eager per-op entries (ops/registry.py),
    where no graph node name exists."""
    return "%s:%s" % (_sanitize(op_type), _sanitize(node_name))


def optimizer_scope(update_fn):
    """``Optimizer::<Name>`` scope of a (bound) fused_update stage."""
    owner = getattr(update_fn, "__self__", update_fn)
    name = None
    hook = getattr(owner, "atlas_scope_name", None)
    if callable(hook):
        try:
            name = hook()
        except Exception:
            name = None
    if not name:
        name = type(owner).__name__
    return "Optimizer::%s" % _sanitize(name)


# one regex, three alternatives, innermost (last) match wins: the token
# survives inside jvp(...)/transpose(jvp(...)) autodiff name wrappers
_SCOPE_TOKEN_RE = re.compile(
    r"Optimizer::[A-Za-z0-9_.\-~]+"
    r"|(?<![\w:])GradSync(?![\w:])"
    r"|[A-Za-z_][A-Za-z0-9_.\-]*:[A-Za-z0-9_.\-~]+")

# --------------------------------------------------------------------------
# MLIR location / type parsing
# --------------------------------------------------------------------------
_LOCDEF_RE = re.compile(r"^\s*#loc(\d*)\s*=\s*loc\((.*)\)\s*$")
_LOCREF_IN_DEF_RE = re.compile(r"#loc(\d*)")
_LOCREF_RE = re.compile(r"loc\((?:#loc(\d*)|unknown)\)\s*$")
_FUNC_RE = re.compile(r"func\.func\b[^@]*@([\w$.\-]+)")
_TYPE_RE = re.compile(r"tensor<((?:[^<>]|<[^<>]*>)*)>")
_CALLEE_RE = re.compile(r"@([\w$.\-]+)")
_RESULT_RE = re.compile(r"^\s*%[\w]+(?::\d+)?\s*=\s*(.*)$")
_OPNAME_RE = re.compile(r'^"?([A-Za-z_][\w.]*)"?')

_ITEMSIZE = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "f8E4M3FN": 1, "f8E5M2": 1, "f8E4M3FNUZ": 1, "f8E5M2FNUZ": 1,
    "i64": 8, "ui64": 8, "i32": 4, "ui32": 4, "i16": 2, "ui16": 2,
    "i8": 1, "ui8": 1, "i4": 1, "ui4": 1, "i1": 1, "pred": 1,
    "complex<f32>": 8, "complex<f64>": 16,
}

#: pure data movement / bookkeeping: bytes count, zero flops
_ZERO_FLOP = frozenset((
    "reshape", "transpose", "broadcast_in_dim", "broadcast", "slice",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "convert",
    "bitcast_convert", "constant", "iota", "reverse", "pad", "gather",
    "copy", "tuple", "get_tuple_element", "optimization_barrier",
    "custom_call", "after_all", "create_token", "rng_bit_generator",
    "return", "real", "imag", "composite", "all_gather", "collective_permute",
))

#: ops whose cost scales with the INPUT, not the output
_REDUCE_OPS = frozenset((
    "reduce", "reduce_window", "select_and_scatter", "sort", "scatter",
    "all_reduce", "reduce_scatter",
))


def _parse_type(text):
    """``"2x3xf32"`` -> ((2, 3), itemsize). Dynamic dims count as 1."""
    parts = text.split("x")
    dtype = parts[-1]
    dims = []
    for p in parts[:-1]:
        p = p.strip()
        dims.append(int(p) if p.isdigit() else 1)
    return tuple(dims), _ITEMSIZE.get(dtype.strip(), 4)


def _numel(dims):
    n = 1
    for d in dims:
        n *= d
    return n


def _paren_delta(line):
    """Net '(' depth change, ignoring parens inside string literals."""
    d, instr, i, n = 0, False, 0, len(line)
    while i < n:
        c = line[i]
        if instr:
            if c == "\\":
                i += 1
            elif c == '"':
                instr = False
        elif c == '"':
            instr = True
        elif c == "(":
            d += 1
        elif c == ")":
            d -= 1
        i += 1
    return d


def _brace_delta(line):
    d, instr, i, n = 0, False, 0, len(line)
    while i < n:
        c = line[i]
        if instr:
            if c == "\\":
                i += 1
            elif c == '"':
                instr = False
        elif c == '"':
            instr = True
        elif c == "{":
            d += 1
        elif c == "}":
            d -= 1
        i += 1
    return d


def _logical_lines(text):
    """Join physical lines until parens balance: a region op
    (``reduce``/``while`` ``({ ... })``) becomes ONE logical instruction
    attributed to the region's own scope."""
    out, buf, depth = [], "", 0
    for raw in text.splitlines():
        stripped = raw.strip()
        if not stripped:
            continue
        buf = (buf + " " + stripped) if buf else stripped
        depth += _paren_delta(stripped)
        if depth <= 0:
            out.append(buf)
            buf, depth = "", 0
    if buf:
        out.append(buf)
    return out


def _build_loc_scopes(text):
    """locid -> innermost scope token (or None) from the ``#locN = loc(...)``
    debug-info table; alias/callsite locs resolve through their refs."""
    raw = {}
    for line in text.splitlines():
        m = _LOCDEF_RE.match(line)
        if m:
            raw[m.group(1)] = m.group(2)
    memo = {}

    def resolve(lid, depth=0):
        if lid in memo:
            return memo[lid]
        memo[lid] = None  # cycle guard
        rhs = raw.get(lid)
        if rhs is None or depth > 8:
            return None
        toks = _SCOPE_TOKEN_RE.findall(rhs)
        if toks:
            memo[lid] = toks[-1]
            return memo[lid]
        for ref in _LOCREF_IN_DEF_RE.findall(rhs):
            if ref != lid:
                s = resolve(ref, depth + 1)
                if s is not None:
                    memo[lid] = s
                    return s
        return None

    return {lid: resolve(lid) for lid in raw}


def _split_funcs(lines):
    """Logical lines -> {func_name: [body lines]} in definition order."""
    funcs = {}
    order = []
    cur, body, depth = None, None, 0
    for ln in lines:
        if cur is None:
            m = _FUNC_RE.search(ln)
            if m and _brace_delta(ln) > 0:
                cur, body, depth = m.group(1), [], _brace_delta(ln)
            continue
        depth += _brace_delta(ln)
        if depth <= 0:
            funcs[cur] = body
            order.append(cur)
            cur, body = None, None
        else:
            body.append(ln)
    if cur is not None:
        funcs[cur] = body
        order.append(cur)
    return funcs, order


def _dot_flops(rest, ins, outs):
    m = (re.search(r"contracting_dims\s*=\s*\[([\d\s,]*)\]", rest)
         or re.search(r"lhs_contracting_dimensions\s*=\s*\[([\d\s,]*)\]",
                      rest))
    out_n = _numel(outs[0][0]) if outs else 0
    if not m or not ins:
        return 2.0 * out_n * (ins[0][0][-1] if ins and ins[0][0] else 1)
    lhs_dims = ins[0][0]
    k = 1
    for tok in m.group(1).split(","):
        tok = tok.strip()
        if tok.isdigit() and int(tok) < len(lhs_dims):
            k *= lhs_dims[int(tok)]
    return 2.0 * out_n * k


def _conv_flops(rest, ins, outs):
    out_n = _numel(outs[0][0]) if outs else 0
    m = re.search(r"x\[([^\]]*)\]\s*->", rest)
    if not m or len(ins) < 2:
        return float(out_n)
    rhs_spec = [t.strip() for t in m.group(1).split(",")]
    rhs_dims = ins[1][0]
    if len(rhs_spec) != len(rhs_dims):
        return float(out_n)
    k = 1
    for spec, d in zip(rhs_spec, rhs_dims):
        if spec != "o":  # kernel spatial dims AND the (per-group) i dim
            k *= d
    return 2.0 * out_n * k


def _op_cost(short, rest, ins, outs, n_operands):
    """(flops, bytes) of one instruction from its parsed types."""
    out_bytes = sum(_numel(d) * isz for d, isz in outs)
    if ins is None:  # elementwise shorthand: operands typed like the result
        in_bytes = out_bytes * n_operands
        ins_eff = [outs[0]] if outs else []
    else:
        in_bytes = sum(_numel(d) * isz for d, isz in ins)
        ins_eff = ins
    nbytes = out_bytes + in_bytes
    if short in _ZERO_FLOP:
        return 0.0, nbytes
    if short in ("dot_general", "dot"):
        return _dot_flops(rest, ins_eff, outs), nbytes
    if short == "convolution":
        return _conv_flops(rest, ins_eff, outs), nbytes
    if short in _REDUCE_OPS:
        n = _numel(ins_eff[0][0]) if ins_eff else 0
        return float(n), nbytes
    return float(_numel(outs[0][0]) if outs else 0), nbytes


def _parse_instr(ln):
    """One logical op line -> (short_op, callee, rest, ins, outs,
    n_operands, locid) or None for non-instructions."""
    m = _LOCREF_RE.search(ln)
    locid = m.group(1) if m and m.group(1) is not None else None
    body = ln[: m.start()].rstrip() if m else ln
    rm = _RESULT_RE.match(body)
    rest = rm.group(1) if rm else body.strip()
    om = _OPNAME_RE.match(rest)
    if not om:
        return None
    opname = om.group(1)
    short = opname.split(".")[-1]
    if short in ("func", "module", "return"):
        return None
    callee = None
    if short == "call":
        cm = _CALLEE_RE.search(rest)
        callee = cm.group(1) if cm else None
    # last " : " is the function-type signature (attr types like
    # ``1 : i64`` always precede it)
    parts = rest.rsplit(" : ", 1)
    ins = outs = None
    if len(parts) == 2:
        sig = parts[1]
        arrow = sig.rfind("->")
        if arrow >= 0:
            ins = [_parse_type(t) for t in _TYPE_RE.findall(sig[:arrow])]
            outs = [_parse_type(t) for t in _TYPE_RE.findall(sig[arrow:])]
        else:
            outs = [_parse_type(t) for t in _TYPE_RE.findall(sig)]
    n_operands = len(re.findall(r"%[A-Za-z0-9_]", parts[0]))
    return short, callee, parts[0], ins, outs or [], n_operands, locid


# --------------------------------------------------------------------------
# aggregation
# --------------------------------------------------------------------------
class ScopeStat:
    """Accumulated cost of one named scope inside one program."""

    __slots__ = ("scope", "flops", "bytes", "instructions", "calls")

    def __init__(self, scope):
        self.scope = scope
        self.flops = 0.0
        self.bytes = 0
        self.instructions = 0
        self.calls = 0

    def add(self, flops, nbytes, instructions=1, calls=0):
        self.flops += flops
        self.bytes += nbytes
        self.instructions += instructions
        self.calls += calls

    def as_dict(self):
        return {"scope": self.scope, "flops": self.flops,
                "bytes": self.bytes, "instructions": self.instructions,
                "calls": self.calls}


class _FuncSummary:
    """Per-function roll-up; private callee costs fold into call sites."""

    def __init__(self):
        self.by_scope = {}  # scope (str|None) -> ScopeStat

    def stat(self, scope):
        s = self.by_scope.get(scope)
        if s is None:
            s = self.by_scope[scope] = ScopeStat(scope)
        return s

    def merge(self, other):
        for scope, st in other.by_scope.items():
            self.stat(scope).add(st.flops, st.bytes, st.instructions,
                                 st.calls)

    def totals(self):
        f = b = i = 0
        for st in self.by_scope.values():
            f += st.flops
            b += st.bytes
            i += st.instructions
        return f, b, i


class ProgramAtlas:
    """Ranked per-scope attribution of one lowered program."""

    __slots__ = ("name", "total_flops", "parsed_flops", "scoped_flops",
                 "scopes", "unattributed", "n_instructions")

    def __init__(self, name, total_flops, by_scope):
        self.name = name
        self.scopes = {s: st for s, st in by_scope.items() if s is not None}
        self.unattributed = by_scope.get(None) or ScopeStat(None)
        self.scoped_flops = sum(st.flops for st in self.scopes.values())
        self.parsed_flops = self.scoped_flops + self.unattributed.flops
        # cost_analysis is the honest denominator when present; fall back
        # to the parsed total so standalone text analysis still ranks
        self.total_flops = float(total_flops or 0.0) or self.parsed_flops
        self.n_instructions = (self.unattributed.instructions
                               + sum(st.instructions
                                     for st in self.scopes.values()))

    def coverage(self):
        """Scoped share of the program's cost_analysis flops, in [0, ~1+]
        (the parsed model may slightly over/under-count vs XLA's)."""
        if self.total_flops <= 0:
            return 1.0 if not self.parsed_flops else 0.0
        return self.scoped_flops / self.total_flops

    def table(self, top_k=None):
        """Ranked rows (flops desc), shares against the program total."""
        denom_f = max(self.total_flops, self.parsed_flops, 1.0)
        denom_b = max(self.unattributed.bytes
                      + sum(st.bytes for st in self.scopes.values()), 1)
        rows = []
        for st in sorted(self.scopes.values(),
                         key=lambda s: (-s.flops, -s.bytes, s.scope)):
            d = st.as_dict()
            d["flops_share"] = st.flops / denom_f
            d["bytes_share"] = st.bytes / denom_b
            rows.append(d)
        return rows[:top_k] if top_k else rows

    def as_dict(self, top_k=None):
        return {"program": self.name,
                "total_flops": self.total_flops,
                "parsed_flops": self.parsed_flops,
                "scoped_flops": self.scoped_flops,
                "coverage_pct": round(100.0 * self.coverage(), 2),
                "n_scopes": len(self.scopes),
                "n_instructions": self.n_instructions,
                "unattributed": self.unattributed.as_dict(),
                "scopes": self.table(top_k)}


def analyze_text(name, asm, cost_flops=None):
    """Pure attribution of one MLIR module text (no jax imports): the
    testable core of :func:`analyze`."""
    loc_scopes = _build_loc_scopes(asm)
    funcs, order = _split_funcs(_logical_lines(asm))
    summaries = {}

    def summarize(fname, stack=()):
        if fname in summaries:
            return summaries[fname]
        if fname in stack or len(stack) > 16:
            return _FuncSummary()
        summary = _FuncSummary()
        for ln in funcs.get(fname, ()):
            parsed = _parse_instr(ln)
            if parsed is None:
                continue
            short, callee, rest, ins, outs, n_ops, locid = parsed
            scope = loc_scopes.get(locid) if locid is not None else None
            if short == "call" and callee in funcs:
                sub = summarize(callee, stack + (fname,))
                if scope is not None:
                    # dedup hazard: a shared private func body carries only
                    # its FIRST caller's locations — charge the call site
                    f, b, i = sub.totals()
                    summary.stat(scope).add(f, b, i, calls=1)
                else:
                    summary.merge(sub)
                    summary.stat(None).calls += 1
                continue
            flops, nbytes = _op_cost(short, rest, ins, outs, n_ops)
            summary.stat(scope).add(flops, nbytes)
        summaries[fname] = summary
        return summary

    entry = "main" if "main" in funcs else (order[0] if order else None)
    top = summarize(entry) if entry else _FuncSummary()
    return ProgramAtlas(name, cost_flops, top.by_scope)


# --------------------------------------------------------------------------
# program registry (fed by health.register_program)
# --------------------------------------------------------------------------
_atlases = {}
_atlases_lock = threading.Lock()


def analyze(name, lowered, cost_flops=None):
    """Attribute one ``jax.stages.Lowered`` and register the result.

    Serialization only — ``compiler_ir().operation.get_asm`` never
    touches XLA, so the zero-extra-compile contract of the health
    registration path holds.  Returns the :class:`ProgramAtlas` or None
    (disabled / unparsable — the atlas must never break registration)."""
    if not enabled:
        return None
    try:
        op = lowered.compiler_ir().operation
        try:
            asm = op.get_asm(enable_debug_info=True, large_elements_limit=16)
        except TypeError:
            asm = op.get_asm(enable_debug_info=True)
        if cost_flops is None:
            cost = lowered.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            cost_flops = float((cost or {}).get("flops", 0.0) or 0.0)
        atl = analyze_text(name, asm, cost_flops)
    except Exception:
        _ATLAS_FAILURES.inc()
        return None
    with _atlases_lock:
        _atlases[name] = atl
    _ATLAS_COVERAGE.labels(program=name).set(100.0 * atl.coverage())
    _ATLAS_SCOPES.labels(program=name).set(len(atl.scopes))
    return atl


def atlases():
    """Snapshot of every analyzed program's atlas."""
    with _atlases_lock:
        return dict(_atlases)


def get(name):
    with _atlases_lock:
        return _atlases.get(name)


def snapshot(top_k=None):
    """JSON-able {program: atlas dict} — the /programz payload shape."""
    return {n: a.as_dict(top_k) for n, a in sorted(atlases().items())}


def reset():
    """Test isolation: drop every analyzed program."""
    with _atlases_lock:
        _atlases.clear()


# --------------------------------------------------------------------------
# before/after diff (CLI --diff)
# --------------------------------------------------------------------------
def diff(a, b):
    """Per-scope flop/byte deltas between two :func:`snapshot` documents
    (``{program: {"scopes": [...], ...}}``), ranked by |delta flops| —
    the before/after attribution of a perf change.  Rows:
    ``{program, scope, flops_a, flops_b, delta_flops, delta_bytes}``."""
    rows = []
    for prog in sorted(set(a) | set(b)):
        sa = {r["scope"]: r for r in (a.get(prog) or {}).get("scopes", ())}
        sb = {r["scope"]: r for r in (b.get(prog) or {}).get("scopes", ())}
        for scope in sorted(set(sa) | set(sb)):
            ra, rb = sa.get(scope), sb.get(scope)
            fa = float(ra["flops"]) if ra else 0.0
            fb = float(rb["flops"]) if rb else 0.0
            ba = int(ra.get("bytes", 0)) if ra else 0
            bb = int(rb.get("bytes", 0)) if rb else 0
            if fa == fb and ba == bb:
                continue
            rows.append({"program": prog, "scope": scope,
                         "flops_a": fa, "flops_b": fb,
                         "delta_flops": fb - fa,
                         "delta_bytes": bb - ba})
    rows.sort(key=lambda r: (-abs(r["delta_flops"]),
                             -abs(r["delta_bytes"]),
                             r["program"], r["scope"]))
    return rows
