"""Pallas flash-attention: the blockwise inner loop of ring attention.

SURVEY.md §7.1 maps ring attention's hot loop to a hand-written Pallas
kernel.  ``parallel/ring_attention.py``'s building block is a
``lax.scan`` of (Q-block x K-block) updates; this module is the same
math — online-softmax with running max/sum — as ONE Pallas kernel per
(batch*head, Q-block): K/V live in VMEM, the K-block loop runs on-core,
scores/accumulators never touch HBM.  Numerics match the scan
formulation (f32 accumulation, running-max rescaling).

Backward (round 5): hand-written Pallas dq and dk/dv kernels — the
standard two-pass flash backward.  The forward saves the per-row
logsumexp ``lse = m + log(l)``; the backward recomputes probabilities
on-core as ``p = exp(s - lse)`` (no score materialization in HBM, same
as forward), computes ``delta = rowsum(dO * O)`` once in XLA, then:
  dv_j = sum_i p_ij dO_i          (dk/dv kernel: grid over KV blocks,
  dk_j = sum_i ds_ij q_i           loop over Q blocks)
  dq_i = sum_j ds_ij k_j          (dq kernel: grid over Q blocks,
                                   loop over KV blocks)
with ``ds = p * (dp - delta) * scale``, ``dp = dO v^T``.  Both
directions now run fused kernels — the reference's cuDNN precedent is
fused-both-directions (/root/reference/src/operator/cudnn_rnn-inl.h:1).

Used by ``parallel/ring_attention.blockwise_attention`` on TPU when
``MXNET_TPU_PALLAS_ATTN`` != "0" and K/V fit VMEM; larger shapes fall
back to the scan.  Reference analog: none (the 2018 reference predates
flash attention); ref for the surrounding design: SURVEY.md §5.7.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["flash_attention", "flash_attention_available",
           "flash_attention_stats", "flash_attention_bwd"]

INTERPRET = False


def flash_attention_available(B, H, Tq, Tk, D, dtype=None) -> bool:
    """SIZE/ENV eligibility only — would the kernel compile on a TPU.

    No platform check here: callers resolve TPU-vs-other at LOWERING time
    via ``jax.lax.platform_dependent`` (parallel/ring_attention.py), so
    CPU-committed arrays on a TPU host lower the scan formulation instead
    of Mosaic (advisor r03)."""
    if os.environ.get("MXNET_TPU_PALLAS_ATTN", "1") == "0":
        return False
    if D % 8 or Tq % 8 or Tk % 128:
        return False
    if not INTERPRET and Tk < 2048:
        # measured crossover (tools/bench_ring_attention.py ring rows,
        # B=1 H=8 D=128 bf16): XLA's fused scan hits ~89 TF at Tk=1024
        # and beats the kernel 4x; the kernel wins ~2x from Tk=2048 up to
        # the VMEM envelope below
        return False
    # K+V resident in VMEM per (b,h) program, double-buffered by the
    # pipeline.  Measured crossover (tools/bench_ring_attention.py):
    # the kernel wins 1.9x while K/V stream from VMEM comfortably
    # (T=4096/D=128), loses once the resident set crowds the 16 MB
    # scoped-vmem limit (T=8192: 0.84x; T=16384: compile failure) —
    # larger shapes use the HBM-blocked lax.scan formulation instead.
    esize = jnp.dtype(dtype).itemsize if dtype is not None else 2
    kv_bytes = 2 * Tk * D * esize
    return 2 * kv_bytes <= 5 * 1024 * 1024


def _online_softmax_loop(q_ref, k_ref, v_ref, *, TQ, BK, Tk, causal,
                         scale):
    """Shared kernel body: the online-softmax K-block loop, returning the
    running (m, l, acc) — finalized differently by the normalized-output
    kernel and the stats-emitting ring kernel."""
    qi = pl.program_id(1)
    qb = q_ref[0]                                    # (TQ, D)
    D = qb.shape[-1]

    m0 = jnp.full((TQ,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((TQ,), jnp.float32)
    a0 = jnp.zeros((TQ, D), jnp.float32)

    q_pos = qi * TQ + jax.lax.broadcasted_iota(jnp.int32, (TQ, BK), 0)

    def body(i, carry):
        m, l, acc = carry
        kblk = k_ref[0, pl.ds(i * BK, BK), :]        # (BK, D)
        vblk = v_ref[0, pl.ds(i * BK, BK), :]
        s = jax.lax.dot_general(
            qb, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (TQ, BK)
        if causal:
            k_pos = i * BK + jax.lax.broadcasted_iota(
                jnp.int32, (TQ, BK), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_cur)
        # guard fully-masked rows: exp(-inf - (-inf)) -> use finite base
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[:, None])
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l2 = l * alpha + jnp.sum(p, axis=-1)
        acc2 = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(vblk.dtype), vblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l2, acc2

    return jax.lax.fori_loop(0, Tk // BK, body, (m0, l0, a0))


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, TQ, BK, Tk, causal,
                  scale, q_chunk_count):
    m, l, acc = _online_softmax_loop(q_ref, k_ref, v_ref, TQ=TQ, BK=BK,
                                     Tk=Tk, causal=causal, scale=scale)
    o_ref[0] = (acc / jnp.maximum(l, 1e-37)[:, None]).astype(o_ref.dtype)


def _out_sds(shape, dtype, like):
    """ShapeDtypeStruct for a pallas_call output, inheriting the caller's
    varying-mesh-axes set — required when the kernel runs inside
    shard_map (the ring-attention per-shard pass)."""
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=jax.typeof(like).vma)
    except (AttributeError, TypeError):
        return jax.ShapeDtypeStruct(shape, dtype)


def _pick_blocks(Tq, Tk, block_q, block_k):
    TQ = min(block_q, Tq)
    while Tq % TQ:
        TQ //= 2
    BK = min(block_k, Tk)
    while Tk % BK:
        BK //= 2
    return TQ, BK


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    BH = B * H
    q3 = q.reshape(BH, Tq, D)
    k3 = k.reshape(BH, Tk, D)
    v3 = v.reshape(BH, Tk, D)
    TQ, BK = _pick_blocks(Tq, Tk, block_q, block_k)

    kern = functools.partial(
        _flash_kernel, TQ=TQ, BK=BK, Tk=Tk, causal=causal, scale=scale,
        q_chunk_count=Tq // TQ)
    out = pl.pallas_call(
        kern,
        grid=(BH, Tq // TQ),
        in_specs=[
            pl.BlockSpec((1, TQ, D), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, t: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, TQ, D), lambda b, t: (b, t, 0)),
        out_shape=_out_sds((BH, Tq, D), q.dtype, q),
        interpret=INTERPRET,
    )(q3, k3, v3)
    return out.reshape(B, H, Tq, D)


def _flash_stats_kernel(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, *,
                        TQ, BK, Tk, causal, scale):
    """_flash_kernel's loop, but emitting the UNNORMALIZED accumulator
    and the online-softmax stats (m, l) instead of the normalized output
    — the building block for cross-shard merging in ring attention (each
    ring step computes local stats on the resident K/V shard; the exact
    combine happens outside in XLA)."""
    m, l, acc = _online_softmax_loop(q_ref, k_ref, v_ref, TQ=TQ, BK=BK,
                                     Tk=Tk, causal=causal, scale=scale)
    acc_ref[0] = acc
    # stats are lane-replicated to a trailing 128 dim: Mosaic requires the
    # last two block dims to be (8k, 128k)-aligned, and a (1, TQ) block
    # is not; callers read lane 0
    m_ref[0] = jnp.broadcast_to(m[:, None], (TQ, 128))
    l_ref[0] = jnp.broadcast_to(l[:, None], (TQ, 128))


def flash_attention_stats(q, k, v, causal, scale, block_q=512,
                          block_k=512):
    """Per-shard flash pass returning (acc, m, l) in f32: acc is the
    UNNORMALIZED output accumulator, (m, l) the online-softmax running
    max/sum.  Exact cross-shard merge (ring attention):

        m' = max(m_a, m_b);  l' = l_a*e^{m_a-m'} + l_b*e^{m_b-m'}
        acc' = acc_a*e^{m_a-m'} + acc_b*e^{m_b-m'};  out = acc'/l'
    """
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    BH = B * H
    TQ, BK = _pick_blocks(Tq, Tk, block_q, block_k)
    kern = functools.partial(_flash_stats_kernel, TQ=TQ, BK=BK, Tk=Tk,
                             causal=causal, scale=scale)
    acc, m, l = pl.pallas_call(
        kern,
        grid=(BH, Tq // TQ),
        in_specs=[
            pl.BlockSpec((1, TQ, D), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, t: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, TQ, D), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, TQ, 128), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, TQ, 128), lambda b, t: (b, t, 0)),
        ],
        out_shape=[
            _out_sds((BH, Tq, D), jnp.float32, q),
            _out_sds((BH, Tq, 128), jnp.float32, q),
            _out_sds((BH, Tq, 128), jnp.float32, q),
        ],
        interpret=INTERPRET,
    )(q.reshape(BH, Tq, D), k.reshape(BH, Tk, D), v.reshape(BH, Tk, D))
    return (acc.reshape(B, H, Tq, D), m[..., 0].reshape(B, H, Tq),
            l[..., 0].reshape(B, H, Tq))


def lse_of(m, l):
    """logsumexp from online-softmax stats; +inf for fully-masked rows so
    the backward's ``p = exp(s - lse)`` is exactly 0 there."""
    return jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-37)), jnp.inf)


def pack_stats(lse, delta):
    """(…,T) lse/delta -> one (…,T,128) f32 array for kernel input: lane 0
    is lse, lane 1 is delta.  Mosaic wants the last two block dims
    (8k, 128k)-aligned, so per-row scalars ride a 128-lane vector; packing
    both into one array halves the HBM traffic vs two broadcasts."""
    st = jnp.stack([lse, delta], axis=-1).astype(jnp.float32)
    return jnp.pad(st, [(0, 0)] * (st.ndim - 1) + [(0, 126)])


def _flash_fwd_lse(q, k, v, causal, scale, block_q, block_k):
    """Forward emitting (out, lse) — the residual-producing pass for the
    custom VJP.  Same online-softmax loop; lse = m + log(l)."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    BH = B * H
    TQ, BK = _pick_blocks(Tq, Tk, block_q, block_k)

    def kern(q_ref, k_ref, v_ref, o_ref, lse_ref):
        m, l, acc = _online_softmax_loop(q_ref, k_ref, v_ref, TQ=TQ,
                                         BK=BK, Tk=Tk, causal=causal,
                                         scale=scale)
        o_ref[0] = (acc / jnp.maximum(l, 1e-37)[:, None]).astype(
            o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(lse_of(m, l)[:, None], (TQ, 128))

    out, lse = pl.pallas_call(
        kern,
        grid=(BH, Tq // TQ),
        in_specs=[
            pl.BlockSpec((1, TQ, D), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, t: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, TQ, D), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, TQ, 128), lambda b, t: (b, t, 0)),
        ],
        out_shape=[
            _out_sds((BH, Tq, D), q.dtype, q),
            _out_sds((BH, Tq, 128), jnp.float32, q),
        ],
        interpret=INTERPRET,
    )(q.reshape(BH, Tq, D), k.reshape(BH, Tk, D), v.reshape(BH, Tk, D))
    return (out.reshape(B, H, Tq, D),
            lse[..., 0].reshape(B, H, Tq))


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, st_ref, dq_ref, *, TQ, BK,
               Tk, causal, scale):
    """dq for one Q block: loop over KV blocks, recompute p from lse,
    accumulate ds @ K in f32.  Causal: the loop stops at the last block
    that intersects the diagonal (traced upper bound)."""
    qi = pl.program_id(1)
    qb = q_ref[0]                                    # (TQ, D)
    dob = do_ref[0]
    D = qb.shape[-1]
    lse = st_ref[0, :, 0:1]                          # (TQ, 1)
    delta = st_ref[0, :, 1:2]
    q_pos = qi * TQ + jax.lax.broadcasted_iota(jnp.int32, (TQ, BK), 0)

    def body(i, dq):
        kblk = k_ref[0, pl.ds(i * BK, BK), :]
        vblk = v_ref[0, pl.ds(i * BK, BK), :]
        s = jax.lax.dot_general(
            qb, kblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # (TQ, BK)
        p = jnp.exp(s - lse)
        if causal:
            k_pos = i * BK + jax.lax.broadcasted_iota(
                jnp.int32, (TQ, BK), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = jax.lax.dot_general(
            dob, vblk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # (TQ, BK)
        ds = (p * (dp - delta) * scale).astype(kblk.dtype)
        return dq + jax.lax.dot_general(
            ds, kblk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    n_blocks = Tk // BK
    if causal:
        n_blocks = jnp.minimum(n_blocks,
                               (qi * TQ + TQ + BK - 1) // BK)
    dq_ref[0] = jax.lax.fori_loop(
        0, n_blocks, body, jnp.zeros((TQ, D), jnp.float32))


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, st_ref, dk_ref, dv_ref, *,
                TQ, BK, Tq, causal, scale):
    """dk/dv for one KV block: loop over Q blocks.  Causal: start at the
    first Q block that can see this KV block (traced lower bound)."""
    ki = pl.program_id(1)
    kb = k_ref[0]                                    # (BK, D)
    vb = v_ref[0]
    D = kb.shape[-1]
    k_pos = ki * BK + jax.lax.broadcasted_iota(jnp.int32, (TQ, BK), 1)

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, pl.ds(i * TQ, TQ), :]
        dob = do_ref[0, pl.ds(i * TQ, TQ), :]
        lse = st_ref[0, pl.ds(i * TQ, TQ), 0:1]
        delta = st_ref[0, pl.ds(i * TQ, TQ), 1:2]
        s = jax.lax.dot_general(
            qb, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # (TQ, BK)
        p = jnp.exp(s - lse)
        if causal:
            q_pos = i * TQ + jax.lax.broadcasted_iota(
                jnp.int32, (TQ, BK), 0)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dv = dv + jax.lax.dot_general(
            p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (BK, D)
        dp = jax.lax.dot_general(
            dob, vb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # (TQ, BK)
        ds = (p * (dp - delta) * scale).astype(qb.dtype)
        dk = dk + jax.lax.dot_general(
            ds, qb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (BK, D)
        return dk, dv

    lo = (ki * BK) // TQ if causal else 0
    dk, dv = jax.lax.fori_loop(
        lo, Tq // TQ, body,
        (jnp.zeros((BK, D), jnp.float32), jnp.zeros((BK, D), jnp.float32)))
    dk_ref[0] = dk
    dv_ref[0] = dv


def flash_attention_bwd(q, k, v, do, lse, delta, causal, scale,
                        block_q=512, block_k=512):
    """Pallas flash backward: (dq, dk, dv) in f32 (callers accumulating
    across ring steps keep full precision; standalone callers cast).

    q/k/v/do: [B,H,T,D]; lse/delta: [B,H,Tq] f32 (global logsumexp and
    rowsum(dO*O) — for ring attention these are the FULL-sequence stats,
    making each per-shard call an exact partial contribution)."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    BH = B * H
    TQ, BK = _pick_blocks(Tq, Tk, block_q, block_k)
    st = pack_stats(lse, delta).reshape(BH, Tq, 128)
    q3 = q.reshape(BH, Tq, D)
    k3 = k.reshape(BH, Tk, D)
    v3 = v.reshape(BH, Tk, D)
    do3 = do.reshape(BH, Tq, D)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, TQ=TQ, BK=BK, Tk=Tk, causal=causal,
                          scale=scale),
        grid=(BH, Tq // TQ),
        in_specs=[
            pl.BlockSpec((1, TQ, D), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, Tk, D), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, TQ, D), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, TQ, 128), lambda b, t: (b, t, 0)),
        ],
        out_specs=pl.BlockSpec((1, TQ, D), lambda b, t: (b, t, 0)),
        out_shape=_out_sds((BH, Tq, D), jnp.float32, q),
        interpret=INTERPRET,
    )(q3, k3, v3, do3, st)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, TQ=TQ, BK=BK, Tq=Tq, causal=causal,
                          scale=scale),
        grid=(BH, Tk // BK),
        in_specs=[
            pl.BlockSpec((1, Tq, D), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, BK, D), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, BK, D), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, Tq, D), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, Tq, 128), lambda b, t: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, BK, D), lambda b, t: (b, t, 0)),
            pl.BlockSpec((1, BK, D), lambda b, t: (b, t, 0)),
        ],
        out_shape=[
            _out_sds((BH, Tk, D), jnp.float32, q),
            _out_sds((BH, Tk, D), jnp.float32, q),
        ],
        interpret=INTERPRET,
    )(q3, k3, v3, do3, st)
    shp = (B, H, Tq, D)
    return (dq.reshape(shp), dk.reshape(B, H, Tk, D),
            dv.reshape(B, H, Tk, D))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, scale=None, block_q=512,
                    block_k=512):
    """[B,H,T,D] attention; Pallas kernels both directions."""
    sc = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    return _flash_fwd(q, k, v, causal, sc, block_q, block_k)


def _fa_vjp_fwd(q, k, v, causal, scale, block_q, block_k):
    sc = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    out, lse = _flash_fwd_lse(q, k, v, causal, sc, block_q, block_k)
    return out, (q, k, v, out, lse)


def _fa_vjp_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    sc = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    dq, dk, dv = flash_attention_bwd(q, k, v, g, lse, delta, causal, sc,
                                     block_q, block_k)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


flash_attention.defvjp(_fa_vjp_fwd, _fa_vjp_bwd)
