"""Control-flow operator: ``_foreach``.

Reference analog: ``src/operator/control_flow.cc:483`` (the ``_foreach`` op:
runs a subgraph over axis 0 of the scan inputs, threading loop states) with
Python front-ends ``mx.nd.contrib.foreach`` / ``mx.sym.contrib.foreach``
(python/mxnet/{ndarray,symbol}/contrib.py:101,157).

TPU-native design: the symbolic form lowers to ``lax.scan`` — the XLA-native
loop primitive — with the body subgraph traced once through the executor's
graph plan (no per-iteration dispatch, unlike the reference's CachedOp-per-
step execution).  The subgraph travels in the node attrs as symbol JSON so
graphs containing ``_foreach`` stay JSON-serializable like the reference's.
"""
from __future__ import annotations

import ast
import functools

import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register, param


@functools.lru_cache(maxsize=64)
def _load_plan(subgraph_json: str, train: bool):
    from ..symbol.symbol import load_json
    from ..executor import _Plan
    return _Plan(load_json(subgraph_json), train=train)


def _names(attrs, key):
    v = attrs.get(key, ())
    if isinstance(v, str):
        v = tuple(ast.literal_eval(v))
    return tuple(v)


@register("_foreach", nin=-1, train_aware=True,
          nout=lambda attrs: int(attrs["num_outputs"]),
          params={"num_data": param(int, 1),
                  "num_states": param(int, 0),
                  "num_out_data": param(int, 1),
                  "num_outputs": param(int, 1)})
def _foreach(attrs, *arrays):
    """Scan the body subgraph over axis 0 of the data inputs.

    Inputs: [data..., init_states..., free_vars...]; outputs:
    [stacked out_data..., final_states...].
    """
    nd_, ns = attrs["num_data"], attrs["num_states"]
    n_out_data = attrs["num_out_data"]
    data = arrays[:nd_]
    states = tuple(arrays[nd_:nd_ + ns])
    free = arrays[nd_ + ns:]
    data_names = _names(attrs, "data_names")
    state_names = _names(attrs, "state_names")
    free_names = _names(attrs, "free_names")
    if len(free) != len(free_names):
        raise MXNetError("_foreach: free-variable count mismatch (%d vs %d)"
                         % (len(free), len(free_names)))
    plan = _load_plan(attrs["subgraph"], bool(attrs.get("__train__", False)))
    if plan.n_rng:
        raise MXNetError("_foreach: random ops inside the loop body are not "
                         "supported yet")
    free_vals = dict(zip(free_names, free))

    def step(carry, xs):
        arg_vals = dict(zip(data_names, xs))
        arg_vals.update(zip(state_names, carry))
        arg_vals.update(free_vals)
        outs, _ = plan.execute(arg_vals, {}, keys=None)
        return tuple(outs[n_out_data:]), tuple(outs[:n_out_data])

    final_states, stacked = lax.scan(step, states, tuple(data))
    out = tuple(stacked) + tuple(final_states)
    return out if len(out) > 1 else out[0]
