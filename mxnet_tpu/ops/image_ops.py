"""Image operators (``_image_*``).

Reference analog: ``src/operator/image/image_random.cc`` (the ``mx.nd.image``
namespace backing gluon.data.vision.transforms): ``_image_to_tensor``,
``_image_normalize``.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .registry import register, param


@register("_image_to_tensor", nin=1, aliases=("to_tensor",))
def _image_to_tensor(attrs, data):
    """HWC (or NHWC) uint8 [0,255] -> CHW (NCHW) float32 [0,1)
    (image_random.cc ToTensor)."""
    x = data.astype(jnp.float32) / 255.0
    if data.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


@register("_image_normalize", nin=1, aliases=("normalize",),
          params={"mean": param("floats", (0.0,)),
                  "std": param("floats", (1.0,))})
def _image_normalize(attrs, data):
    """Channel-wise normalization of a CHW / NCHW float tensor
    (image_random.cc Normalize)."""
    c_axis = 0 if data.ndim == 3 else 1
    shape = [1] * data.ndim
    shape[c_axis] = -1
    mean = jnp.asarray(np.asarray(attrs["mean"], np.float32)).reshape(shape)
    std = jnp.asarray(np.asarray(attrs["std"], np.float32)).reshape(shape)
    return (data - mean) / std
