"""Partial shape inference hints + input names for parameter-bearing ops.

Reference analog: per-op ``FInferShape`` functions (e.g. ``ConvolutionShape``
in src/operator/nn/convolution.cc) which *fill in* weight/bias shapes from the
data shape so ``simple_bind`` can allocate parameters automatically, and
``FListInputNames`` which names them (data/weight/bias...) for
``list_arguments``.  TPU-native: full-output inference is jax.eval_shape; only
the backward "fill the unknown param shapes" step needs these hints.
"""
from __future__ import annotations

import numpy as np

from .registry import OPS


def _conv_hint(attrs, shapes):
    data = shapes[0]
    if data is None:
        return shapes
    k = attrs["kernel"]
    nf, g = attrs["num_filter"], attrs["num_group"]
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (nf, data[1] // g) + tuple(k)
    if len(out) > 2 and out[2] is None and not attrs["no_bias"]:
        out[2] = (nf,)
    return out


def _deconv_hint(attrs, shapes):
    data = shapes[0]
    if data is None:
        return shapes
    k = attrs["kernel"]
    nf, g = attrs["num_filter"], attrs["num_group"]
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (data[1], nf // g) + tuple(k)
    if len(out) > 2 and out[2] is None and not attrs["no_bias"]:
        out[2] = (nf,)
    return out


def _fc_hint(attrs, shapes):
    data = shapes[0]
    if data is None:
        return shapes
    nh = attrs["num_hidden"]
    in_dim = int(np.prod(data[1:])) if attrs.get("flatten", True) else data[-1]
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (nh, in_dim)
    if len(out) > 2 and out[2] is None and not attrs["no_bias"]:
        out[2] = (nh,)
    return out


def _channel_hint(axis_attr=None, default_axis=1, n_params=None):
    def hint(attrs, shapes):
        data = shapes[0]
        if data is None:
            return shapes
        ax = attrs.get(axis_attr, default_axis) if axis_attr else default_axis
        c = data[ax % len(data)]
        out = list(shapes)
        for i in range(1, len(out)):
            if out[i] is None:
                out[i] = (c,)
        return out
    return hint


def _embedding_hint(attrs, shapes):
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (attrs["input_dim"], attrs["output_dim"])
    return out


def _mha_hint(attrs, shapes):
    """MultiHeadAttention: all four projection weights are square
    (model_dim, model_dim) in the FullyConnected (out, in) orientation."""
    data = shapes[0]
    if data is None:
        return shapes
    D = data[-1]
    out = list(shapes)
    for i in range(1, len(out)):
        if out[i] is None:
            out[i] = (D, D)
    return out


def _rnn_hint(attrs, shapes):
    """RNN: packed parameter size + state shapes from the TNC data shape
    (reference rnn-inl.h RNNShape/GetParamSize)."""
    data = shapes[0]
    if data is None:
        return shapes
    from .rnn import rnn_param_size
    h, L = attrs["state_size"], attrs["num_layers"]
    bi = attrs["bidirectional"]
    dirs = 2 if bi else 1
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (rnn_param_size(L, h, data[2], bi, attrs["mode"]),)
    for i in (2, 3):
        if len(out) > i and out[i] is None:
            out[i] = (L * dirs, data[1], h)
    return out


def _softmax_label_hint(attrs, shapes):
    """SoftmaxOutput: label = data shape minus the class dim."""
    data = shapes[0]
    if data is None:
        return shapes
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        if attrs.get("multi_output"):
            out[1] = (data[0],) + tuple(data[2:])
        else:
            out[1] = (data[0],)
    return out


def _label_like_hint(attrs, shapes):
    """Regression outputs: label shape defaults to data shape."""
    data = shapes[0]
    if data is None:
        return shapes
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = data
    return out


def install():
    cfg = {
        "Convolution": (("data", "weight", "bias"), (), _conv_hint),
        "Deconvolution": (("data", "weight", "bias"), (), _deconv_hint),
        "FullyConnected": (("data", "weight", "bias"), (), _fc_hint),
        "BatchNorm": (("data", "gamma", "beta", "moving_mean", "moving_var"),
                      (3, 4), _channel_hint("axis", 1)),
        "LayerNorm": (("data", "gamma", "beta"), (),
                      _channel_hint("axis", -1)),
        "InstanceNorm": (("data", "gamma", "beta"), (), _channel_hint()),
        "Embedding": (("data", "weight"), (), _embedding_hint),
        "MultiHeadAttention": (("data", "query_weight", "key_weight",
                                "value_weight", "out_proj_weight"), (),
                               _mha_hint),
        "LeakyReLU": (("data", "gamma"), (), _channel_hint()),
        "RNN": (("data", "parameters", "state", "state_cell"), (),
                _rnn_hint),
        "SoftmaxOutput": (("data", "label"), (), _softmax_label_hint),
        "LinearRegressionOutput": (("data", "label"), (), _label_like_hint),
        "LogisticRegressionOutput": (("data", "label"), (), _label_like_hint),
        "MAERegressionOutput": (("data", "label"), (), _label_like_hint),
        "softmax_cross_entropy": (("data", "label"), (), _label_like_hint),
        "SequenceMask": (("data", "sequence_length"), (), None),
        "SequenceLast": (("data", "sequence_length"), (), None),
        "SequenceReverse": (("data", "sequence_length"), (), None),
        "dot": (("lhs", "rhs"), (), None),
        "batch_dot": (("lhs", "rhs"), (), None),
        "broadcast_add": (("lhs", "rhs"), (), None),
        "broadcast_sub": (("lhs", "rhs"), (), None),
        "broadcast_mul": (("lhs", "rhs"), (), None),
        "broadcast_div": (("lhs", "rhs"), (), None),
    }
    for name, (arg_names, aux, hint) in cfg.items():
        op = OPS.get(name)
        if op is None:
            continue
        op.arg_names = list(arg_names)
        op.aux_inputs = tuple(aux)
        if hint is not None:
            op.shape_hint = hint


install()
