"""Reduction / broadcast-shape / ordering operators.

Reference analog: ``src/operator/tensor/broadcast_reduce_op*.{cc,cu}`` and
``ordering_op.cc`` (topk/sort/argsort).  XLA lowers reductions onto the VPU
with tree reductions; no hand kernels needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, param
from ..base import MXNetError

_REDUCE_PARAMS = {
    "axis": param("shape", None),
    "keepdims": param(bool, False),
    "exclude": param(bool, False),
}


def _resolve_axes(attrs, ndim):
    axis = attrs["axis"]
    if axis is None or axis == ():
        axes = tuple(range(ndim))
    else:
        axes = tuple(a % ndim for a in axis)
    if attrs.get("exclude"):
        axes = tuple(a for a in range(ndim) if a not in axes)
    return axes


def _make_reduce(jfn):
    def fn(attrs, x):
        axes = _resolve_axes(attrs, x.ndim)
        return jfn(x, axis=axes, keepdims=attrs["keepdims"])
    return fn


for _name, _jf in {
    "sum": jnp.sum, "mean": jnp.mean, "prod": jnp.prod,
    "nansum": jnp.nansum, "nanprod": jnp.nanprod,
    "max": jnp.max, "min": jnp.min,
}.items():
    register(_name, params=dict(_REDUCE_PARAMS), nin=1,
             aliases=(_name + "_axis",) if _name in ("sum", "max", "min")
                     else ())(
        _make_reduce(_jf))


@register("norm", nin=1, params={"ord": param(int, 2),
                                 "axis": param("shape", None),
                                 "keepdims": param(bool, False)})
def _norm(attrs, x):
    axis = attrs["axis"]
    axes = tuple(a % x.ndim for a in axis) if axis else None
    if attrs["ord"] == 1:
        return jnp.sum(jnp.abs(x), axis=axes, keepdims=attrs["keepdims"])
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=attrs["keepdims"]))


def _make_arg_reduce(jfn):
    def fn(attrs, x):
        axis = attrs["axis"]
        if axis is None:
            # reference semantics: flatten, return float index
            r = jfn(x.reshape(-1), axis=0)
            out = r.astype(jnp.float32)
            return out.reshape((1,)) if attrs["keepdims"] else out
        return jfn(x, axis=int(axis[0]),
                   keepdims=attrs["keepdims"]).astype(jnp.float32)
    return fn


for _name, _jf in {"argmax": jnp.argmax, "argmin": jnp.argmin}.items():
    register(_name, nin=1, params={"axis": param("shape", None),
                                   "keepdims": param(bool, False)})(
        _make_arg_reduce(_jf))

register("argmax_channel", nin=1)(
    lambda attrs, x: jnp.argmax(x, axis=1).astype(jnp.float32))


# --------------------------------------------------------------------------
# broadcast-shape ops
# --------------------------------------------------------------------------
@register("broadcast_to", nin=1, params={"shape": param("shape", ())})
def _broadcast_to(attrs, x):
    tgt = tuple(s if t == 0 else t for s, t in zip(x.shape, attrs["shape"]))
    return jnp.broadcast_to(x, tgt)


@register("broadcast_axis", nin=1, aliases=("broadcast_axes",),
          params={"axis": param("shape", ()), "size": param("shape", ())})
def _broadcast_axis(attrs, x):
    tgt = list(x.shape)
    for a, s in zip(attrs["axis"], attrs["size"]):
        tgt[a % x.ndim] = s
    return jnp.broadcast_to(x, tuple(tgt))


@register("broadcast_like", nin=2)
def _broadcast_like(attrs, x, like):
    return jnp.broadcast_to(x, like.shape)


# --------------------------------------------------------------------------
# ordering ops (ref: src/operator/tensor/ordering_op.cc)
# --------------------------------------------------------------------------
_TOPK_PARAMS = {
    "axis": param("shape", (-1,)),
    "k": param(int, 1),
    "ret_typ": param(["value", "indices", "mask", "both"], "indices"),
    "is_ascend": param(bool, False),
    "dtype": param("dtype", "float32"),
}


@register("topk", nin=1, params=dict(_TOPK_PARAMS),
          nout=lambda attrs: 2 if attrs["ret_typ"] == "both" else 1)
def _topk(attrs, x):
    axis = int(attrs["axis"][0]) % x.ndim if attrs["axis"] else x.ndim - 1
    k = attrs["k"] if attrs["k"] > 0 else x.shape[axis]
    xs = -x if not attrs["is_ascend"] else x
    idx = jnp.argsort(xs, axis=axis)
    idx = jax.lax.slice_in_dim(idx, 0, k, axis=axis)
    vals = jnp.take_along_axis(x, idx, axis=axis)
    rt = attrs["ret_typ"]
    idt = np.dtype(attrs["dtype"] or "float32")
    if rt == "value":
        return vals
    if rt == "indices":
        return idx.astype(idt)
    if rt == "mask":
        mask = jnp.zeros_like(x)
        return jnp.put_along_axis(mask, idx, 1.0, axis=axis, inplace=False)
    return vals, idx.astype(idt)


@register("sort", nin=1, params={"axis": param("shape", (-1,)),
                                 "is_ascend": param(bool, True)})
def _sort(attrs, x):
    axis = int(attrs["axis"][0]) if attrs["axis"] else -1
    s = jnp.sort(x, axis=axis)
    return s if attrs["is_ascend"] else jnp.flip(s, axis=axis)


@register("argsort", nin=1, params={"axis": param("shape", (-1,)),
                                    "is_ascend": param(bool, True),
                                    "dtype": param("dtype", "float32")})
def _argsort(attrs, x):
    axis = int(attrs["axis"][0]) if attrs["axis"] else -1
    xs = x if attrs["is_ascend"] else -x
    return jnp.argsort(xs, axis=axis).astype(np.dtype(attrs["dtype"] or "float32"))
