"""Detection contrib ops: multibox SSD trio, bounding-box ops, RCNN family.

Reference analogs (`src/operator/contrib/`, SURVEY.md N7 contrib/):

- ``_contrib_MultiBoxPrior`` — multibox_prior.cc:31-72 (anchor layout: per
  pixel, ``num_sizes`` anchors at ratio 1 then ``num_ratios-1`` at size[0]).
- ``_contrib_MultiBoxTarget`` — multibox_target.cc:80-280 (bipartite match,
  threshold match, negative mining, variance-encoded loc targets).
- ``_contrib_MultiBoxDetection`` — multibox_detection.cc:44-170 (decode +
  per-class greedy NMS, output rows ``[id, score, xmin, ymin, xmax, ymax]``).
- ``_contrib_box_nms`` / ``_contrib_box_iou`` / ``_contrib_bipartite_matching``
  — bounding_box-inl.h:55-90,560-700.
- ``_contrib_Proposal`` / ``_contrib_MultiProposal`` — proposal-inl.h:60-90,
  multi_proposal-inl.h (RPN proposal generation + NMS).
- ``ROIPooling`` — roi_pooling-inl.h:50-60; ``_contrib_ROIAlign`` —
  roi_align-inl.h:50-60; ``_contrib_PSROIPooling`` — psroi_pooling-inl.h:55-65;
  ``_contrib_DeformableConvolution`` — deformable_convolution-inl.h:70-90;
  ``_contrib_DeformablePSROIPooling`` — deformable_psroi_pooling-inl.h:60-74.

TPU-native design: every data-dependent-size loop of the reference (greedy
NMS, bipartite matching, per-roi bin loops) is re-expressed as fixed-shape
masked tensor programs — sorts + ``lax.fori_loop`` with vectorized suppression
for NMS (padded outputs with -1 rows, the convention the reference already
uses), one-hot/gather bilinear sampling for the ROI/deformable family so the
inner products ride the MXU, and ``vmap`` over batch/roi instead of host
loops.  Gradients (where defined: ROI/deformable/resize ops) come from
``jax.vjp`` of these definitions; detection-target ops are non-differentiable
(reference writes zero gradients) and are marked ``stop_gradient``.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register, param
from ._sampling import bilinear_sample as _bilinear_sample

BIG_NEG = -1e30


# ---------------------------------------------------------------------------
# shared geometry helpers
# ---------------------------------------------------------------------------
def _corner_iou(a, b):
    """IoU of corner-format boxes. a: (..., A, 4), b: (..., B, 4) ->
    (..., A, B)."""
    al, at, ar, ab = jnp.split(a[..., :, None, :], 4, axis=-1)
    bl, bt, br, bb = jnp.split(b[..., None, :, :], 4, axis=-1)
    iw = jnp.maximum(0.0, jnp.minimum(ar, br) - jnp.maximum(al, bl))
    ih = jnp.maximum(0.0, jnp.minimum(ab, bb) - jnp.maximum(at, bt))
    inter = (iw * ih)[..., 0]
    area_a = ((ar - al) * (ab - at))[..., 0]
    area_b = ((br - bl) * (bb - bt))[..., 0]
    union = area_a + area_b - inter
    return jnp.where(union <= 0, 0.0, inter / union)


def _center_to_corner(box):
    x, y, w, h = jnp.split(box, 4, axis=-1)
    return jnp.concatenate(
        [x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)


def _corner_to_center(box):
    l, t, r, b = jnp.split(box, 4, axis=-1)
    return jnp.concatenate(
        [(l + r) / 2, (t + b) / 2, r - l, b - t], axis=-1)


def _greedy_nms_keep(boxes, order, valid, classes, thresh, force_suppress):
    """Greedy NMS over boxes visited in ``order`` (descending score).

    boxes: (A, 4) corner format; order: (A,) permutation; valid: (A,) bool
    (in sorted order); classes: (A,) in sorted order (or None).
    Returns keep flags (A,) aligned with the sorted order.

    The reference's O(n²) greedy loop (multibox_detection.cc:170-210,
    bounding_box-inl.h NMS kernels) becomes a ``fori_loop`` of A steps, each
    doing one vectorized suppression row — the standard TPU-friendly NMS.
    """
    sboxes = boxes[order]
    iou = _corner_iou(sboxes, sboxes)  # (A, A) in sorted order
    if classes is not None and not force_suppress:
        same = classes[:, None] == classes[None, :]
        iou = jnp.where(same, iou, 0.0)
    n = sboxes.shape[0]

    def body(i, keep):
        k_i = keep[i]
        sup = (iou[i] > thresh) & (jnp.arange(n) > i) & k_i
        return keep & ~sup

    keep = lax.fori_loop(0, n, body, valid)
    return keep


# ---------------------------------------------------------------------------
# MultiBox SSD trio
# ---------------------------------------------------------------------------
@register("_contrib_MultiBoxPrior", nin=1, aliases=("MultiBoxPrior",),
          params={"sizes": param("floats", (1.0,)),
                  "ratios": param("floats", (1.0,)),
                  "clip": param(bool, False),
                  "steps": param("floats", (-1.0, -1.0)),
                  "offsets": param("floats", (0.5, 0.5))})
def _multibox_prior(attrs, data):
    """Anchor generation (multibox_prior.cc:31-72).  Output (1, H*W*A, 4)."""
    h, w = data.shape[2], data.shape[3]
    sizes, ratios = attrs["sizes"], attrs["ratios"]
    step_y, step_x = attrs["steps"]
    if step_y <= 0 or step_x <= 0:
        step_y, step_x = 1.0 / h, 1.0 / w
    off_y, off_x = attrs["offsets"]
    cy = (np.arange(h) + off_y) * step_y
    cx = (np.arange(w) + off_x) * step_x
    # anchor wh list: sizes at ratio 1 (w scaled by H/W), then ratios[1:]
    whs = [(s * h / w / 2.0, s / 2.0) for s in sizes]
    whs += [(sizes[0] * h / w * np.sqrt(r) / 2.0, sizes[0] / np.sqrt(r) / 2.0)
            for r in ratios[1:]]
    whs = np.asarray(whs, np.float32)  # (A, 2)
    cyx = np.stack(np.meshgrid(cy, cx, indexing="ij"), -1)  # (H, W, 2)
    centers = np.broadcast_to(cyx[:, :, None, :], (h, w, len(whs), 2))
    half = np.broadcast_to(whs[None, None, :, :], (h, w, len(whs), 2))
    out = np.concatenate([
        centers[..., 1:2] - half[..., 0:1], centers[..., 0:1] - half[..., 1:2],
        centers[..., 1:2] + half[..., 0:1], centers[..., 0:1] + half[..., 1:2],
    ], axis=-1).reshape(1, -1, 4)
    anchors = jnp.asarray(out, dtype=data.dtype)
    if attrs["clip"]:
        anchors = jnp.clip(anchors, 0.0, 1.0)
    return lax.stop_gradient(anchors)


def _encode_loc(anchor, gt, variances):
    """Variance-encoded box regression target (multibox_target.cc:34-56)."""
    vx, vy, vw, vh = variances
    aw = anchor[..., 2] - anchor[..., 0]
    ah = anchor[..., 3] - anchor[..., 1]
    ax = (anchor[..., 0] + anchor[..., 2]) * 0.5
    ay = (anchor[..., 1] + anchor[..., 3]) * 0.5
    gw = gt[..., 2] - gt[..., 0]
    gh = gt[..., 3] - gt[..., 1]
    gx = (gt[..., 0] + gt[..., 2]) * 0.5
    gy = (gt[..., 1] + gt[..., 3]) * 0.5
    safe = lambda x: jnp.where(x == 0, 1.0, x)
    return jnp.stack([
        (gx - ax) / safe(aw) / vx,
        (gy - ay) / safe(ah) / vy,  # reference divides y-offset by ah
        jnp.log(jnp.maximum(gw, 1e-12) / safe(aw)) / vw,
        jnp.log(jnp.maximum(gh, 1e-12) / safe(ah)) / vh,
    ], axis=-1)


@register("_contrib_MultiBoxTarget", nin=3, nout=3,
          aliases=("MultiBoxTarget",),
          params={"overlap_threshold": param(float, 0.5),
                  "ignore_label": param(float, -1.0),
                  "negative_mining_ratio": param(float, -1.0),
                  "negative_mining_thresh": param(float, 0.5),
                  "minimum_negative_samples": param(int, 0),
                  "variances": param("floats", (0.1, 0.1, 0.2, 0.2))})
def _multibox_target(attrs, anchor, label, cls_pred):
    """SSD training-target assignment (multibox_target.cc:80-280).

    anchor (1, A, 4); label (N, L, >=5) rows [cls, xmin, ymin, xmax, ymax],
    padded with -1; cls_pred (N, num_cls, A).  Outputs: loc_target (N, 4A),
    loc_mask (N, 4A), cls_target (N, A).
    """
    ov_thresh = attrs["overlap_threshold"]
    ignore = attrs["ignore_label"]
    mine_ratio = attrs["negative_mining_ratio"]
    mine_thresh = attrs["negative_mining_thresh"]
    min_neg = attrs["minimum_negative_samples"]
    variances = attrs["variances"]
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]

    def one(labels, cls_preds):
        L = labels.shape[0]
        valid_gt = labels[:, 0] > -0.5
        gt_boxes = labels[:, 1:5]
        ious = _corner_iou(anchors, gt_boxes)          # (A, L)
        ious = jnp.where(valid_gt[None, :], ious, -1.0)

        # --- stage 1: bipartite matching (multibox_target.cc:112-148) ---
        def bip_body(_, st):
            flag, mgt, miou, gt_done = st
            m = jnp.where((flag == 1)[:, None] | gt_done[None, :],
                          BIG_NEG, ious)
            idx = jnp.argmax(m)
            a_i, g_i = idx // L, idx % L
            good = m[a_i, g_i] > 1e-6
            flag = jnp.where(good, flag.at[a_i].set(1), flag)
            mgt = jnp.where(good, mgt.at[a_i].set(g_i), mgt)
            miou = jnp.where(good, miou.at[a_i].set(m[a_i, g_i]), miou)
            gt_done = jnp.where(good, gt_done.at[g_i].set(True), gt_done)
            return flag, mgt, miou, gt_done

        flag0 = jnp.full((A,), -1, jnp.int32)
        st = (flag0, jnp.zeros((A,), jnp.int32), jnp.full((A,), -1.0),
              ~valid_gt)
        flag, mgt, miou, _ = lax.fori_loop(0, L, bip_body, st)

        # --- stage 2: threshold matching (multibox_target.cc:151-180) ---
        best_gt = jnp.argmax(ious, axis=1)
        best_iou = jnp.max(ious, axis=1)
        unmatched = flag != 1
        if ov_thresh > 0:
            pos2 = unmatched & (best_iou > ov_thresh)
            flag = jnp.where(pos2, 1, flag)
            mgt = jnp.where(pos2, best_gt, mgt)
        cand_iou = jnp.where(unmatched, best_iou, miou)

        num_pos = jnp.sum(flag == 1)
        if mine_ratio > 0:
            # --- negative mining (multibox_target.cc:182-240) ---
            num_neg = jnp.minimum((num_pos * mine_ratio).astype(jnp.int32),
                                  A - num_pos)
            num_neg = jnp.maximum(num_neg, min_neg)
            prob_bg = jax.nn.softmax(cls_preds, axis=0)[0]      # (A,)
            cand = (flag == -1) & (cand_iou < mine_thresh)
            key = jnp.where(cand, -prob_bg, BIG_NEG)            # hardest first
            rank = jnp.argsort(jnp.argsort(-key))
            flag = jnp.where(cand & (rank < num_neg), 0, flag)
        else:
            flag = jnp.where(flag != 1, 0, flag)

        has_gt = jnp.any(valid_gt)
        pos = (flag == 1) & has_gt
        neg = (flag == 0) & has_gt
        cls_t = jnp.where(pos, labels[mgt, 0] + 1.0,
                          jnp.where(neg, 0.0, ignore))
        loc_t = _encode_loc(anchors, gt_boxes[mgt], variances)
        loc_t = jnp.where(pos[:, None], loc_t, 0.0)
        loc_m = jnp.broadcast_to(pos[:, None], (A, 4)).astype(anchors.dtype)
        return loc_t.reshape(-1), loc_m.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(one)(label, cls_pred)
    return (lax.stop_gradient(loc_t.astype(anchor.dtype)),
            lax.stop_gradient(loc_m.astype(anchor.dtype)),
            lax.stop_gradient(cls_t.astype(anchor.dtype)))


def _decode_loc(anchors, loc, variances, clip):
    """Inverse of _encode_loc (multibox_detection.cc:46-76)."""
    vx, vy, vw, vh = variances
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) / 2
    ay = (anchors[:, 1] + anchors[:, 3]) / 2
    ox = loc[:, 0] * vx * aw + ax
    oy = loc[:, 1] * vy * ah + ay
    ow = jnp.exp(loc[:, 2] * vw) * aw / 2
    oh = jnp.exp(loc[:, 3] * vh) * ah / 2
    out = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], -1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


@register("_contrib_MultiBoxDetection", nin=3,
          aliases=("MultiBoxDetection",),
          params={"clip": param(bool, True),
                  "threshold": param(float, 0.01),
                  "background_id": param(int, 0),
                  "nms_threshold": param(float, 0.5),
                  "force_suppress": param(bool, False),
                  "variances": param("floats", (0.1, 0.1, 0.2, 0.2)),
                  "nms_topk": param(int, -1)})
def _multibox_detection(attrs, cls_prob, loc_pred, anchor):
    """SSD decode + NMS (multibox_detection.cc:80-210).

    cls_prob (N, C, A), loc_pred (N, 4A), anchor (1, A, 4) ->
    (N, A, 6) rows [id, score, xmin, ymin, xmax, ymax], -1-padded.
    """
    thresh = attrs["threshold"]
    nms_th = attrs["nms_threshold"]
    topk = attrs["nms_topk"]
    force = attrs["force_suppress"]
    anchors = anchor.reshape(-1, 4)
    A = anchors.shape[0]

    bg = attrs["background_id"]

    def one(probs, loc):
        nc = probs.shape[0]
        masked = jnp.where(jnp.arange(nc)[:, None] == bg, BIG_NEG, probs)
        score = jnp.max(masked, axis=0)
        raw = jnp.argmax(masked, axis=0)                # class incl. bg slot
        # id with background removed from the numbering (bg=0 -> raw-1)
        cid = (raw - (raw > bg)).astype(probs.dtype) if bg >= 0 \
            else raw.astype(probs.dtype)
        cid = jnp.where(score < thresh, -1.0, cid)
        boxes = _decode_loc(anchors, loc.reshape(-1, 4), attrs["variances"],
                            attrs["clip"])
        valid = cid >= 0
        # sort by score descending, invalid rows last
        key = jnp.where(valid, score, BIG_NEG)
        order = jnp.argsort(-key)
        svalid = valid[order]
        # nms_topk only limits which rows participate in (and survive with
        # an id) the suppression stage; the reference marks beyond-top-k
        # rows id=-1 but keeps score/coords (multibox_detection.cc:155-160)
        in_topk = svalid & (jnp.arange(A) < topk) if topk > 0 else svalid
        if 0 < nms_th <= 1:
            keep = _greedy_nms_keep(boxes, order, in_topk, cid[order],
                                    nms_th, force)
        else:
            keep = in_topk
        rows = jnp.concatenate(
            [jnp.where(keep, cid[order], -1.0)[:, None],
             score[order][:, None], boxes[order]], axis=-1)
        rows = jnp.where(svalid[:, None], rows, -1.0)
        return rows

    out = jax.vmap(one)(cls_prob, loc_pred)
    return lax.stop_gradient(out.astype(cls_prob.dtype))


# ---------------------------------------------------------------------------
# bounding-box ops (bounding_box-inl.h)
# ---------------------------------------------------------------------------
@register("_contrib_box_nms", nin=1, nout=2, visible=1,
          aliases=("_contrib_box_non_maximum_suppression", "box_nms"),
          params={"overlap_thresh": param(float, 0.5),
                  "valid_thresh": param(float, 0.0),
                  "topk": param(int, -1),
                  "coord_start": param(int, 2),
                  "score_index": param(int, 1),
                  "id_index": param(int, -1),
                  "force_suppress": param(bool, False),
                  "in_format": param(["corner", "center"], "corner"),
                  "out_format": param(["corner", "center"], "corner")})
def _box_nms(attrs, data):
    """Generic batched NMS (bounding_box-inl.h:55-90).  Input (..., N, K);
    output[0]: same shape, surviving rows (sorted by score desc) at front,
    suppressed rows -1; output[1]: per-batch valid count (..., 1)."""
    shape = data.shape
    k = shape[-1]
    n = shape[-2]
    flat = data.reshape((-1, n, k))
    cs, si, ii = attrs["coord_start"], attrs["score_index"], attrs["id_index"]
    thresh = attrs["overlap_thresh"]
    vthresh = attrs["valid_thresh"]
    topk = attrs["topk"]
    force = attrs["force_suppress"]

    def one(rows):
        score = rows[:, si]
        valid = score > vthresh
        key = jnp.where(valid, score, BIG_NEG)
        order = jnp.argsort(-key)
        svalid = valid[order]
        if topk > 0:
            svalid = svalid & (jnp.arange(n) < topk)
        boxes = rows[:, cs:cs + 4]
        if attrs["in_format"] == "center":
            boxes = _center_to_corner(boxes)
        classes = rows[order, ii] if ii >= 0 else None
        keep = _greedy_nms_keep(boxes, order, svalid, classes, thresh, force)
        out_rows = rows[order]
        if attrs["out_format"] != attrs["in_format"]:
            b = out_rows[:, cs:cs + 4]
            b = (_corner_to_center(b) if attrs["out_format"] == "center"
                 else _center_to_corner(b))
            out_rows = out_rows.at[:, cs:cs + 4].set(b)
        # compact survivors to the front (preserving score order); the
        # trailing rows are all -1
        perm = jnp.argsort(~keep)
        out_rows = jnp.where(keep[perm][:, None], out_rows[perm], -1.0)
        return out_rows, jnp.sum(valid).astype(rows.dtype)[None]

    out, count = jax.vmap(one)(flat)
    return (lax.stop_gradient(out.reshape(shape)),
            lax.stop_gradient(count.reshape(shape[:-2] + (1,))))


@register("_contrib_box_iou", nin=2, aliases=("box_iou",),
          params={"format": param(["corner", "center"], "corner")})
def _box_iou(attrs, lhs, rhs):
    """Pairwise IoU (bounding_box-inl.h:560-600): (..., 4) x (..., 4) ->
    lhs.shape[:-1] + rhs.shape[:-1]."""
    a = lhs.reshape((-1, 4))
    b = rhs.reshape((-1, 4))
    if attrs["format"] == "center":
        a, b = _center_to_corner(a), _center_to_corner(b)
    out = _corner_iou(a, b)
    return lax.stop_gradient(
        out.reshape(lhs.shape[:-1] + rhs.shape[:-1]).astype(lhs.dtype))


@register("_contrib_bipartite_matching", nin=1, nout=2,
          aliases=("bipartite_matching",),
          params={"is_ascend": param(bool, False),
                  "threshold": param(float, None, required=True),
                  "topk": param(int, -1)})
def _bipartite_matching(attrs, data):
    """Greedy bipartite matching on a score matrix (bounding_box-inl.h:
    680-700).  Input (..., N, M); outputs: row->col (..., N) and
    col->row (..., M), -1 when unmatched."""
    shape = data.shape
    n, m = shape[-2], shape[-1]
    flat = data.reshape((-1, n, m))
    thr = attrs["threshold"]
    asc = attrs["is_ascend"]
    steps = min(n, m)
    if attrs["topk"] > 0:
        steps = min(steps, attrs["topk"])

    def one(mat):
        work = -mat if not asc else mat
        lim = -thr if not asc else thr

        def body(_, st):
            rowm, colm, work = st
            idx = jnp.argmin(work)
            i, j = idx // m, idx % m
            ok = work[i, j] <= lim
            rowm = jnp.where(ok, rowm.at[i].set(j), rowm)
            colm = jnp.where(ok, colm.at[j].set(i), colm)
            work = jnp.where(ok, work.at[i, :].set(jnp.inf)
                             .at[:, j].set(jnp.inf), work)
            return rowm, colm, work

        rowm = jnp.full((n,), -1.0, mat.dtype)
        colm = jnp.full((m,), -1.0, mat.dtype)
        rowm, colm, _ = lax.fori_loop(0, steps, body, (rowm, colm, work))
        return rowm, colm

    rowm, colm = jax.vmap(one)(flat)
    return (lax.stop_gradient(rowm.reshape(shape[:-1])),
            lax.stop_gradient(colm.reshape(shape[:-2] + (m,))))


# ---------------------------------------------------------------------------
# RPN proposals (proposal-inl.h, multi_proposal-inl.h)
# ---------------------------------------------------------------------------
def _gen_base_anchors(base_size, scales, ratios):
    """py-faster-rcnn style base anchors (proposal-inl.h GenerateAnchors):
    ratio-first enumeration with rounding."""
    px = (base_size - 1) * 0.5
    anchors = []
    size = base_size * base_size
    for r in ratios:
        ws = round(np.sqrt(size / r))
        hs = round(ws * r)
        for s in scales:
            w2, h2 = ws * s * 0.5, hs * s * 0.5
            anchors.append([px - w2 + 0.5, px - h2 + 0.5,
                            px + w2 - 0.5, px + h2 - 0.5])
    return np.asarray(anchors, np.float32)


def _proposal_impl(attrs, score, bbox_deltas, im_info):
    """One image's RPN proposals.  score (A, H, W) foreground scores."""
    stride = attrs["feature_stride"]
    anchors0 = _gen_base_anchors(stride, attrs["scales"], attrs["ratios"])
    na = anchors0.shape[0]
    h, w = score.shape[-2], score.shape[-1]
    shift_x = np.arange(w) * stride
    shift_y = np.arange(h) * stride
    sx, sy = np.meshgrid(shift_x, shift_y)
    shifts = np.stack([sx, sy, sx, sy], axis=-1).reshape(-1, 1, 4)
    all_anchors = jnp.asarray(
        (shifts + anchors0[None]).reshape(-1, 4))       # (H*W*A, 4)
    # deltas (4A, H, W) -> (H*W*A, 4); scores (A, H, W) -> (H*W*A,)
    deltas = bbox_deltas.reshape(na, 4, h, w).transpose(2, 3, 0, 1)\
        .reshape(-1, 4)
    scores = score.transpose(1, 2, 0).reshape(-1)

    if attrs["iou_loss"]:
        # IoUTransformInv (proposal.cc): deltas are direct corner offsets
        boxes = all_anchors + deltas
    else:
        aw = all_anchors[:, 2] - all_anchors[:, 0] + 1.0
        ah = all_anchors[:, 3] - all_anchors[:, 1] + 1.0
        ax = all_anchors[:, 0] + aw * 0.5
        ay = all_anchors[:, 1] + ah * 0.5
        cx = deltas[:, 0] * aw + ax
        cy = deltas[:, 1] * ah + ay
        pw = jnp.exp(jnp.clip(deltas[:, 2], -10, 10)) * aw
        ph = jnp.exp(jnp.clip(deltas[:, 3], -10, 10)) * ah
        boxes = jnp.stack([cx - 0.5 * (pw - 1), cy - 0.5 * (ph - 1),
                           cx + 0.5 * (pw - 1), cy + 0.5 * (ph - 1)], -1)
    im_h, im_w, im_scale = im_info[0], im_info[1], im_info[2]
    boxes = jnp.stack([jnp.clip(boxes[:, 0], 0, im_w - 1),
                       jnp.clip(boxes[:, 1], 0, im_h - 1),
                       jnp.clip(boxes[:, 2], 0, im_w - 1),
                       jnp.clip(boxes[:, 3], 0, im_h - 1)], -1)
    min_size = attrs["rpn_min_size"] * im_scale
    keep_size = ((boxes[:, 2] - boxes[:, 0] + 1) >= min_size) & \
                ((boxes[:, 3] - boxes[:, 1] + 1) >= min_size)
    scores = jnp.where(keep_size, scores, BIG_NEG)

    pre_n = attrs["rpn_pre_nms_top_n"]
    # non-positive means "keep all" (reference proposal-inl.h convention)
    pre_n = boxes.shape[0] if pre_n <= 0 else min(pre_n, boxes.shape[0])
    post_n = attrs["rpn_post_nms_top_n"]
    top_scores, order = lax.top_k(scores, pre_n)
    top_boxes = boxes[order]
    valid = top_scores > BIG_NEG / 2
    keep = _greedy_nms_keep(top_boxes, jnp.arange(pre_n), valid, None,
                            attrs["threshold"], True)
    # compact kept to front preserving score order, pad by wrapping (the
    # reference fills the fixed post_nms_top_n output cyclically)
    nkeep = jnp.maximum(jnp.sum(keep), 1)
    slots = jnp.arange(post_n) % nkeep
    src = jnp.argsort(~keep)
    idx = src[slots]
    rois = top_boxes[idx]
    roi_scores = top_scores[idx][:, None]
    return rois, roi_scores


_PROPOSAL_PARAMS = {
    "rpn_pre_nms_top_n": param(int, 6000),
    "rpn_post_nms_top_n": param(int, 300),
    "threshold": param(float, 0.7),
    "rpn_min_size": param(int, 16),
    "scales": param("floats", (4.0, 8.0, 16.0, 32.0)),
    "ratios": param("floats", (0.5, 1.0, 2.0)),
    "feature_stride": param(int, 16),
    "output_score": param(bool, False),
    "iou_loss": param(bool, False),
}


@register("_contrib_Proposal", nin=3, aliases=("Proposal",),
          nout=lambda attrs: 2 if attrs["output_score"] else 1,
          params=dict(_PROPOSAL_PARAMS))
def _proposal(attrs, cls_prob, bbox_pred, im_info):
    """RPN proposal op (proposal-inl.h:60-90); batch size 1.
    cls_prob (1, 2A, H, W); output rois (post_n, 5) [batch_idx, corners]."""
    na = cls_prob.shape[1] // 2
    rois, scores = _proposal_impl(attrs, cls_prob[0, na:], bbox_pred[0],
                                  im_info[0])
    rois = jnp.concatenate([jnp.zeros((rois.shape[0], 1), rois.dtype), rois],
                           axis=-1)
    rois = lax.stop_gradient(rois.astype(cls_prob.dtype))
    if attrs["output_score"]:
        return rois, lax.stop_gradient(scores.astype(cls_prob.dtype))
    return rois


@register("_contrib_MultiProposal", nin=3, aliases=("MultiProposal",),
          nout=lambda attrs: 2 if attrs["output_score"] else 1,
          params=dict(_PROPOSAL_PARAMS))
def _multi_proposal(attrs, cls_prob, bbox_pred, im_info):
    """Batched Proposal (multi_proposal-inl.h): output (N*post_n, 5) with
    per-image batch index in column 0."""
    n = cls_prob.shape[0]
    na = cls_prob.shape[1] // 2

    def one(probs, deltas, info):
        return _proposal_impl(attrs, probs[na:], deltas, info)

    rois, scores = jax.vmap(one)(cls_prob, bbox_pred, im_info)
    post_n = rois.shape[1]
    bidx = jnp.broadcast_to(
        jnp.arange(n, dtype=rois.dtype)[:, None, None], (n, post_n, 1))
    rois = jnp.concatenate([bidx, rois], -1).reshape(n * post_n, 5)
    rois = lax.stop_gradient(rois.astype(cls_prob.dtype))
    if attrs["output_score"]:
        return rois, lax.stop_gradient(
            scores.reshape(n * post_n, 1).astype(cls_prob.dtype))
    return rois


# ---------------------------------------------------------------------------
# ROI pooling family
# ---------------------------------------------------------------------------
@register("ROIPooling", nin=2, aliases=("roipooling",),
          params={"pooled_size": param("shape", None, required=True),
                  "spatial_scale": param(float, None, required=True)})
def _roi_pooling(attrs, data, rois):
    """Max ROI pooling (roi_pooling-inl.h:50-60; forward roi_pooling.cc).

    data (N, C, H, W); rois (R, 5) [batch_idx, x1, y1, x2, y2] in image
    coords.  TPU design: per-roi masked max over the feature map (bin
    membership as a separable h/w mask) instead of scalar bin loops.
    """
    ph, pw = attrs["pooled_size"]
    scale = attrs["spatial_scale"]
    n, c, h, w = data.shape

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale)
        y1 = jnp.round(roi[2] * scale)
        x2 = jnp.round(roi[3] * scale)
        y2 = jnp.round(roi[4] * scale)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        img = data[b]                                    # (C, H, W)
        iy = jnp.arange(ph)
        ix = jnp.arange(pw)
        hstart = jnp.clip(jnp.floor(iy * bin_h) + y1, 0, h)
        hend = jnp.clip(jnp.ceil((iy + 1) * bin_h) + y1, 0, h)
        wstart = jnp.clip(jnp.floor(ix * bin_w) + x1, 0, w)
        wend = jnp.clip(jnp.ceil((ix + 1) * bin_w) + x1, 0, w)
        hs = jnp.arange(h)
        ws = jnp.arange(w)
        mh = (hs[None, :] >= hstart[:, None]) & (hs[None, :] < hend[:, None])
        mw = (ws[None, :] >= wstart[:, None]) & (ws[None, :] < wend[:, None])
        # (C, ph, H, W) masked -> max over H,W
        m = mh[None, :, None, :, None] & mw[None, None, :, None, :]
        vals = jnp.where(m, img[:, None, None, :, :], BIG_NEG)
        out = jnp.max(vals, axis=(3, 4))
        empty = (hend[:, None] <= hstart[:, None]) | \
                (wend[None, :] <= wstart[None, :])
        return jnp.where(empty[None], 0.0, out)

    return jax.vmap(one)(rois).astype(data.dtype)


@register("_contrib_ROIAlign", nin=2, aliases=("ROIAlign",),
          params={"pooled_size": param("shape", None, required=True),
                  "spatial_scale": param(float, None, required=True),
                  "sample_ratio": param(int, -1)})
def _roi_align(attrs, data, rois):
    """ROIAlign (roi_align-inl.h:50-60): average of bilinear samples per
    bin, no coordinate rounding."""
    ph, pw = attrs["pooled_size"]
    scale = attrs["spatial_scale"]
    sr = attrs["sample_ratio"]
    n, c, h, w = data.shape
    # static sample counts (reference uses adaptive ceil(roi/bin) when -1;
    # static compromise: 2 — the detectron default)
    sh = sr if sr > 0 else 2
    sw = sr if sr > 0 else 2

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * scale, roi[2] * scale, \
            roi[3] * scale, roi[4] * scale
        rh = jnp.maximum(y2 - y1, 1.0)
        rw = jnp.maximum(x2 - x1, 1.0)
        bh, bw = rh / ph, rw / pw
        iy = jnp.arange(ph)[:, None, None, None]
        ix = jnp.arange(pw)[None, :, None, None]
        ky = jnp.arange(sh)[None, None, :, None]
        kx = jnp.arange(sw)[None, None, None, :]
        ys = y1 + iy * bh + (ky + 0.5) * bh / sh
        xs = x1 + ix * bw + (kx + 0.5) * bw / sw
        ys = jnp.broadcast_to(ys, (ph, pw, sh, sw))
        xs = jnp.broadcast_to(xs, (ph, pw, sh, sw))
        vals = _bilinear_sample(data[b], ys, xs)         # (C, ph, pw, sh, sw)
        return jnp.mean(vals, axis=(3, 4))

    return jax.vmap(one)(rois).astype(data.dtype)


@register("_contrib_PSROIPooling", nin=2, aliases=("PSROIPooling",),
          params={"spatial_scale": param(float, None, required=True),
                  "output_dim": param(int, None, required=True),
                  "pooled_size": param(int, None, required=True),
                  "group_size": param(int, 0)})
def _psroi_pooling(attrs, data, rois):
    """Position-sensitive ROI pooling (psroi_pooling-inl.h:55-65, R-FCN):
    bin (i,j) of output channel d averages input channel
    (d*G + gi)*G + gj over the bin."""
    scale = attrs["spatial_scale"]
    od = attrs["output_dim"]
    p = attrs["pooled_size"]
    g = attrs["group_size"] or p
    n, c, h, w = data.shape
    # static channel map (p, p) -> group cell
    gi = (np.arange(p) * g // p).clip(0, g - 1)
    chan = (np.arange(od)[:, None, None] * g + gi[None, :, None]) * g + \
        gi[None, None, :]                                # (od, p, p)
    chan = jnp.asarray(chan)

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * scale
        y1 = jnp.round(roi[2]) * scale
        x2 = jnp.round(roi[3] + 1.0) * scale
        y2 = jnp.round(roi[4] + 1.0) * scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bh, bw = rh / p, rw / p
        iy, ix = jnp.arange(p), jnp.arange(p)
        hstart = jnp.clip(jnp.floor(iy * bh + y1), 0, h)
        hend = jnp.clip(jnp.ceil((iy + 1) * bh + y1), 0, h)
        wstart = jnp.clip(jnp.floor(ix * bw + x1), 0, w)
        wend = jnp.clip(jnp.ceil((ix + 1) * bw + x1), 0, w)
        hs, ws = jnp.arange(h), jnp.arange(w)
        mh = (hs[None] >= hstart[:, None]) & (hs[None] < hend[:, None])
        mw = (ws[None] >= wstart[:, None]) & (ws[None] < wend[:, None])
        m = (mh[:, None, :, None] & mw[None, :, None, :]).astype(data.dtype)
        img = data[b][chan]                              # (od, p, p, h, w)
        s = jnp.einsum("dijhw,ijhw->dij", img, m)
        cnt = jnp.maximum(jnp.einsum("ijhw->ij", m), 1.0)
        empty = (hend[:, None] <= hstart[:, None]) | \
                (wend[None, :] <= wstart[None, :])
        return jnp.where(empty[None], 0.0, s / cnt[None])

    return jax.vmap(one)(rois).astype(data.dtype)


@register("_contrib_DeformablePSROIPooling", nin=-1,
          aliases=("DeformablePSROIPooling",),
          params={"spatial_scale": param(float, None, required=True),
                  "output_dim": param(int, None, required=True),
                  "group_size": param(int, None, required=True),
                  "pooled_size": param(int, None, required=True),
                  "part_size": param(int, 0),
                  "sample_per_part": param(int, 1),
                  "trans_std": param(float, 0.0),
                  "no_trans": param(bool, False)})
def _deformable_psroi_pooling(attrs, data, rois, *maybe_trans):
    """Deformable PS-ROI pooling (deformable_psroi_pooling-inl.h:60-74):
    PS-ROI bins shifted by a learned normalized offset per part cell,
    sampled bilinearly (sample_per_part² samples per bin)."""
    scale = attrs["spatial_scale"]
    od = attrs["output_dim"]
    p = attrs["pooled_size"]
    g = attrs["group_size"]
    part = attrs["part_size"] or p
    sp = attrs["sample_per_part"]
    tstd = attrs["trans_std"]
    no_trans = attrs["no_trans"] or not maybe_trans
    n, c, h, w = data.shape
    gi = (np.arange(p) * g // p).clip(0, g - 1)
    chan = (np.arange(od)[:, None, None] * g + gi[None, :, None]) * g + \
        gi[None, None, :]
    chan = jnp.asarray(chan)
    pi = (np.arange(p) * part // p).clip(0, part - 1)

    def one(roi, trans):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * scale - 0.5
        y1 = jnp.round(roi[2]) * scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * scale - 0.5
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bh, bw = rh / p, rw / p
        # per-bin learned offsets from the part grid (class-agnostic: the
        # trans input has 2*num_offset_classes channels; class 0 used here
        # per bin cell)
        if no_trans:
            dy = jnp.zeros((p, p))
            dx = jnp.zeros((p, p))
        else:
            tr = trans.reshape(-1, 2, part, part)
            dy = tr[0, 1][pi[:, None], pi[None, :]] * tstd * rh
            dx = tr[0, 0][pi[:, None], pi[None, :]] * tstd * rw
        iy = jnp.arange(p)[:, None, None, None]
        ix = jnp.arange(p)[None, :, None, None]
        ky = jnp.arange(sp)[None, None, :, None]
        kx = jnp.arange(sp)[None, None, None, :]
        sub_h = bh / sp
        sub_w = bw / sp
        ys = y1 + iy * bh + (ky + 0.5) * sub_h + dy[:, :, None, None]
        xs = x1 + ix * bw + (kx + 0.5) * sub_w + dx[:, :, None, None]
        ys = jnp.broadcast_to(ys, (p, p, sp, sp)).reshape(p * p, sp, sp)
        xs = jnp.broadcast_to(xs, (p, p, sp, sp)).reshape(p * p, sp, sp)
        # gather each bin's position-sensitive channels FIRST, then sample
        # only those od channels (g² fewer gathers than sampling all C)
        imgs = data[b][chan].transpose(1, 2, 0, 3, 4)\
            .reshape(p * p, od, h, w)
        vals = jax.vmap(_bilinear_sample)(imgs, ys, xs)  # (p*p, od, sp, sp)
        pooled = jnp.mean(vals, axis=(2, 3))             # (p*p, od)
        return pooled.T.reshape(od, p, p)

    r = rois.shape[0]
    trans = maybe_trans[0] if maybe_trans else jnp.zeros((r, 2, part, part),
                                                         data.dtype)
    return jax.vmap(one)(rois, trans).astype(data.dtype)


# ---------------------------------------------------------------------------
# deformable convolution (deformable_convolution-inl.h, deformable_im2col.h)
# ---------------------------------------------------------------------------
@register("_contrib_DeformableConvolution", nin=-1,
          aliases=("DeformableConvolution",),
          params={"kernel": param("shape", None, required=True),
                  "stride": param("shape", ()),
                  "dilate": param("shape", ()),
                  "pad": param("shape", ()),
                  "num_filter": param(int, None, required=True),
                  "num_group": param(int, 1),
                  "num_deformable_group": param(int, 1),
                  "workspace": param(int, 1024),
                  "no_bias": param(bool, False),
                  "layout": param(str, None)})
def _deformable_convolution(attrs, data, offset, weight, *maybe_bias):
    """Deformable conv v1 (deformable_im2col.h bilinear im2col + GEMM).

    offset (N, num_deformable_group*2*kh*kw, Ho, Wo), per-tap (dy, dx)
    channel pairs (deformable_im2col.h: channel 2*tap = y, 2*tap+1 = x).
    TPU design: bilinear-gather the deformed im2col patch tensor
    (N, C, kh*kw, Ho, Wo) then one grouped einsum on the MXU.
    """
    kh, kw = attrs["kernel"]
    stride = attrs["stride"] or (1, 1)
    dilate = attrs["dilate"] or (1, 1)
    pad = attrs["pad"] or (0, 0)
    groups = attrs["num_group"]
    dg = attrs["num_deformable_group"]
    nf = attrs["num_filter"]
    n, c, h, w = data.shape
    ho = (h + 2 * pad[0] - dilate[0] * (kh - 1) - 1) // stride[0] + 1
    wo = (w + 2 * pad[1] - dilate[1] * (kw - 1) - 1) // stride[1] + 1
    kk = kh * kw

    base_y = (np.arange(ho) * stride[0] - pad[0])[:, None] + \
        (np.arange(kh) * dilate[0])[None, :]             # (Ho, kh)
    base_x = (np.arange(wo) * stride[1] - pad[1])[:, None] + \
        (np.arange(kw) * dilate[1])[None, :]             # (Wo, kw)

    # per-tap base coordinates: tap t = (t//kw, t%kw)
    ys_tap = np.repeat(base_y.T, kw, axis=0)             # (kk, Ho)
    xs_tap = np.tile(base_x.T, (kh, 1))                  # (kk, Wo)

    def one(img, off):
        # off (dg*2*kk, Ho, Wo) -> (dg, kk, 2, Ho, Wo)
        off = off.reshape(dg, kk, 2, ho, wo)
        cols = []
        for gidx in range(dg):
            ys = jnp.asarray(ys_tap)[:, :, None] + off[gidx, :, 0]
            xs = jnp.asarray(xs_tap)[:, None, :] + off[gidx, :, 1]
            sub = img[gidx * (c // dg):(gidx + 1) * (c // dg)]
            cols.append(_bilinear_sample(sub, ys, xs))   # (C/dg, kk, Ho, Wo)
        return jnp.concatenate(cols, axis=0)             # (C, kk, Ho, Wo)

    cols = jax.vmap(one)(data, offset)                   # (N, C, kk, Ho, Wo)
    cols = cols.reshape(n, groups, (c // groups) * kk, ho * wo)
    w3 = weight.reshape(groups, nf // groups, (c // groups) * kk)
    out = jnp.einsum("gmk,ngkp->ngmp", w3, cols,
                     preferred_element_type=jnp.float32)
    out = out.reshape(n, nf, ho, wo).astype(data.dtype)
    if not attrs["no_bias"] and maybe_bias:
        out = out + maybe_bias[0].reshape(1, -1, 1, 1)
    return out
