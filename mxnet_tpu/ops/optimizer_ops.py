"""Fused optimizer update operators.

Reference analog: ``src/operator/optimizer_op.cc`` — SGD(+momentum,
multi-precision), Adam, RMSProp(+alex), FTRL, FTML, Signum/SignSGD, NAG —
each a single fused kernel so the update never materializes intermediates in
HBM.  On TPU each update is one jitted elementwise fusion (XLA fuses the whole
chain); states are returned as extra outputs and written back by the dispatch
layer (``aux_writeback``), the functional analog of the reference's in-place
state mutation.

Convention (matches reference): ``rescale_grad`` scales raw grads, then
``clip_gradient`` clips, then weight decay ``wd`` is added as ``wd*weight``.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register, param, OPS

_COMMON = {
    "lr": param(float, 0.0, required=True),
    "wd": param(float, 0.0),
    "rescale_grad": param(float, 1.0),
    "clip_gradient": param(float, -1.0),
}


def _prep_grad(attrs, weight, grad):
    g = grad * attrs["rescale_grad"]
    if attrs["clip_gradient"] >= 0:
        g = jnp.clip(g, -attrs["clip_gradient"], attrs["clip_gradient"])
    return g + attrs["wd"] * weight


def _opt(name, nin, nout=1, extra=None, writeback=None, hidden=0, aliases=()):
    def deco(fn):
        register(name, nin=nin, nout=nout,
                 params={**_COMMON, **(extra or {})},
                 aux_writeback=writeback, visible=nout - hidden,
                 aliases=aliases)(fn)
        return fn
    return deco


@_opt("sgd_update", nin=2)
def _sgd_update(attrs, weight, grad):
    return weight - attrs["lr"] * _prep_grad(attrs, weight, grad)


@_opt("sgd_mom_update", nin=3, nout=2, writeback={1: 2}, hidden=1,
      extra={"momentum": param(float, 0.0)})
def _sgd_mom_update(attrs, weight, grad, mom):
    g = _prep_grad(attrs, weight, grad)
    new_mom = attrs["momentum"] * mom - attrs["lr"] * g
    return weight + new_mom, new_mom


@_opt("nag_mom_update", nin=3, nout=2, writeback={1: 2}, hidden=1,
      extra={"momentum": param(float, 0.0)})
def _nag_mom_update(attrs, weight, grad, mom):
    g = _prep_grad(attrs, weight, grad)
    new_mom = attrs["momentum"] * mom + g
    return weight - attrs["lr"] * (g + attrs["momentum"] * new_mom), new_mom


@_opt("mp_sgd_update", nin=3, nout=2, writeback={1: 2}, hidden=1)
def _mp_sgd_update(attrs, weight, grad, weight32):
    """Multi-precision SGD: fp32 master weights for fp16/bf16 params
    (ref: optimizer_op.cc MP_SGD)."""
    g = grad.astype(jnp.float32) * attrs["rescale_grad"]
    if attrs["clip_gradient"] >= 0:
        g = jnp.clip(g, -attrs["clip_gradient"], attrs["clip_gradient"])
    g = g + attrs["wd"] * weight32
    new_w32 = weight32 - attrs["lr"] * g
    return new_w32.astype(weight.dtype), new_w32


@_opt("mp_sgd_mom_update", nin=4, nout=3, writeback={1: 2, 2: 3}, hidden=2,
      extra={"momentum": param(float, 0.0)})
def _mp_sgd_mom_update(attrs, weight, grad, mom, weight32):
    g = grad.astype(jnp.float32) * attrs["rescale_grad"]
    if attrs["clip_gradient"] >= 0:
        g = jnp.clip(g, -attrs["clip_gradient"], attrs["clip_gradient"])
    g = g + attrs["wd"] * weight32
    new_mom = attrs["momentum"] * mom - attrs["lr"] * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@_opt("adam_update", nin=4, nout=3, writeback={1: 2, 2: 3}, hidden=2,
      extra={"beta1": param(float, 0.9), "beta2": param(float, 0.999),
             "epsilon": param(float, 1e-8), "lazy_update": param(bool, True)})
def _adam_update(attrs, weight, grad, mean, var):
    g = _prep_grad(attrs, weight, grad)
    new_mean = attrs["beta1"] * mean + (1 - attrs["beta1"]) * g
    new_var = attrs["beta2"] * var + (1 - attrs["beta2"]) * jnp.square(g)
    w = weight - attrs["lr"] * new_mean / (jnp.sqrt(new_var) + attrs["epsilon"])
    return w, new_mean, new_var


@_opt("rmsprop_update", nin=3, nout=2, writeback={1: 2}, hidden=1,
      extra={"gamma1": param(float, 0.95), "epsilon": param(float, 1e-8),
             "clip_weights": param(float, -1.0)})
def _rmsprop_update(attrs, weight, grad, n):
    g = _prep_grad(attrs, weight, grad)
    new_n = (1 - attrs["gamma1"]) * jnp.square(g) + attrs["gamma1"] * n
    w = weight - attrs["lr"] * g / jnp.sqrt(new_n + attrs["epsilon"])
    if attrs["clip_weights"] > 0:
        w = jnp.clip(w, -attrs["clip_weights"], attrs["clip_weights"])
    return w, new_n


@_opt("rmspropalex_update", nin=5, nout=4, writeback={1: 2, 2: 3, 3: 4},
      hidden=3,
      extra={"gamma1": param(float, 0.95), "gamma2": param(float, 0.9),
             "epsilon": param(float, 1e-8), "clip_weights": param(float, -1.0)})
def _rmspropalex_update(attrs, weight, grad, n, g_, delta):
    g = _prep_grad(attrs, weight, grad)
    new_n = (1 - attrs["gamma1"]) * jnp.square(g) + attrs["gamma1"] * n
    new_g = (1 - attrs["gamma1"]) * g + attrs["gamma1"] * g_
    new_delta = attrs["gamma2"] * delta - attrs["lr"] * g / \
        jnp.sqrt(new_n - jnp.square(new_g) + attrs["epsilon"])
    w = weight + new_delta
    if attrs["clip_weights"] > 0:
        w = jnp.clip(w, -attrs["clip_weights"], attrs["clip_weights"])
    return w, new_n, new_g, new_delta


@_opt("ftrl_update", nin=4, nout=3, writeback={1: 2, 2: 3}, hidden=2,
      extra={"lamda1": param(float, 0.01), "beta": param(float, 1.0)})
def _ftrl_update(attrs, weight, grad, z, n):
    g = grad * attrs["rescale_grad"]
    if attrs["clip_gradient"] >= 0:
        g = jnp.clip(g, -attrs["clip_gradient"], attrs["clip_gradient"])
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / attrs["lr"]
    new_z = z + g - sigma * weight
    denom = (attrs["beta"] + jnp.sqrt(new_n)) / attrs["lr"] + attrs["wd"]
    w = jnp.where(jnp.abs(new_z) > attrs["lamda1"],
                  (jnp.sign(new_z) * attrs["lamda1"] - new_z) / denom,
                  0.0)
    return w, new_z, new_n


@_opt("ftml_update", nin=5, nout=4, writeback={1: 2, 2: 3, 3: 4}, hidden=3,
      extra={"beta1": param(float, 0.6), "beta2": param(float, 0.999),
             "epsilon": param(float, 1e-8), "t": param(int, 1)})
def _ftml_update(attrs, weight, grad, d, v, z):
    g = _prep_grad(attrs, weight, grad)
    t = attrs["t"]
    b1, b2 = attrs["beta1"], attrs["beta2"]
    new_v = b2 * v + (1 - b2) * jnp.square(g)
    d_t = (1 - b1 ** t) / attrs["lr"] * \
        (jnp.sqrt(new_v / (1 - b2 ** t)) + attrs["epsilon"])
    sigma = d_t - b1 * d
    new_z = b1 * z + (1 - b1) * g - sigma * weight
    new_d = d_t
    w = -new_z / new_d
    return w, new_d, new_v, new_z


@_opt("signsgd_update", nin=2)
def _signsgd_update(attrs, weight, grad):
    g = _prep_grad(attrs, weight, grad)
    return weight - attrs["lr"] * jnp.sign(g)


@_opt("signum_update", nin=3, nout=2, writeback={1: 2}, hidden=1,
      extra={"momentum": param(float, 0.0), "wd_lh": param(float, 0.0)})
def _signum_update(attrs, weight, grad, mom):
    g = grad * attrs["rescale_grad"]
    if attrs["clip_gradient"] >= 0:
        g = jnp.clip(g, -attrs["clip_gradient"], attrs["clip_gradient"])
    g = g + attrs["wd"] * weight
    new_mom = attrs["momentum"] * mom - (1 - attrs["momentum"]) * g
    w = (1 - attrs["lr"] * attrs["wd_lh"]) * weight \
        + attrs["lr"] * jnp.sign(new_mom)
    return w, new_mom
