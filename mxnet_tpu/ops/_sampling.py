"""Shared bilinear-sampling kernel for the gather-based spatial ops.

One definition serves ROIAlign, DeformableConvolution/DeformablePSROIPooling
(contrib_det.py) and BilinearSampler/SpatialTransformer (spatial.py) — the
reference implements this gather five times over (roi_align.cc,
deformable_im2col.h, bilinear_sampler.cc, spatial_transformer.cc,
deformable_psroi_pooling.cc); here it is a single XLA-fusable program.
"""
from __future__ import annotations

import jax.numpy as jnp


def bilinear_sample(img, ys, xs):
    """Bilinear-sample ``img (C, H, W)`` at float coords, zero outside.

    ``ys``/``xs`` may be any (matching) shape S; returns ``(C,) + S``.
    Out-of-range taps contribute zero (the between-boundary rule shared by
    all the reference samplers).
    """
    h, w = img.shape[-2], img.shape[-1]
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    fy = ys - y0
    fx = xs - x0
    out = 0.0
    for dy, wy in ((0, 1 - fy), (1, fy)):
        for dx, wx in ((0, 1 - fx), (1, fx)):
            yy = (y0 + dy).astype(jnp.int32)
            xx = (x0 + dx).astype(jnp.int32)
            inb = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            v = img[:, jnp.clip(yy, 0, h - 1), jnp.clip(xx, 0, w - 1)]
            out = out + v * (wy * wx * inb)[None]
    return out
