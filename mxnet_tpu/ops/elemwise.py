"""Elementwise / scalar / comparison operator families.

Reference analog: ``src/operator/tensor/elemwise_binary_op*.cc``,
``elemwise_unary_op*.cc``, ``elemwise_binary_broadcast_op*.cc``,
``elemwise_binary_scalar_op*.cc``, ``elemwise_sum.cc`` — the "4-family"
elementwise ops (SURVEY.md N7).  On TPU these are single XLA HLO ops that the
compiler fuses into adjacent matmuls/convs (VPU work riding on MXU output),
so each is just its jnp expression; no hand kernels needed.

Naming parity: both the broadcast_* names and the legacy elemwise names /
``_plus``-style internal names are registered, matching what Symbol JSON files
and ``mx.nd`` users expect.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, param

__all__ = []


# --------------------------------------------------------------------------
# binary broadcasting ops
# --------------------------------------------------------------------------
_BINARY = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
    "mod": jnp.mod,
    "power": jnp.power,
    "maximum": jnp.maximum,
    "minimum": jnp.minimum,
    "hypot": jnp.hypot,
}

_LEGACY_BINARY_ALIAS = {  # elemwise (same-shape) names share the kernel
    "add": ("elemwise_add", "_plus", "_add"),
    "sub": ("elemwise_sub", "_minus", "_sub"),
    "mul": ("elemwise_mul", "_mul"),
    "div": ("elemwise_div", "_div"),
    "mod": ("_mod",),
    "power": ("_power", "_pow"),
    "maximum": ("_maximum",),
    "minimum": ("_minimum",),
    "hypot": ("_hypot",),
}

for _name, _f in _BINARY.items():
    register("broadcast_" + _name, nin=2,
             aliases=_LEGACY_BINARY_ALIAS.get(_name, ()))(
        (lambda f: lambda attrs, lhs, rhs: f(lhs, rhs))(_f))

_CMP = {
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
    "greater": jnp.greater,
    "greater_equal": jnp.greater_equal,
    "lesser": jnp.less,
    "lesser_equal": jnp.less_equal,
    "logical_and": jnp.logical_and,
    "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
}

for _name, _f in _CMP.items():
    # reference comparison ops return same-dtype 0/1 arrays, not bools
    register("broadcast_" + _name, nin=2, aliases=("_" + _name,))(
        (lambda f: lambda attrs, lhs, rhs:
            f(lhs, rhs).astype(jnp.result_type(lhs)))(_f))


# --------------------------------------------------------------------------
# binary scalar ops (attrs: scalar)
# --------------------------------------------------------------------------
_SCALAR_P = {"scalar": param(float, 0.0)}

_SCALAR_OPS = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(jnp.full_like(x, s), x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, jnp.asarray(s, x.dtype)),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
    "_logical_and_scalar": lambda x, s: jnp.logical_and(x, s).astype(x.dtype),
    "_logical_or_scalar": lambda x, s: jnp.logical_or(x, s).astype(x.dtype),
    "_logical_xor_scalar": lambda x, s: jnp.logical_xor(x, s).astype(x.dtype),
}

for _name, _f in _SCALAR_OPS.items():
    register(_name, params=dict(_SCALAR_P), nin=1)(
        (lambda f: lambda attrs, x: f(x, attrs["scalar"]))(_f))


# --------------------------------------------------------------------------
# unary math ops
# --------------------------------------------------------------------------
def _softrelu(x):
    return jnp.logaddexp(x, 0.0)


_UNARY = {
    "negative": jnp.negative,
    "abs": jnp.abs,
    "sign": jnp.sign,
    "round": jnp.round,
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,  # round toward zero (jnp.fix deprecated in jax 0.9)
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": lambda x: jax.scipy.special.gammaln(x),
    "erf": lambda x: jax.scipy.special.erf(x),
    "erfinv": lambda x: jax.scipy.special.erfinv(x),
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "softrelu": _softrelu,
    "reciprocal": jnp.reciprocal,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype),
}

for _name, _f in _UNARY.items():
    register(_name, nin=1)(
        (lambda f: lambda attrs, x: f(x))(_f))

register("_copy", nin=1, aliases=("identity",))(lambda attrs, x: x)
register("BlockGrad", nin=1, aliases=("stop_gradient",))(
    lambda attrs, x: jax.lax.stop_gradient(x))
register("make_loss", nin=1)(lambda attrs, x: x)

register("hard_sigmoid", nin=1,
         params={"alpha": param(float, 0.2), "beta": param(float, 0.5)})(
    lambda attrs, x: jnp.clip(attrs["alpha"] * x + attrs["beta"], 0.0, 1.0))

register("clip", nin=1, params={"a_min": param(float, 0.0, required=True),
                                "a_max": param(float, 0.0, required=True)})(
    lambda attrs, x: jnp.clip(x, attrs["a_min"], attrs["a_max"]))


@register("smooth_l1", nin=1, params={"scalar": param(float, 1.0)})
def _smooth_l1(attrs, x):
    """Huber-style loss used by SSD/RCNN (ref: src/operator/tensor/
    elemwise_binary_scalar_op_extended.cc smooth_l1)."""
    s2 = attrs["scalar"] ** 2
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0 / s2, 0.5 * s2 * x * x, ax - 0.5 / s2)


@register("add_n", nin=-1, aliases=("ElementWiseSum", "_sum"))
def _add_n(attrs, *xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out
