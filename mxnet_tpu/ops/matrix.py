"""Matrix / shape-manipulation / indexing operators.

Reference analog: ``src/operator/tensor/matrix_op.cc`` (reshape with MXNet's
0/-1/-2/-3/-4 codes, transpose, slice family, dot, concat/stack/split, tile,
repeat, pad, flip, space/depth), ``indexing_op.cc`` (take, one_hot, pick,
gather_nd, scatter_nd, Embedding), ``cast``.  All are XLA-native
(reshape/transpose are layout ops; dot/batch_dot hit the MXU directly).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, param
from ..base import MXNetError


def infer_reshape(src_shape, target, reverse=False):
    """MXNet reshape target semantics (matrix_op.cc ReshapeShape):
    0=keep, -1=infer, -2=copy rest, -3=merge two, -4=split (next 2 entries)."""
    src = list(src_shape)
    if reverse:
        src = src[::-1]
        target = tuple(target)[::-1]
    out = []
    i = 0  # index into src
    t = list(target)
    j = 0
    while j < len(t):
        d = t[j]
        if d == 0:
            out.append(src[i]); i += 1
        elif d == -1:
            out.append(-1); i += 1
        elif d == -2:
            out.extend(src[i:]); i = len(src)
        elif d == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif d == -4:
            d1, d2 = t[j + 1], t[j + 2]
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            out.extend([d1, d2]); i += 1; j += 2
        else:
            out.append(d)
            if i < len(src):
                i += 1
        j += 1
    if -1 in out:
        known = int(np.prod([d for d in out if d != -1])) or 1
        total = int(np.prod(src_shape)) if src_shape else 1
        out[out.index(-1)] = total // known
    if reverse:
        out = out[::-1]
    return tuple(int(d) for d in out)


@register("Reshape", nin=1, aliases=("reshape",),
          params={"shape": param("shape", ()), "reverse": param(bool, False),
                  "target_shape": param("shape", ()),
                  "keep_highest": param(bool, False)})
def _reshape(attrs, x):
    tgt = attrs["shape"] or attrs["target_shape"]
    return jnp.reshape(x, infer_reshape(x.shape, tgt, attrs["reverse"]))


@register("Flatten", nin=1, aliases=("flatten",))
def _flatten(attrs, x):
    return jnp.reshape(x, (x.shape[0], -1))


@register("transpose", nin=1, params={"axes": param("shape", ())})
def _transpose(attrs, x):
    axes = attrs["axes"] or None
    return jnp.transpose(x, axes)


@register("expand_dims", nin=1, params={"axis": param(int, 0, required=True)})
def _expand_dims(attrs, x):
    return jnp.expand_dims(x, attrs["axis"])


@register("squeeze", nin=1, params={"axis": param("shape", None)})
def _squeeze(attrs, x):
    ax = attrs["axis"]
    return jnp.squeeze(x, axis=tuple(a % x.ndim for a in ax) if ax else None)


@register("slice", nin=1, aliases=("crop",),
          params={"begin": param("shape", ()), "end": param("shape", ()),
                  "step": param("shape", ())})
def _slice(attrs, x):
    idx = []
    step = attrs["step"] or (None,) * len(attrs["begin"])
    for b, e, s in zip(attrs["begin"], attrs["end"], step):
        idx.append(slice(None if b in (None, "None") else b,
                         None if e in (None, "None") else e,
                         None if s in (None, 0, "None") else s))
    return x[tuple(idx)]


@register("slice_axis", nin=1,
          params={"axis": param(int, 0, required=True),
                  "begin": param(int, 0, required=True),
                  "end": param("shape", None)})
def _slice_axis(attrs, x):
    ax = attrs["axis"] % x.ndim
    end = attrs["end"]
    end = None if end in (None, ()) else int(end[0]) if isinstance(end, tuple) else int(end)
    idx = [slice(None)] * x.ndim
    idx[ax] = slice(attrs["begin"], end)
    return x[tuple(idx)]


@register("slice_like", nin=2, params={"axes": param("shape", ())})
def _slice_like(attrs, x, like):
    axes = attrs["axes"] or tuple(range(x.ndim))
    idx = [slice(None)] * x.ndim
    for a in axes:
        a = a % x.ndim
        idx[a] = slice(0, like.shape[a])
    return x[tuple(idx)]


@register("dot", nin=2, params={"transpose_a": param(bool, False),
                                "transpose_b": param(bool, False)})
def _dot(attrs, a, b):
    """MXU matmul.  Reference dot (matrix_op.cc) contracts the last axis of a
    with the first of b for ndim>2; fp32 accumulation is preserved."""
    if attrs["transpose_a"]:
        a = jnp.transpose(a, tuple(range(1, a.ndim)) + (0,)) if a.ndim > 2 else a.T
    if attrs["transpose_b"]:
        b = jnp.transpose(b, (b.ndim - 1,) + tuple(range(b.ndim - 1))) if b.ndim > 2 else b.T
    return jnp.tensordot(a, b, axes=1)


@register("batch_dot", nin=2, params={"transpose_a": param(bool, False),
                                      "transpose_b": param(bool, False)})
def _batch_dot(attrs, a, b):
    if attrs["transpose_a"]:
        a = jnp.swapaxes(a, -1, -2)
    if attrs["transpose_b"]:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register("Concat", nin=-1, aliases=("concat",),
          params={"dim": param(int, 1), "num_args": param(int, 0)})
def _concat(attrs, *xs):
    return jnp.concatenate(xs, axis=attrs["dim"])


@register("stack", nin=-1, params={"axis": param(int, 0),
                                   "num_args": param(int, 0)})
def _stack(attrs, *xs):
    return jnp.stack(xs, axis=attrs["axis"])


def _split_nout(attrs):
    return 1 if attrs.get("squeeze_axis") and attrs["num_outputs"] == 1 \
        else attrs["num_outputs"]


@register("SliceChannel", nin=1, aliases=("split",),
          params={"num_outputs": param(int, 1, required=True),
                  "axis": param(int, 1), "squeeze_axis": param(bool, False)},
          nout=lambda attrs: attrs["num_outputs"])
def _split(attrs, x):
    parts = jnp.split(x, attrs["num_outputs"], axis=attrs["axis"])
    if attrs["squeeze_axis"]:
        parts = [jnp.squeeze(p, axis=attrs["axis"]) for p in parts]
    return tuple(parts) if len(parts) > 1 else parts[0]


@register("tile", nin=1, params={"reps": param("shape", (), required=True)})
def _tile(attrs, x):
    return jnp.tile(x, attrs["reps"])


@register("repeat", nin=1, params={"repeats": param(int, 1, required=True),
                                   "axis": param("shape", None)})
def _repeat(attrs, x):
    ax = attrs["axis"]
    return jnp.repeat(x, attrs["repeats"],
                      axis=None if ax is None else int(ax[0]))


@register("Pad", nin=1, aliases=("pad",),
          params={"mode": param(["constant", "edge", "reflect"], "constant"),
                  "pad_width": param("shape", (), required=True),
                  "constant_value": param(float, 0.0)})
def _pad(attrs, x):
    pw = attrs["pad_width"]
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    if attrs["mode"] == "constant":
        return jnp.pad(x, pairs, constant_values=attrs["constant_value"])
    return jnp.pad(x, pairs, mode=attrs["mode"])


@register("reverse", nin=1, aliases=("flip",),
          params={"axis": param("shape", (), required=True)})
def _reverse(attrs, x):
    out = x
    for a in attrs["axis"]:
        out = jnp.flip(out, axis=a)
    return out


@register("SwapAxis", nin=1, aliases=("swapaxes",),
          params={"dim1": param(int, 0), "dim2": param(int, 0)})
def _swapaxes(attrs, x):
    return jnp.swapaxes(x, attrs["dim1"], attrs["dim2"])


@register("depth_to_space", nin=1, params={"block_size": param(int, 1, required=True)})
def _depth_to_space(attrs, x):
    b = attrs["block_size"]
    n, c, h, w = x.shape
    y = x.reshape(n, b, b, c // (b * b), h, w)
    y = jnp.transpose(y, (0, 3, 4, 1, 5, 2))
    return y.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth", nin=1, params={"block_size": param(int, 1, required=True)})
def _space_to_depth(attrs, x):
    b = attrs["block_size"]
    n, c, h, w = x.shape
    y = x.reshape(n, c, h // b, b, w // b, b)
    y = jnp.transpose(y, (0, 3, 5, 1, 2, 4))
    return y.reshape(n, c * b * b, h // b, w // b)


@register("reorg", nin=1, aliases=("newreorg",),
          params={"stride": param(int, 2)})
def _reorg(attrs, x):
    """YOLO-style reorg from the yangyu12 fork (src/operator/nn/reorg.cc):
    space-to-depth with stride s on NCHW."""
    s = attrs["stride"]
    n, c, h, w = x.shape
    y = x.reshape(n, c, h // s, s, w // s, s)
    y = jnp.transpose(y, (0, 1, 3, 5, 2, 4))
    return y.reshape(n, c * s * s, h // s, w // s)


# --------------------------------------------------------------------------
# indexing ops
# --------------------------------------------------------------------------
@register("take", nin=2, params={"axis": param(int, 0),
                                 "mode": param(["clip", "wrap", "raise"], "clip")})
def _take(attrs, a, indices):
    return jnp.take(a, indices.astype(jnp.int32), axis=attrs["axis"],
                    mode="clip" if attrs["mode"] == "raise" else attrs["mode"])


@register("batch_take", nin=2)
def _batch_take(attrs, a, indices):
    return jnp.take_along_axis(
        a, indices.astype(jnp.int32).reshape(-1, 1), axis=1).reshape(indices.shape)


@register("one_hot", nin=1, params={"depth": param(int, 0, required=True),
                                    "on_value": param(float, 1.0),
                                    "off_value": param(float, 0.0),
                                    "dtype": param("dtype", "float32")})
def _one_hot(attrs, indices):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), attrs["depth"],
                        dtype=np.dtype(attrs["dtype"] or "float32"))
    return oh * (attrs["on_value"] - attrs["off_value"]) + attrs["off_value"]


@register("pick", nin=2, params={"axis": param("shape", (-1,)),
                                 "keepdims": param(bool, False),
                                 "mode": param(["clip", "wrap"], "clip")})
def _pick(attrs, x, index):
    ax = attrs["axis"]
    axis = int(ax[0]) % x.ndim if ax else x.ndim - 1
    idx = jnp.expand_dims(index.astype(jnp.int32), axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return out if attrs["keepdims"] else jnp.squeeze(out, axis=axis)


@register("where", nin=3)
def _where(attrs, cond, x, y):
    return jnp.where(cond.astype(bool), x, y)


@register("gather_nd", nin=2)
def _gather_nd(attrs, data, indices):
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return data[idx]


@register("scatter_nd", nin=2, params={"shape": param("shape", (), required=True)})
def _scatter_nd(attrs, data, indices):
    out = jnp.zeros(attrs["shape"], dtype=data.dtype)
    idx = tuple(indices[i].astype(jnp.int32) for i in range(indices.shape[0]))
    return out.at[idx].add(data)


@register("Embedding", nin=2, aliases=("embedding",),
          params={"input_dim": param(int, 0, required=True),
                  "output_dim": param(int, 0, required=True),
                  "dtype": param("dtype", "float32"),
                  "sparse_grad": param(bool, False)})
def _embedding(attrs, data, weight):
    """Embedding lookup = one_hot @ weight on MXU for tiny vocab, or gather.
    XLA picks the gather path; sparse_grad handled by optimizer-side rowwise
    updates (ref: src/operator/tensor/indexing_op.cc Embedding)."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register("_contrib_SparseEmbedding", nin=2,
          params={"input_dim": param(int, 0, required=True),
                  "output_dim": param(int, 0, required=True),
                  "dtype": param("dtype", "float32")})
def _sparse_embedding(attrs, data, weight):
    """Embedding whose weight gradient is row-sparse (ref:
    src/operator/tensor/indexing_op.cc _contrib_SparseEmbedding).  Compute
    is the same XLA gather as Embedding; the row-sparse gradient contract
    is honored by the trainer/kvstore layer (row_sparse_pull of touched
    rows), which is where TPU sparsity lives."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register("sparse_retain", nin=2, aliases=("_sparse_retain",))
def _sparse_retain_op(attrs, data, indices):
    """Dense view of sparse_retain: zero every row of ``data`` whose index
    is not in ``indices`` (ref: src/operator/tensor/sparse_retain.cc:27).
    For RowSparseNDArray inputs the frontend dispatches to
    ndarray.sparse.retain, which keeps the result row_sparse."""
    rows = jnp.arange(data.shape[0])
    mask = jnp.isin(rows, indices.astype(jnp.int32))
    return jnp.where(mask.reshape((-1,) + (1,) * (data.ndim - 1)), data,
                     jnp.zeros_like(data))


@register("Cast", nin=1, aliases=("cast",),
          params={"dtype": param("dtype", "float32", required=True)})
def _cast(attrs, x):
    return x.astype(np.dtype(attrs["dtype"]))


@register("amp_cast", nin=1, params={"dtype": param("dtype", "float32")})
def _amp_cast(attrs, x):
    return x.astype(np.dtype(attrs["dtype"] or "float32"))


register("zeros_like", nin=1)(lambda attrs, x: jnp.zeros_like(x))
register("ones_like", nin=1)(lambda attrs, x: jnp.ones_like(x))
register("shape_array", nin=1)(
    lambda attrs, x: jnp.asarray(x.shape, dtype=jnp.int64))
register("size_array", nin=1)(
    lambda attrs, x: jnp.asarray([x.size], dtype=jnp.int64))
register("reshape_like", nin=2)(
    lambda attrs, x, like: jnp.reshape(x, like.shape))


@register("diag", nin=1, params={"k": param(int, 0)})
def _diag(attrs, x):
    if x.ndim == 1:
        return jnp.diag(x, k=attrs["k"])
    return jnp.diagonal(x, offset=attrs["k"], axis1=-2, axis2=-1)


# --------------------------------------------------------------------------
# sequence ops (ref: src/operator/sequence_*.cc) — used by RNN/bucketing
# --------------------------------------------------------------------------
@register("SequenceMask", nin=-1, aliases=("sequence_mask",),
          params={"use_sequence_length": param(bool, False),
                  "value": param(float, 0.0), "axis": param(int, 0)})
def _sequence_mask(attrs, data, *maybe_len):
    if not attrs["use_sequence_length"] or not maybe_len:
        return data
    seq_len = maybe_len[0]
    ax = attrs["axis"]  # time axis: 0 or 1
    T = data.shape[ax]
    steps = jnp.arange(T)
    shape = [1] * data.ndim
    shape[ax] = T
    steps = steps.reshape(shape)
    lens_shape = [1] * data.ndim
    batch_ax = 1 - ax
    lens_shape[batch_ax] = data.shape[batch_ax]
    mask = steps < seq_len.astype(jnp.int32).reshape(lens_shape)
    return jnp.where(mask, data, attrs["value"])


@register("SequenceLast", nin=-1, aliases=("sequence_last",),
          params={"use_sequence_length": param(bool, False),
                  "axis": param(int, 0)})
def _sequence_last(attrs, data, *maybe_len):
    ax = attrs["axis"]
    if not attrs["use_sequence_length"] or not maybe_len:
        return jnp.take(data, data.shape[ax] - 1, axis=ax)
    seq_len = maybe_len[0].astype(jnp.int32) - 1
    idx = jnp.expand_dims(seq_len, axis=ax)
    while idx.ndim < data.ndim:
        idx = jnp.expand_dims(idx, -1)
    idx = jnp.broadcast_to(idx, data.shape[:ax] + (1,) + data.shape[ax + 1:])
    return jnp.squeeze(jnp.take_along_axis(data, idx, axis=ax), axis=ax)


@register("SequenceReverse", nin=-1, aliases=("sequence_reverse",),
          params={"use_sequence_length": param(bool, False),
                  "axis": param(int, 0)})
def _sequence_reverse(attrs, data, *maybe_len):
    if not attrs["use_sequence_length"] or not maybe_len:
        return jnp.flip(data, axis=0)
    seq_len = maybe_len[0].astype(jnp.int32)
    T = data.shape[0]
    t = jnp.arange(T)[:, None]
    src = jnp.where(t < seq_len[None, :], seq_len[None, :] - 1 - t, t)
    idx = src
    while idx.ndim < data.ndim:
        idx = idx[..., None]
    idx = jnp.broadcast_to(idx, data.shape)
    return jnp.take_along_axis(data, idx, axis=0)
