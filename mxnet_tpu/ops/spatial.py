"""Spatial-transform ops: BilinearSampler, GridGenerator, SpatialTransformer,
Correlation, SVMOutput.

Reference analogs:
- ``BilinearSampler`` — src/operator/bilinear_sampler-inl.h (STN sampler:
  grid (N, 2, Ho, Wo) with channel 0 = x, 1 = y in [-1, 1]; zero padding
  outside).
- ``GridGenerator`` — src/operator/grid_generator-inl.h:56-130 (affine:
  (N, 6) theta x normalized target grid; warp: optical flow + identity,
  normalized).
- ``SpatialTransformer`` — src/operator/spatial_transformer-inl.h:59-63
  (= affine GridGenerator + BilinearSampler fused).
- ``Correlation`` — src/operator/correlation-inl.h:53-63, correlation.cc:
  41-82 (FlowNet cost volume: displacement-grid inner products,
  normalized by kernel²·C).
- ``SVMOutput`` — src/operator/svm_output-inl.h:56-62, svm_output.cc:30-67
  (identity forward; L1/L2 margin hinge gradient as custom VJP).

TPU-native design: the samplers are gather+weight tensor programs (vmapped
over batch) and the correlation op is a static displacement-grid loop of
elementwise multiplies + channel reductions — all static shapes, XLA-fusable,
gradients via jax.vjp (reference hand-writes each backward kernel).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, param
from ._sampling import bilinear_sample


@register("BilinearSampler", nin=2, aliases=("bilinearsampler",))
def _bilinear_sampler(attrs, data, grid):
    """STN bilinear sampler: data (N, C, H, W), grid (N, 2, Ho, Wo) with
    x = grid[:, 0], y = grid[:, 1] in [-1, 1]."""
    h, w = data.shape[2], data.shape[3]

    def one(img, g):
        xs = (g[0] + 1.0) * (w - 1) / 2.0
        ys = (g[1] + 1.0) * (h - 1) / 2.0
        return bilinear_sample(img, ys, xs)

    return jax.vmap(one)(data, grid).astype(data.dtype)


@register("GridGenerator", nin=1, nout=2, visible=1,
          aliases=("gridgenerator",),
          params={"transform_type": param(["affine", "warp"], None,
                                          required=True),
                  "target_shape": param("shape", (0, 0))})
def _grid_generator(attrs, data):
    """Sampling-grid generator (grid_generator-inl.h:86-130).

    affine: data (N, 6) -> grid (N, 2, H, W) = theta @ [x_t; y_t; 1]
    warp:   data = flow (N, 2, H, W) -> (flow + pixel grid) normalized
    Second (hidden) output is the reference's grid_dst workspace.
    """
    if attrs["transform_type"] == "affine":
        th, tw = attrs["target_shape"]
        xs = -1.0 + np.arange(tw) * (2.0 / (tw - 1)) if tw > 1 \
            else np.zeros(tw)
        ys = -1.0 + np.arange(th) * (2.0 / (th - 1)) if th > 1 \
            else np.zeros(th)
        gx, gy = np.meshgrid(xs, ys)
        dst = jnp.asarray(np.stack([gx.ravel(), gy.ravel(),
                                    np.ones(th * tw)], 0), data.dtype)
        theta = data.reshape(-1, 2, 3)
        out = jnp.matmul(theta, dst,
                         precision=lax.Precision.HIGHEST).reshape(-1, 2, th, tw)
        return out.astype(data.dtype), dst
    # warp
    n, _, h, w = data.shape
    gx, gy = np.meshgrid(np.arange(w, dtype=np.float32),
                         np.arange(h, dtype=np.float32))
    base = jnp.asarray(np.stack([gx, gy], 0), data.dtype)   # (2, H, W)
    denom = jnp.asarray(
        np.array([(w - 1) / 2.0, (h - 1) / 2.0], np.float32)
    ).reshape(1, 2, 1, 1)
    out = (data + base[None]) / denom - 1.0
    return out.astype(data.dtype), base


@register("SpatialTransformer", nin=2, nout=2, visible=1,
          aliases=("spatialtransformer",),
          params={"target_shape": param("shape", (0, 0)),
                  "transform_type": param(["affine"], "affine"),
                  "sampler_type": param(["bilinear"], "bilinear")})
def _spatial_transformer(attrs, data, loc):
    """Affine STN (spatial_transformer-inl.h): grid = theta @ target grid,
    then bilinear sampling of data.  loc (N, 6)."""
    th, tw = attrs["target_shape"]
    h, w = data.shape[2], data.shape[3]
    xs = -1.0 + np.arange(tw) * (2.0 / (tw - 1)) if tw > 1 else np.zeros(tw)
    ys = -1.0 + np.arange(th) * (2.0 / (th - 1)) if th > 1 else np.zeros(th)
    gx, gy = np.meshgrid(xs, ys)
    dst = jnp.asarray(np.stack([gx.ravel(), gy.ravel(), np.ones(th * tw)], 0),
                      data.dtype)
    grid = jnp.matmul(loc.reshape(-1, 2, 3), dst,
                      precision=lax.Precision.HIGHEST)   # (N, 2, th*tw)

    def one(img, g):
        xr = (g[0] + 1.0) * (w - 1) / 2.0
        yr = (g[1] + 1.0) * (h - 1) / 2.0
        return bilinear_sample(img, yr, xr)

    out = jax.vmap(one)(data, grid)                         # (N, C, th*tw)
    out = out.reshape(data.shape[0], data.shape[1], th, tw)
    return out.astype(data.dtype), grid.reshape(-1, 2, th, tw)


@register("Correlation", nin=2, nout=3, visible=1,
          aliases=("correlation",),
          params={"kernel_size": param(int, 1),
                  "max_displacement": param(int, 1),
                  "stride1": param(int, 1),
                  "stride2": param(int, 1),
                  "pad_size": param(int, 0),
                  "is_multiply": param(bool, True)})
def _correlation(attrs, data1, data2):
    """FlowNet correlation / cost volume (correlation.cc:41-82).

    out[n, (p,o), i, j] = sum over kernel window & channels of
    data1[window at (i,j)] * data2[window shifted by (p,o)*stride2],
    normalized by kernel²·C.  Hidden outputs = the reference's padded
    workspaces (tmp1, tmp2).
    """
    ks = attrs["kernel_size"]
    md = attrs["max_displacement"]
    s1, s2 = attrs["stride1"], attrs["stride2"]
    pad = attrs["pad_size"]
    kr = (ks - 1) // 2
    border = md + kr
    n, c, h, w = data1.shape
    ph, pw = h + 2 * pad, w + 2 * pad
    top_h = int(np.ceil((ph - 2 * border) / s1))
    top_w = int(np.ceil((pw - 2 * border) / s1))
    ngr = md // s2
    ngw = 2 * ngr + 1
    sumelems = ks * ks * c

    d1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    d2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    iy = md + np.arange(top_h) * s1
    ix = md + np.arange(top_w) * s1

    outs = []
    for p in range(-ngr, ngr + 1):
        for o in range(-ngr, ngr + 1):
            acc = 0.0
            for kh in range(ks):
                for kw in range(ks):
                    a = d1[:, :, iy + kh][:, :, :, ix + kw]
                    b = d2[:, :, iy + kh + p * s2][:, :, :, ix + kw + o * s2]
                    if attrs["is_multiply"]:
                        acc = acc + jnp.sum(a * b, axis=1)
                    else:
                        acc = acc + jnp.sum(jnp.abs(a - b), axis=1)
            outs.append(acc / sumelems)
    out = jnp.stack(outs, axis=1).astype(data1.dtype)       # (N, ngw², th, tw)
    return out, d1, d2


@register("SVMOutput", nin=2, aliases=("svmoutput",),
          params={"margin": param(float, 1.0),
                  "regularization_coefficient": param(float, 1.0),
                  "use_linear": param(bool, False)})
def _svm_output(attrs, data, label):
    """SVM output layer (svm_output.cc:30-67): identity forward; backward
    is the L1/L2 margin hinge gradient (incoming head gradient ignored,
    like SoftmaxOutput)."""
    margin = attrs["margin"]
    reg = attrs["regularization_coefficient"]
    l2 = not attrs["use_linear"]

    @jax.custom_vjp
    def _fwd(d, l):
        return d

    def _fwd_fwd(d, l):
        return d, (d, l)

    def _fwd_bwd(res, g):
        d, l = res
        lab = l.astype(jnp.int32)
        is_k = jax.nn.one_hot(lab, d.shape[1], dtype=bool, axis=-1)
        if l2:
            gk = jnp.where(margin > d, -2.0 * reg * (margin - d), 0.0)
            gx = jnp.where(margin > -d, 2.0 * reg * (margin + d), 0.0)
        else:
            gk = -reg * (margin > d)
            gx = reg * (margin > -d)
        grad = jnp.where(is_k, gk, gx).astype(d.dtype)
        return grad, jnp.zeros_like(l)

    _fwd.defvjp(_fwd_fwd, _fwd_bwd)
    return _fwd(data, label)
