"""Neural-network operators: Convolution, FullyConnected, Pooling, norms,
softmax family, Dropout, activations, UpSampling.

Reference analog: ``src/operator/nn/*`` (convolution.cc:476-519 is the
canonical registration; batch_norm.cc, pooling.cc, fully_connected.cc,
softmax.cc, dropout.cc, layer_norm.cc, lrn.cc, upsampling.cc) plus the cuDNN
fast paths (``src/operator/nn/cudnn/``).  TPU-native design: convolutions and
FC lower straight onto the MXU via ``lax.conv_general_dilated`` / ``dot``; the
cuDNN algo-selection machinery has no analog because XLA picks conv strategies
itself.  NCHW is kept as the user-facing layout (reference default); XLA
relayouts internally for the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, param
from ..base import MXNetError
from .. import telemetry as _telemetry

# Trace-time dispatch mix of the Convolution formulations (one inc per
# compiled specialization, not per step — executables are cached).  Lets
# /metrics answer "which conv path did this process actually take".
_CONV_DISPATCH = _telemetry.counter(
    "conv_dispatch_total",
    "Convolution dispatch decisions by formulation path (trace-time)",
    ("path",))


def _spatial_dims(kernel):
    return len(kernel)


def _conv_dnums(nd):
    sp = "DHW"[-nd:] if nd <= 3 else None
    return jax.lax.conv_dimension_numbers(
        (1, 1) + (1,) * nd, (1, 1) + (1,) * nd,
        ("NC" + sp, "OI" + sp, "NC" + sp))


_CONV_PARAMS = {
    "kernel": param("shape", (), required=True),
    "stride": param("shape", ()),
    "dilate": param("shape", ()),
    "pad": param("shape", ()),
    "num_filter": param(int, 0, required=True),
    "num_group": param(int, 1),
    "no_bias": param(bool, False),
    "workspace": param(int, 1024),      # accepted, ignored (XLA owns memory)
    "cudnn_tune": param(str, None),     # accepted, ignored on TPU
    "cudnn_off": param(bool, False),
    "layout": param(str, None),
}


def _stem_s2d_eligible(attrs, data, nd):
    """True for thin-input stride-2 2-D stems (e.g. ResNet 7x7s2 on RGB).

    The MXU pads the contraction dim to a full lane tile, so C_in=3 convs
    run at <25 TF while C_in>=64 convs reach 150+ TF (measured,
    docs/perf_analysis.md round 3).  Space-to-depth(2) rewrites the conv
    EXACTLY into a stride-1 conv on 4x the channels.
    """
    import os
    if os.environ.get("MXNET_TPU_STEM_S2D", "1") == "0":
        return False
    if nd != 2 or attrs["num_group"] != 1:
        return False
    stride = attrs["stride"] or (1,) * nd
    dilate = attrs["dilate"] or (1,) * nd
    k = attrs["kernel"]
    if stride != (2, 2) or dilate != (1, 1):
        return False
    if data.shape[1] > 4 or data.shape[2] % 2 or data.shape[3] % 2:
        return False
    return k[0] % 2 == 1 and k[1] % 2 == 1 and k[0] > 1


def _stem_s2d_conv(attrs, data, weight):
    """stride-2 kxk conv on (N,C,H,W) == stride-1 conv on space-to-depth(2).

    y[ho] = sum_dh x[2*ho + dh - pad]; writing dh - pad = 2e + p maps tap
    dh to s2d parity plane p at spatial offset e — a ceil(k/2)-tap
    stride-1 conv over the (N, 4C, H/2, W/2) s2d input (exact rewrite;
    the TPU-MLPerf ResNet stem trick).
    """
    k = attrs["kernel"]
    pad = attrs["pad"] or (0, 0)
    N, C, H, W = data.shape
    O = weight.shape[0]

    def tap_range(kk, p):
        e0 = -(p // 2) - (p % 2)            # floor((0 - p) / 2)
        e1 = (kk - 1 - p) // 2
        return e0, e1
    eh0, eh1 = tap_range(k[0], pad[0])
    ew0, ew1 = tap_range(k[1], pad[1])
    kh, kw = eh1 - eh0 + 1, ew1 - ew0 + 1

    # kernel transform is itself an inverse space-to-depth: shift w so tap
    # dh aligns with (2*e' + p), then fold each spatial parity into the
    # channel dim — layout (p, q, c) -> p*2C + q*C + c, matching x below
    lh, lw = -(2 * eh0 + pad[0]), -(2 * ew0 + pad[1])
    wp = jnp.pad(weight, ((0, 0), (0, 0),
                          (lh, 2 * kh - k[0] - lh),
                          (lw, 2 * kw - k[1] - lw)))
    w4 = wp.reshape(O, C, kh, 2, kw, 2)
    w4 = w4.transpose(0, 3, 5, 1, 2, 4).reshape(O, 4 * C, kh, kw)

    xs = data.reshape(N, C, H // 2, 2, W // 2, 2)
    xs = xs.transpose(0, 3, 5, 1, 2, 4).reshape(N, 4 * C, H // 2, W // 2)
    # high pad sized so the output length matches the strided original:
    # Ho = (H + 2p - k)//2 + 1
    ho = (H + 2 * pad[0] - k[0]) // 2 + 1
    wo = (W + 2 * pad[1] - k[1]) // 2 + 1
    return jax.lax.conv_general_dilated(
        xs, w4, window_strides=(1, 1),
        padding=[(-eh0, ho + kh - H // 2 + eh0 - 1),
                 (-ew0, wo + kw - W // 2 + ew0 - 1)],
        dimension_numbers=_conv_dnums(2))


def _is_3x3_same_unit(attrs, data, nd):
    """Shared shape predicate: 2-D / 3x3 kernel / stride 1 / dilate 1 /
    SAME pad / ungrouped — the class both GEMM formulations cover."""
    k = attrs["kernel"]
    return (nd == 2 and tuple(k) == (3, 3)
            and tuple(attrs["stride"] or (1, 1)) == (1, 1)
            and tuple(attrs["dilate"] or (1, 1)) == (1, 1)
            and tuple(attrs["pad"] or (0, 0)) == (1, 1)
            and attrs["num_group"] == 1 and data.ndim == 4)


def _nhwc_taps(data):
    """Yield the nine SAME-padded NHWC tap views flattened to
    (N*H*W, C) — the shared building block of both 9-GEMM forms."""
    N, C, H, W = data.shape
    xh = jnp.transpose(data, (0, 2, 3, 1))               # NHWC
    xp = jnp.pad(xh, ((0, 0), (1, 1), (1, 1), (0, 0)))
    for dy in range(3):
        for dx in range(3):
            yield dy, dx, xp[:, dy:dy + H, dx:dx + W, :].reshape(
                N * H * W, C)


def _shifted_gemm_eligible(attrs, data, nd):
    """3x3 / stride 1 / dilate 1 / SAME / ungrouped 2-D convs can run as
    9 shifted GEMMs — measured STABLE at 175-191 TF on v5e in chained
    blocks where the lax.conv lowering is bimodal across processes
    (136 TF fast mode, ~21 TF slow mode; tools/probe_fused_block.py).
    E2E-MEASURED AND REJECTED as a default: inside the full ResNet-50
    training graph the same formulation collapses to 125 img/s (~18x
    slower than lax.conv) — the chain win does not survive whole-graph
    scheduling (docs/perf_analysis.md round-4 probe).  Kept behind
    MXNET_TPU_CONV_SHIFTED_GEMM=1 as a probing tool.  The flag is read
    at TRACE time and is part of Convolution's jit-cache key
    (``env_keys`` in ops/registry.py), so toggling it takes effect on
    the next call — no cache clearing or process restart needed."""
    import os
    if os.environ.get("MXNET_TPU_CONV_SHIFTED_GEMM", "0") != "1":
        return False
    return _is_3x3_same_unit(attrs, data, nd)


def _shifted_gemm_conv(data, weight):
    """NCHW 3x3 SAME conv as 9 shifted (NHW, C)x(C, O) GEMMs."""
    N, C, H, W = data.shape
    O = weight.shape[0]
    acc = None
    for dy, dx, tap in _nhwc_taps(data):
        wk = weight[:, :, dy, dx].T                      # (C, O)
        # f32 accumulation across the 9 taps (matches lax.conv's
        # single f32 accumulate and the probe formulation — bf16
        # partial rounding would change the numerics being compared)
        part = jax.lax.dot_general(
            tap, wk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc = part if acc is None else acc + part
    return jnp.transpose(acc.reshape(N, H, W, O),
                         (0, 3, 1, 2)).astype(data.dtype)


def _gemm_wgrad_eligible(attrs, data, nd):
    """3x3 / stride 1 / SAME / ungrouped convs at SMALL spatial dims get
    a hand 9-GEMM weight-gradient formulation: tools/probe_wgrad.py
    (round 5, v5e) measured XLA's chosen wgrad lowering at 90 TF (14px)
    and 61 TF (7px) while the per-tap GEMM form hits 178/128 TF — ~2x —
    with XLA winning at 56/28px (259/307 TF), hence the H<=16 gate.
    Forward and dgrad stay on lax.conv; only the VJP's dw changes.
    E2e-measured OFF-worthy (2,445 vs 2,497 img/s — see
    docs/perf_analysis.md round 5); enable with MXNET_TPU_GEMM_WGRAD=1.
    Like MXNET_TPU_CONV_SHIFTED_GEMM, the flag is read at TRACE time and
    is part of Convolution's jit-cache key (``env_keys`` in
    ops/registry.py), so toggling it takes effect on the next call."""
    import os
    if os.environ.get("MXNET_TPU_GEMM_WGRAD", "0") != "1":
        return False
    return (_is_3x3_same_unit(attrs, data, nd)
            and data.shape[2] <= 16 and data.shape[3] <= 16)


@jax.custom_vjp
def _conv3x3_same_gemm_wgrad(data, weight):
    """3x3 SAME conv whose VJP computes dw as 9 per-tap GEMMs (dgrad
    stays the standard transposed conv)."""
    return jax.lax.conv_general_dilated(
        data, weight, (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=_conv_dnums(2))


def _c3g_fwd(data, weight):
    return _conv3x3_same_gemm_wgrad(data, weight), (data, weight)


def _c3g_bwd(res, g):
    data, weight = res
    N, C, H, W = data.shape
    O = weight.shape[0]
    # dgrad: conv of g with the spatially-flipped, io-swapped kernel
    wT = jnp.flip(weight.transpose(1, 0, 2, 3), axis=(2, 3))
    dx = jax.lax.conv_general_dilated(
        g, wT.astype(g.dtype), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=_conv_dnums(2)).astype(data.dtype)
    # wgrad: dw[o,c,dy,dx] = sum_nhw x_pad[n,c,h+dy,w+dx] g[n,o,h,w] —
    # one (NHW,C)x(NHW,O) GEMM per tap, f32 accumulation
    g2 = jnp.transpose(g, (0, 2, 3, 1)).reshape(N * H * W, O)
    taps = [jax.lax.dot_general(tap, g2, (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            for _, _, tap in _nhwc_taps(data)]           # each (C, O)
    dw = jnp.stack(taps).reshape(3, 3, C, O).transpose(3, 2, 0, 1)
    return dx, dw.astype(weight.dtype)


_conv3x3_same_gemm_wgrad.defvjp(_c3g_fwd, _c3g_bwd)


def _pallas_conv_mode(attrs, data, nd):
    """Return "s1" / "s2" when the Pallas implicit-GEMM kernels
    (ops/pallas_conv.py) cover this conv, else None.

    "s1" = the `_is_3x3_same_unit` class with full lane tiles and a
    VMEM-feasible plan; "s2" = 3x3 / stride-2 / pad-1, run through the
    exact space-to-depth rewrite.  Gated by MXNET_TPU_PALLAS_CONV
    (default OFF — every prior hand-conv formulation won its isolated
    chain and lost e2e; see docs/perf_analysis.md round 6).  The flag is
    part of Convolution's jit-cache key, so toggling takes effect on the
    next call."""
    import os
    if os.environ.get("MXNET_TPU_PALLAS_CONV", "0") != "1":
        return None
    if nd != 2 or data.ndim != 4 or attrs["num_group"] != 1:
        return None
    from . import pallas_conv
    N, C, H, W = data.shape
    O = attrs["num_filter"]
    if _is_3x3_same_unit(attrs, data, nd):
        if pallas_conv.conv3x3_same_available(N, H, W, C, O, data.dtype):
            return "s1"
        return None
    if (tuple(attrs["kernel"]) == (3, 3)
            and tuple(attrs["stride"] or (1, 1)) == (2, 2)
            and tuple(attrs["dilate"] or (1, 1)) == (1, 1)
            and tuple(attrs["pad"] or (0, 0)) == (1, 1)
            and pallas_conv.conv3x3_s2_available(N, H, W, C, O, data.dtype)):
        return "s2"
    return None


@register("Convolution", nin=-1, aliases=("convolution", "Convolution_v1"),
          params=dict(_CONV_PARAMS),
          env_keys=("MXNET_TPU_PALLAS_CONV", "MXNET_TPU_CONV_SHIFTED_GEMM",
                    "MXNET_TPU_GEMM_WGRAD", "MXNET_TPU_STEM_S2D"))
def _convolution(attrs, data, weight, *maybe_bias):
    """N-D convolution on the MXU (ref: src/operator/nn/convolution.cc)."""
    k = attrs["kernel"]
    nd = len(k)
    stride = attrs["stride"] or (1,) * nd
    dilate = attrs["dilate"] or (1,) * nd
    pad = attrs["pad"] or (0,) * nd
    pallas_mode = _pallas_conv_mode(attrs, data, nd)
    if _stem_s2d_eligible(attrs, data, nd):
        path = "s2d_stem"
        out = _stem_s2d_conv(attrs, data, weight)
    elif pallas_mode is not None:
        from . import pallas_conv
        if pallas_mode == "s1":
            path = "pallas"
            out = pallas_conv.conv3x3_same(data, weight)
        else:
            path = "pallas_s2"
            out = pallas_conv.conv3x3_s2(data, weight)
    elif _shifted_gemm_eligible(attrs, data, nd):
        path = "shifted_gemm"
        out = _shifted_gemm_conv(data, weight)
    elif _gemm_wgrad_eligible(attrs, data, nd):
        path = "gemm_wgrad"
        out = _conv3x3_same_gemm_wgrad(data, weight)
    else:
        path = "lax"
        out = jax.lax.conv_general_dilated(
            data, weight,
            window_strides=stride,
            padding=[(p, p) for p in pad],
            rhs_dilation=dilate,
            dimension_numbers=_conv_dnums(nd),
            feature_group_count=attrs["num_group"])
    if _telemetry.enabled:
        # the dispatch path is a compile-time choice, so this bump fires
        # once per compiled conv variant — that IS the intended signal
        # graftlint: disable=GL002 -- counts compiled variants, not calls
        _CONV_DISPATCH.labels(path=path).inc()
    # NOTE: no preferred_element_type here — the MXU accumulates bf16 convs
    # in f32 natively, and an explicit f32 preference breaks the conv
    # transpose rule (mixed-dtype cotangents) under jax.vjp
    out = out.astype(data.dtype)
    if not attrs["no_bias"] and maybe_bias:
        bias = maybe_bias[0].reshape((1, -1) + (1,) * nd)
        out = out + bias
    return out


@register("Deconvolution", nin=-1, aliases=("deconvolution",),
          params={**_CONV_PARAMS, "adj": param("shape", ()),
                  "target_shape": param("shape", ())})
def _deconvolution(attrs, data, weight, *maybe_bias):
    """Transposed conv (ref: src/operator/nn/deconvolution.cc): gradient of
    Convolution w.r.t. its input, expressed with lhs dilation."""
    k = attrs["kernel"]
    nd = len(k)
    stride = attrs["stride"] or (1,) * nd
    dilate = attrs["dilate"] or (1,) * nd
    pad = attrs["pad"] or (0,) * nd
    adj = attrs["adj"] or (0,) * nd
    # output_size = stride*(in-1) + dilate*(k-1) + 1 - 2*pad + adj
    padding = [(dilate[i] * (k[i] - 1) - pad[i],
                dilate[i] * (k[i] - 1) - pad[i] + adj[i]) for i in range(nd)]
    # weight layout (in_c, out_c/g, *k) → IOHW spec with flipped spatial dims
    sp = "DHW"[-nd:]
    dnums = jax.lax.conv_dimension_numbers(
        data.shape, weight.shape, ("NC" + sp, "IO" + sp, "NC" + sp))
    out = jax.lax.conv_general_dilated(
        data, jnp.flip(weight, axis=tuple(range(2, 2 + nd))),
        window_strides=(1,) * nd,
        padding=padding,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dnums,
        feature_group_count=attrs["num_group"])
    out = out.astype(data.dtype)
    if not attrs["no_bias"] and maybe_bias:
        out = out + maybe_bias[0].reshape((1, -1) + (1,) * nd)
    return out


@register("FullyConnected", nin=-1, aliases=("fullyconnected", "FullyConnected_v1"),
          params={"num_hidden": param(int, 0, required=True),
                  "no_bias": param(bool, False),
                  "flatten": param(bool, True)})
def _fully_connected(attrs, data, weight, *maybe_bias):
    """y = x·Wᵀ + b on the MXU (ref: src/operator/nn/fully_connected.cc)."""
    if attrs["flatten"]:
        x = data.reshape(data.shape[0], -1)
    else:
        x = data
    out = jnp.matmul(x, weight.T)
    if not attrs["no_bias"] and maybe_bias:
        out = out + maybe_bias[0]
    return out


_POOL_PARAMS = {
    "kernel": param("shape", ()),
    "pool_type": param(["max", "avg", "sum", "lp"], "max"),
    "global_pool": param(bool, False),
    "kernel_layout": param(str, None),
    "cudnn_off": param(bool, False),
    "pooling_convention": param(["valid", "full", "same"], "valid"),
    "stride": param("shape", ()),
    "pad": param("shape", ()),
    "p_value": param(int, 2),
    "count_include_pad": param(bool, True),
}


@register("Pooling", nin=1, aliases=("pooling", "Pooling_v1"),
          params=dict(_POOL_PARAMS))
def _pooling(attrs, data):
    """Max/avg/sum pooling via windowed reduction on the VPU
    (ref: src/operator/nn/pooling.cc)."""
    nd = data.ndim - 2
    if attrs["global_pool"]:
        axes = tuple(range(2, data.ndim))
        if attrs["pool_type"] == "max":
            out = jnp.max(data, axis=axes, keepdims=True)
        elif attrs["pool_type"] == "sum":
            out = jnp.sum(data, axis=axes, keepdims=True)
        else:
            out = jnp.mean(data, axis=axes, keepdims=True)
        return out
    k = attrs["kernel"]
    stride = attrs["stride"] or (1,) * nd
    pad = attrs["pad"] or (0,) * nd
    window = (1, 1) + tuple(k)
    strides = (1, 1) + tuple(stride)
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if attrs["pooling_convention"] == "full":
        # ceil instead of floor for output size: add extra padding on the right
        extra = []
        for i in range(nd):
            in_sz = data.shape[2 + i] + 2 * pad[i]
            rem = (in_sz - k[i]) % stride[i]
            extra.append((stride[i] - rem) % stride[i] if rem else 0)
        pads = ((0, 0), (0, 0)) + tuple(
            (p, p + e) for p, e in zip(pad, extra))
    pt = attrs["pool_type"]
    if pt == "max":
        if jnp.issubdtype(data.dtype, jnp.floating):
            init = -jnp.inf
        else:  # typed scalar so reduce_window init matches operand dtype
            init = np.asarray(jnp.iinfo(data.dtype).min, data.dtype)[()]
        return jax.lax.reduce_window(data, init, jax.lax.max, window, strides, pads)
    ssum = jax.lax.reduce_window(data, 0.0, jax.lax.add, window, strides, pads)
    if pt == "sum":
        return ssum.astype(data.dtype)
    if pt == "lp":
        p = attrs["p_value"]
        sp = jax.lax.reduce_window(jnp.abs(data) ** p, 0.0, jax.lax.add,
                                   window, strides, pads)
        return (sp ** (1.0 / p)).astype(data.dtype)
    # avg
    if attrs["count_include_pad"]:
        denom = float(np.prod(k))
        return (ssum / denom).astype(data.dtype)
    ones = jnp.ones_like(data)
    counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
    return (ssum / counts).astype(data.dtype)


@register("Activation", nin=1, aliases=("activation",),
          params={"act_type": param(["relu", "sigmoid", "tanh", "softrelu",
                                     "softsign", "gelu"], "relu",
                                    required=True)})
def _activation(attrs, x):
    act = attrs["act_type"]
    if act == "relu":
        return jax.nn.relu(x)
    if act == "sigmoid":
        return jax.nn.sigmoid(x)
    if act == "tanh":
        return jnp.tanh(x)
    if act == "softrelu":
        return jnp.logaddexp(x, 0.0)
    if act == "gelu":
        # exact (erf) formulation: the tanh approximation would put the
        # fused and eager transformer steps on different curves
        return jax.nn.gelu(x, approximate=False)
    return jax.nn.soft_sign(x)


@register("LeakyReLU", nin=-1, aliases=("leakyrelu",), needs_rng=True,
          train_aware=True,
          params={"act_type": param(["elu", "leaky", "prelu", "rrelu", "selu",
                                     "gelu"], "leaky"),
                  "slope": param(float, 0.25),
                  "lower_bound": param(float, 0.125),
                  "upper_bound": param(float, 0.334),
                  "__train__": param(bool, False)})
def _leaky_relu(attrs, key, x, *maybe_gamma):
    act = attrs["act_type"]
    if act == "leaky":
        return jnp.where(x > 0, x, attrs["slope"] * x)
    if act == "elu":
        return jnp.where(x > 0, x, attrs["slope"] * jnp.expm1(x))
    if act == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))
    if act == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if act == "prelu":
        gamma = maybe_gamma[0]
        shape = [1] * x.ndim
        if gamma.ndim == 1 and x.ndim > 1:
            shape[1] = gamma.shape[0] if gamma.shape[0] > 1 else 1
        g = gamma.reshape(shape)
        return jnp.where(x > 0, x, g * x)
    # rrelu: random slope in [lower, upper] at train, mean at eval
    lo, hi = attrs["lower_bound"], attrs["upper_bound"]
    if attrs.get("__train__"):
        slope = jax.random.uniform(key, x.shape, x.dtype, lo, hi)
    else:
        slope = (lo + hi) / 2.0
    return jnp.where(x > 0, x, slope * x)


# --------------------------------------------------------------------------
# normalization
# --------------------------------------------------------------------------
_BN_PARAMS = {
    "eps": param(float, 1e-3),
    "momentum": param(float, 0.9),
    "fix_gamma": param(bool, True),
    "use_global_stats": param(bool, False),
    "output_mean_var": param(bool, False),
    "axis": param(int, 1),
    "cudnn_off": param(bool, False),
    "__train__": param(bool, False),
}


@register("BatchNorm", nin=5, aliases=("batchnorm", "BatchNorm_v1"),
          params=dict(_BN_PARAMS), train_aware=True, nout=3,
          aux_writeback={1: 3, 2: 4},
          visible=lambda a: 3 if a["output_mean_var"] else 1)
def _batch_norm(attrs, data, gamma, beta, moving_mean, moving_var):
    """BatchNorm (ref: src/operator/nn/batch_norm.cc).

    Outputs (out, new_moving_mean, new_moving_var); in training mode the
    dispatch layer writes outputs 1,2 back into the moving-stat aux arrays —
    the functional TPU expression of the reference's in-kernel aux mutation.
    """
    ax = attrs["axis"] % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    shape = [1] * data.ndim
    shape[ax] = data.shape[ax]
    train = attrs.get("__train__") and not attrs["use_global_stats"]
    low_precision = data.dtype in (jnp.bfloat16, jnp.float16)
    if train:
        if low_precision:
            # bf16/f16 fast path: f32-ACCUMULATED stats straight off the
            # low-precision activations (no materialized f32 copy — the
            # square fuses into the reduction), one-pass variance.  The
            # activation-sized reads/writes stay 2 bytes/elt, halving the
            # HBM traffic of this memory-bound op (~17% ResNet-50 step
            # time on v5e).
            mean = jnp.mean(data, axis=red, dtype=jnp.float32)
            m2 = jnp.mean(jax.lax.square(data.astype(jnp.float32)),
                          axis=red)
            var = jnp.maximum(m2 - jax.lax.square(mean), 0.0)
        else:
            x32 = data.astype(jnp.float32)
            mean = jnp.mean(x32, axis=red)
            var = jnp.var(x32, axis=red)
        m = attrs["momentum"]
        new_mm = moving_mean * m + mean * (1 - m)
        new_mv = moving_var * m + var * (1 - m)
    else:
        mean, var = moving_mean, moving_var
        new_mm, new_mv = moving_mean, moving_var
    g = jnp.ones_like(gamma) if attrs["fix_gamma"] else gamma
    inv = jax.lax.rsqrt(var + attrs["eps"]) * g
    if low_precision:
        # normalize in the input dtype with the scale/shift folded into
        # two per-channel scalars (y = x*inv + (beta - mean*inv))
        shift = beta - mean * inv
        return (data * inv.astype(data.dtype).reshape(shape)
                + shift.astype(data.dtype).reshape(shape)), new_mm, new_mv
    out = (data - mean.reshape(shape)) * inv.reshape(shape) \
        + beta.reshape(shape)
    return out.astype(data.dtype), new_mm, new_mv


@register("LayerNorm", nin=3, aliases=("layernorm",),
          params={"axis": param(int, -1), "eps": param(float, 1e-5),
                  "output_mean_var": param(bool, False)}, nout=3,
          visible=lambda a: 3 if a["output_mean_var"] else 1)
def _layer_norm(attrs, data, gamma, beta):
    ax = attrs["axis"] % data.ndim
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=ax, keepdims=True)
    var = jnp.var(x32, axis=ax, keepdims=True)
    inv = jax.lax.rsqrt(var + attrs["eps"])
    shape = [1] * data.ndim
    shape[ax] = data.shape[ax]
    out = (x32 - mean) * inv * gamma.reshape(shape) + beta.reshape(shape)
    return (out.astype(data.dtype), jnp.squeeze(mean, ax), jnp.squeeze(var, ax))


_ATTN_DISPATCH = _telemetry.counter(
    "attention_dispatch_total",
    "MultiHeadAttention dispatch decisions by formulation path (trace-time)",
    ("path",))


def _mha_reference(q, k, v, causal, scale):
    """XLA reference attention, [B,H,T,d].  Same math contract as the
    Pallas flash kernel: f32 score/softmax/accumulate regardless of the
    input dtype, and the causal mask admits position j<=i exactly."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        keep = jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
        s = jnp.where(keep, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    return o.astype(q.dtype)


@register("MultiHeadAttention", nin=5, aliases=("multiheadattention",),
          params={"num_heads": param(int, 0, required=True),
                  "causal": param(bool, True)},
          env_keys=("MXNET_TPU_FLASH_ATTENTION", "MXNET_TPU_PALLAS_ATTN"))
def _multi_head_attention(attrs, data, query_weight, key_weight,
                          value_weight, out_proj_weight):
    """Decoder attention: QKV projections, scaled-dot-product over
    ``num_heads``, output projection.  No reference analog — the
    reference predates transformer first-class ops; the contract follows
    ``sym.FullyConnected`` conventions (weights are (out, in), y=x·Wᵀ).

    Dispatch: ``MXNET_TPU_FLASH_ATTENTION`` (default on) selects the
    Pallas flash kernel (ops/pallas_attention.py) whenever its shape/
    VMEM gate admits the problem; otherwise the XLA reference runs.
    Both env gates are declared in ``env_keys`` so flipping either
    re-specializes every cached program containing this op (GL001).

    Weight names are chosen so ``parallel.mesh.megatron_rules`` shards
    them with zero extra configuration: query/key/value_weight match the
    column-parallel rule (P(t, None)), out_proj_weight the row-parallel
    rule (P(None, t)).
    """
    import os
    from functools import partial
    from . import pallas_attention as pa
    if data.ndim != 3:
        raise MXNetError(
            "MultiHeadAttention: data must be (batch, time, model_dim), "
            "got %s" % (data.shape,))
    B, T, D = data.shape
    H = attrs["num_heads"]
    if H <= 0 or D % H:
        raise MXNetError(
            "MultiHeadAttention: num_heads=%d must divide model_dim=%d"
            % (H, D))
    d = D // H
    causal = attrs["causal"]
    scale = 1.0 / (d ** 0.5)

    def proj(w):
        y = jnp.matmul(data, w.T)                     # [B,T,D]
        return y.reshape(B, T, H, d).transpose(0, 2, 1, 3)   # [B,H,T,d]

    q, k, v = proj(query_weight), proj(key_weight), proj(value_weight)

    use_flash = os.environ.get("MXNET_TPU_FLASH_ATTENTION", "1") != "0" \
        and pa.flash_attention_available(B, H, T, T, d, q.dtype)
    ref = partial(_mha_reference, causal=causal, scale=scale)
    if use_flash:
        flash = partial(pa.flash_attention, causal=causal, scale=scale)
        if pa.INTERPRET:       # test hook: force the interpreter on CPU
            out = flash(q, k, v)
            path = "flash_interpret"
        else:
            # platform resolved at LOWERING time where the jax version
            # supports branch pruning (advisor r03), trace time otherwise
            from ..parallel._compat import platform_dependent
            out = platform_dependent(q, k, v, tpu=flash,
                                     default=lambda q, k, v: ref(q, k, v))
            path = "flash"
    else:
        out = ref(q, k, v)
        path = "reference"
    if _telemetry.enabled:
        # one inc per compiled attention variant, not per step — the
        # dispatch is a trace-time choice, same contract as conv_dispatch
        # graftlint: disable=GL002 -- counts compiled variants, not calls
        _ATTN_DISPATCH.labels(path=path).inc()
    out = out.transpose(0, 2, 1, 3).reshape(B, T, D)  # [B,T,D]
    return jnp.matmul(out, out_proj_weight.T)


@register("InstanceNorm", nin=3, aliases=("instancenorm",),
          params={"eps": param(float, 1e-3)})
def _instance_norm(attrs, data, gamma, beta):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return ((data - mean) * jax.lax.rsqrt(var + attrs["eps"])
            * gamma.reshape(shape) + beta.reshape(shape))


@register("L2Normalization", nin=1,
          params={"eps": param(float, 1e-10),
                  "mode": param(["instance", "channel", "spatial"], "instance")})
def _l2_normalization(attrs, data):
    mode = attrs["mode"]
    if mode == "instance":
        red = tuple(range(1, data.ndim))
    elif mode == "channel":
        red = (1,)
    else:
        red = tuple(range(2, data.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=red, keepdims=True)
                    + attrs["eps"])
    return data / norm


@register("LRN", nin=1, aliases=("lrn",), nout=2, visible=1,
          params={"alpha": param(float, 1e-4), "beta": param(float, 0.75),
                  "knorm": param(float, 2.0), "nsize": param(int, 0, required=True)})
def _lrn(attrs, data):
    """Local response norm across channels (ref: src/operator/nn/lrn.cc)."""
    n = attrs["nsize"]
    half = n // 2
    sq = jnp.square(data)
    # sum over channel window via padded cumulative trick
    pad = [(0, 0)] * data.ndim
    pad[1] = (half, half)
    sqp = jnp.pad(sq, pad)
    window = [1] * data.ndim
    window[1] = n
    ssum = jax.lax.reduce_window(sqp, 0.0, jax.lax.add, tuple(window),
                                 (1,) * data.ndim, "valid")
    scale = (attrs["knorm"] + attrs["alpha"] * ssum / n) ** attrs["beta"]
    return data / scale, scale


# --------------------------------------------------------------------------
# softmax family
# --------------------------------------------------------------------------
@register("softmax", nin=1, params={"axis": param(int, -1),
                                    "temperature": param(float, None),
                                    "dtype": param("dtype", None)})
def _softmax(attrs, x):
    t = attrs["temperature"]
    if t is not None and t != 1.0:
        x = x / t
    out = jax.nn.softmax(x, axis=attrs["axis"])
    return out.astype(np.dtype(attrs["dtype"])) if attrs["dtype"] else out


@register("log_softmax", nin=1, params={"axis": param(int, -1),
                                        "temperature": param(float, None)})
def _log_softmax(attrs, x):
    t = attrs["temperature"]
    if t is not None and t != 1.0:
        x = x / t
    return jax.nn.log_softmax(x, axis=attrs["axis"])


@register("SoftmaxActivation", nin=1,
          params={"mode": param(["instance", "channel"], "instance")})
def _softmax_activation(attrs, x):
    axis = 1 if attrs["mode"] == "channel" else -1
    if attrs["mode"] == "instance" and x.ndim > 2:
        return jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)
    return jax.nn.softmax(x, axis=axis)


_SOFTMAX_OUT_PARAMS = {
    "grad_scale": param(float, 1.0),
    "ignore_label": param(float, -1.0),
    "multi_output": param(bool, False),
    "use_ignore": param(bool, False),
    "preserve_shape": param(bool, False),
    "normalization": param(["null", "batch", "valid"], "null"),
    "out_grad": param(bool, False),
    "smooth_alpha": param(float, 0.0),
}


def _softmax_output_impl(attrs, data, label):
    if attrs["multi_output"]:
        prob = jax.nn.softmax(data, axis=1)
    elif attrs["preserve_shape"]:
        prob = jax.nn.softmax(data, axis=-1)
    else:
        prob = jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1)
        prob = prob.reshape(data.shape)
    return prob


@register("SoftmaxOutput", nin=2, aliases=("softmaxoutput", "Softmax"),
          params=dict(_SOFTMAX_OUT_PARAMS))
def _softmax_output(attrs, data, label):
    """Softmax with implicit cross-entropy gradient
    (ref: src/operator/softmax_output.cc).  Forward = softmax(data); the
    backward is (p - onehot(label)) * grad_scale with ignore-label masking —
    expressed as a custom VJP so autograd/Symbol backward matches the
    reference exactly (the incoming head gradient is ignored, as in MXNet)."""

    @jax.custom_vjp
    def _fwd(d, l):
        return _softmax_output_impl(attrs, d, l)

    def _fwd_fwd(d, l):
        p = _softmax_output_impl(attrs, d, l)
        return p, (p, l)

    def _fwd_bwd(res, g):
        p, l = res
        axis = 1 if attrs["multi_output"] else -1
        if attrs["multi_output"]:
            lab = l.astype(jnp.int32)
            oh = jax.nn.one_hot(lab, p.shape[1], dtype=p.dtype, axis=1)
        else:
            flat_label = l.reshape(l.shape[0], -1) if l.ndim > 1 else l
            lab = flat_label.astype(jnp.int32)
            oh = jax.nn.one_hot(lab.reshape(p.shape[:-1]), p.shape[-1],
                                dtype=p.dtype)
        grad = (p - oh)
        if attrs["use_ignore"]:
            mask = (l != attrs["ignore_label"]).astype(p.dtype)
            mask = jnp.expand_dims(mask, 1 if attrs["multi_output"] else -1)
            grad = grad * mask
        scale = attrs["grad_scale"]
        if attrs["normalization"] == "batch":
            scale = scale / p.shape[0]
        elif attrs["normalization"] == "valid" and attrs["use_ignore"]:
            nvalid = jnp.maximum(jnp.sum(l != attrs["ignore_label"]), 1)
            scale = scale / nvalid
        return grad * scale, jnp.zeros_like(l)

    _fwd.defvjp(_fwd_fwd, _fwd_bwd)
    # loss head: low-precision logits go through the exp/sum reduction in
    # f32 (keyed on input dtype, never on env — GL002); output prob stays
    # f32 so downstream loss reduction is full precision.  The cast sits
    # OUTSIDE the custom VJP so its transpose re-casts the f32 head
    # gradient back to the logits' storage dtype automatically.
    if data.dtype in (jnp.bfloat16, jnp.float16):
        data = data.astype(jnp.float32)
    return _fwd(data, label)


def streaming_ce(logits, labels, axis=-1):
    """Per-example softmax cross-entropy via streaming logsumexp.

    ``logsumexp(logits) - logits[label]`` in f32 — mathematically identical
    to ``-log_softmax(logits)[label]`` (ref: python/mxnet/gluon/loss.py:304
    and src/operator/loss_binary_op.cc) but never materializes the
    ``(N, vocab)`` f32 log-softmax: only the two ``(N,)`` reductions leave
    registers.  The custom VJP emits ``(softmax - onehot)`` directly in the
    logits dtype, so the backward carries a bf16 — not f32 — ``(N, vocab)``
    intermediate.  Measured +23% tokens/s on the LSTM LM bench where the
    600 MB f32 intermediate was ~1/3 of the device step.
    """
    axis = axis % logits.ndim

    @jax.custom_vjp
    def _ce(lg, lab):
        return _fwd(lg, lab)[0]

    def _fwd(lg, lab):
        lgm = jnp.moveaxis(lg, axis, -1)
        lab_i = lab.astype(jnp.int32)
        # logsumexp unrolled so the f32 upcast feeds exactly ONE reduction:
        # max runs on the input dtype (max never rounds), leaving the
        # convert→sub→exp chain a single-consumer elementwise producer that
        # XLA fuses into the sum — no (N, V) f32 buffer is ever allocated
        # (jax.scipy logsumexp's f32 input feeds both reductions, which
        # makes XLA materialize the converted array)
        m = jnp.max(lgm, axis=-1)
        m32 = jnp.where(jnp.isfinite(m), m, 0).astype(jnp.float32)
        z = jnp.sum(jnp.exp(lgm.astype(jnp.float32) - m32[..., None]),
                    axis=-1)
        lse = m32 + jnp.log(z)
        picked = jnp.take_along_axis(lgm, lab_i[..., None], axis=-1)[..., 0]
        return lse - picked.astype(jnp.float32), (lgm, lab, lse)

    def _bwd(res, g):
        lgm, lab, lse = res
        # softmax recomputed in the logits dtype: exp(x - lse) fuses into
        # the one_hot subtraction, no f32 (N, V) buffer in the backward
        p = jnp.exp(lgm - lse.astype(lgm.dtype)[..., None])
        oh = jax.nn.one_hot(lab.astype(jnp.int32), lgm.shape[-1],
                            dtype=lgm.dtype)
        gm = g.astype(lgm.dtype)[..., None] * (p - oh)
        lab_ct = (jnp.zeros_like(lab)
                  if jnp.issubdtype(lab.dtype, jnp.inexact)
                  else jnp.zeros(lab.shape, jax.dtypes.float0))
        return jnp.moveaxis(gm, -1, axis), lab_ct

    _ce.defvjp(lambda lg, lab: _fwd(lg, lab), _bwd)
    return _ce(logits, labels)


@register("streaming_softmax_ce", nin=2,
          params={"axis": param(int, -1), "keepdims": param(bool, False)})
def _streaming_softmax_ce_op(attrs, data, label):
    """Registered form of :func:`streaming_ce` — the fused sparse-label CE
    used by ``gluon.loss.SoftmaxCrossEntropyLoss`` in place of the
    reference's log_softmax+pick composition."""
    out = streaming_ce(data, label, attrs["axis"])
    return jnp.expand_dims(out, attrs["axis"] % data.ndim) \
        if attrs["keepdims"] else out


@register("softmax_cross_entropy", nin=2)
def _softmax_cross_entropy(attrs, data, label):
    """Total CE over the batch (ref: src/operator/loss_binary_op.cc),
    lowered to the streaming logsumexp formulation."""
    return jnp.sum(streaming_ce(data, label, -1)).astype(data.dtype)


@register("LinearRegressionOutput", nin=2, aliases=("linearregressionoutput",),
          params={"grad_scale": param(float, 1.0)})
def _linear_regression_output(attrs, data, label):
    @jax.custom_vjp
    def _fwd(d, l):
        return d

    def _f(d, l):
        return d, (d, l)

    def _b(res, g):
        d, l = res
        return ((d - l.reshape(d.shape)) * attrs["grad_scale"],
                jnp.zeros_like(l))

    _fwd.defvjp(_f, _b)
    return _fwd(data, label)


@register("LogisticRegressionOutput", nin=2, aliases=("logisticregressionoutput",),
          params={"grad_scale": param(float, 1.0)})
def _logistic_regression_output(attrs, data, label):
    @jax.custom_vjp
    def _fwd(d, l):
        return jax.nn.sigmoid(d)

    def _f(d, l):
        p = jax.nn.sigmoid(d)
        return p, (p, l)

    def _b(res, g):
        p, l = res
        return ((p - l.reshape(p.shape)) * attrs["grad_scale"], jnp.zeros_like(l))

    _fwd.defvjp(_f, _b)
    return _fwd(data, label)


@register("MAERegressionOutput", nin=2, aliases=("maeregressionoutput",),
          params={"grad_scale": param(float, 1.0)})
def _mae_regression_output(attrs, data, label):
    @jax.custom_vjp
    def _fwd(d, l):
        return d

    def _f(d, l):
        return d, (d, l)

    def _b(res, g):
        d, l = res
        return (jnp.sign(d - l.reshape(d.shape)) * attrs["grad_scale"],
                jnp.zeros_like(l))

    _fwd.defvjp(_f, _b)
    return _fwd(data, label)


# --------------------------------------------------------------------------
# dropout
# --------------------------------------------------------------------------
@register("Dropout", nin=1, aliases=("dropout",), needs_rng=True,
          train_aware=True, nout=2, visible=1,
          params={"p": param(float, 0.5),
                  "mode": param(["training", "always"], "training"),
                  "axes": param("shape", ()),
                  "cudnn_off": param(bool, False),
                  "__train__": param(bool, False)})
def _dropout(attrs, key, data):
    """Inverted dropout (ref: src/operator/nn/dropout.cc); returns
    (out, mask)."""
    p = attrs["p"]
    active = attrs.get("__train__") or attrs["mode"] == "always"
    if not active or p == 0.0:
        return data, jnp.ones_like(data)
    shape = data.shape
    if attrs["axes"]:
        shape = tuple(1 if i in attrs["axes"] else s
                      for i, s in enumerate(data.shape))
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, shape).astype(data.dtype) / keep
    return data * mask, jnp.broadcast_to(mask, data.shape)


@register("UpSampling", nin=-1, aliases=("upsampling",),
          params={"scale": param(int, 1, required=True),
                  "num_filter": param(int, 0),
                  "sample_type": param(["nearest", "bilinear"], "nearest"),
                  "multi_input_mode": param(["concat", "sum"], "concat"),
                  "num_args": param(int, 1),
                  "workspace": param(int, 512)})
def _upsampling(attrs, *inputs):
    s = attrs["scale"]
    outs = []
    for x in inputs:
        if attrs["sample_type"] == "nearest":
            y = jnp.repeat(jnp.repeat(x, s, axis=2), s, axis=3)
        else:
            n, c, h, w = x.shape
            y = jax.image.resize(x, (n, c, h * s, w * s), method="bilinear")
        outs.append(y)
    if len(outs) == 1:
        return outs[0]
    if attrs["multi_input_mode"] == "sum":
        out = outs[0]
        for y in outs[1:]:
            out = out + y
        return out
    return jnp.concatenate(outs, axis=1)


@register("Crop", nin=-1, aliases=("crop_like",),
          params={"offset": param("shape", (0, 0)),
                  "h_w": param("shape", (0, 0)),
                  "num_args": param(int, 1),
                  "center_crop": param(bool, False)})
def _crop_op(attrs, data, *maybe_like):
    if maybe_like:
        th, tw = maybe_like[0].shape[2:4]
    else:
        th, tw = attrs["h_w"]
    h, w = data.shape[2:4]
    if attrs["center_crop"]:
        oy, ox = (h - th) // 2, (w - tw) // 2
    else:
        oy, ox = attrs["offset"]
    return data[:, :, oy:oy + th, ox:ox + tw]
