"""Linear-algebra operators (``_linalg_*``).

Reference analog: ``src/operator/tensor/la_op.cc`` (BLAS3/LAPACK wrappers:
gemm/gemm2/potrf/potri/trmm/trsm/sumlogdiag/syrk/gelqf/syevd at
la_op.cc:36-577, param struct la_op.h:40-95).

TPU-native design: each maps to an XLA linear-algebra HLO (``jnp.linalg`` /
``jax.scipy.linalg``), batched over leading dimensions natively instead of
the reference's explicit batch loops; gradients via jax.vjp of these
definitions (the reference hand-codes the matrix-calculus backward for each,
la_op.cc backward registrations — vjp yields the same formulas).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, param


def _t(x, flag):
    return jnp.swapaxes(x, -1, -2) if flag else x


@register("_linalg_gemm", nin=3, aliases=("linalg_gemm",),
          params={"transpose_a": param(bool, False),
                  "transpose_b": param(bool, False),
                  "alpha": param(float, 1.0),
                  "beta": param(float, 1.0),
                  "axis": param(int, -3)})
def _linalg_gemm(attrs, a, b, c):
    """out = alpha * op(A) op(B) + beta * C (la_op.cc:36)."""
    prod = jnp.matmul(_t(a, attrs["transpose_a"]), _t(b, attrs["transpose_b"]),
                      precision=lax.Precision.HIGHEST)
    return (attrs["alpha"] * prod + attrs["beta"] * c).astype(a.dtype)


@register("_linalg_gemm2", nin=2, aliases=("linalg_gemm2",),
          params={"transpose_a": param(bool, False),
                  "transpose_b": param(bool, False),
                  "alpha": param(float, 1.0),
                  "axis": param(int, -3)})
def _linalg_gemm2(attrs, a, b):
    """out = alpha * op(A) op(B) (la_op.cc:109)."""
    prod = jnp.matmul(_t(a, attrs["transpose_a"]), _t(b, attrs["transpose_b"]),
                      precision=lax.Precision.HIGHEST)
    return (attrs["alpha"] * prod).astype(a.dtype)


@register("_linalg_potrf", nin=1, aliases=("linalg_potrf",))
def _linalg_potrf(attrs, a):
    """Cholesky factor L with A = L Lᵀ (la_op.cc:176)."""
    return jnp.linalg.cholesky(a)


@register("_linalg_potri", nin=1, aliases=("linalg_potri",))
def _linalg_potri(attrs, a):
    """Inverse of A from its Cholesky factor input L: out = (L Lᵀ)⁻¹
    (la_op.cc:225)."""
    eye = jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape)
    linv = jax.scipy.linalg.solve_triangular(a, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv,
                      precision=lax.Precision.HIGHEST)


@register("_linalg_trmm", nin=2, aliases=("linalg_trmm",),
          params={"transpose": param(bool, False),
                  "rightside": param(bool, False),
                  "alpha": param(float, 1.0)})
def _linalg_trmm(attrs, a, b):
    """Triangular matrix multiply: alpha * op(L) B, or B op(L) when
    rightside (la_op.cc:280).  L = tril(A)."""
    tri = _t(jnp.tril(a), attrs["transpose"])
    mm = lambda x, y: jnp.matmul(x, y, precision=lax.Precision.HIGHEST)
    out = mm(b, tri) if attrs["rightside"] else mm(tri, b)
    return (attrs["alpha"] * out).astype(a.dtype)


@register("_linalg_trsm", nin=2, aliases=("linalg_trsm",),
          params={"transpose": param(bool, False),
                  "rightside": param(bool, False),
                  "alpha": param(float, 1.0)})
def _linalg_trsm(attrs, a, b):
    """Solve triangular system: out = alpha * op(L)⁻¹ B (or B op(L)⁻¹ when
    rightside) (la_op.cc:343)."""
    lower = not attrs["transpose"]
    if attrs["rightside"]:
        # B op(L)^-1 = (op(L)^-T B^T)^T
        sol = jax.scipy.linalg.solve_triangular(
            _t(jnp.tril(a), attrs["transpose"]),
            jnp.swapaxes(b, -1, -2), lower=lower, trans=1)
        out = jnp.swapaxes(sol, -1, -2)
    else:
        out = jax.scipy.linalg.solve_triangular(
            jnp.tril(a), b, lower=True, trans=1 if attrs["transpose"] else 0)
    return (attrs["alpha"] * out).astype(a.dtype)


@register("_linalg_sumlogdiag", nin=1, aliases=("linalg_sumlogdiag",))
def _linalg_sumlogdiag(attrs, a):
    """sum(log(diag(A))) over the last two axes (la_op.cc:406)."""
    diag = jnp.diagonal(a, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(diag), axis=-1)


@register("_linalg_syrk", nin=1, aliases=("linalg_syrk",),
          params={"transpose": param(bool, False),
                  "alpha": param(float, 1.0)})
def _linalg_syrk(attrs, a):
    """Symmetric rank-k update: alpha * A Aᵀ (or Aᵀ A when transpose)
    (la_op.cc:449)."""
    at = jnp.swapaxes(a, -1, -2)
    mm = lambda x, y: jnp.matmul(x, y, precision=lax.Precision.HIGHEST)
    out = mm(at, a) if attrs["transpose"] else mm(a, at)
    return (attrs["alpha"] * out).astype(a.dtype)


@register("_linalg_gelqf", nin=1, nout=2, aliases=("linalg_gelqf",))
def _linalg_gelqf(attrs, a):
    """LQ factorization A = L Q with orthonormal rows of Q (la_op.cc:506).
    Computed via QR of Aᵀ (XLA has a QR HLO, not LQ)."""
    q, r = jnp.linalg.qr(jnp.swapaxes(a, -1, -2), mode="reduced")
    # sign-normalize: reference LAPACK gelqf yields L with positive diag
    # only up to convention; make diag(L) >= 0 for determinism
    d = jnp.sign(jnp.diagonal(r, axis1=-2, axis2=-1))
    d = jnp.where(d == 0, 1.0, d).astype(a.dtype)
    r = r * d[..., :, None]
    q = q * d[..., None, :]
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("_linalg_syevd", nin=1, nout=2, aliases=("linalg_syevd",))
def _linalg_syevd(attrs, a):
    """Symmetric eigendecomposition A = Uᵀ diag(L) U, eigenvalues ascending;
    rows of U are eigenvectors (la_op.cc:577)."""
    w, v = jnp.linalg.eigh(a)
    return jnp.swapaxes(v, -1, -2), w
