"""Misc contrib ops: CTC loss, FFT, count-sketch, khatri-rao, quadratic,
adaptive/bilinear pooling-resize, channel operator, div_sqrt_dim.

Reference analogs (`src/operator/contrib/`, SURVEY.md N7 contrib/):
- ``_contrib_CTCLoss`` — ctc_loss-inl.h:195-215 (warp-ctc semantics:
  softmax inside, ``blank_label`` first/last, optional per-sample lengths).
- ``_contrib_fft`` / ``_contrib_ifft`` — fft-inl.h:50-60 (cuFFT real->
  interleaved-complex; ifft unnormalized like cuFFT).
- ``_contrib_count_sketch`` — count_sketch-inl.h:45-55.
- ``khatri_rao`` — krprod.cc (column-wise Kronecker product).
- ``_contrib_quadratic`` — quadratic_op-inl.h (a*x² + b*x + c).
- ``_contrib_AdaptiveAvgPooling2D`` — adaptive_avg_pooling-inl.h:50-56;
  ``_contrib_BilinearResize2D`` — bilinear_resize-inl.h:50-58 (both lowered
  to interpolation-matrix einsums so they ride the MXU instead of the
  reference's scalar bin loops).
- ``_contrib_ChannelOperator`` — channel_operator-inl.h:32-50 (the fork's
  R-FCN helper: Group_Max / Group_Softmax / Group_Pick).
- ``_contrib_div_sqrt_dim`` — transformer.cc:33-40.

TPU-native design notes: CTC's alpha recursion is a ``lax.scan`` over time
in log space — the backward pass is ``jax.vjp`` of that scan (the reference
ships warp-ctc's hand-written beta recursion; vjp-of-alpha computes the
same gradient); FFTs map to XLA's native fft HLO.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register, param

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# CTC loss
# ---------------------------------------------------------------------------
@register("_contrib_CTCLoss", nin=-1, nout=2, visible=1,
          aliases=("_contrib_ctc_loss", "ctc_loss", "CTCLoss"),
          params={"use_data_lengths": param(bool, False),
                  "use_label_lengths": param(bool, False),
                  "blank_label": param(["first", "last"], "first")})
def _ctc_loss(attrs, data, label, *lengths):
    """CTC loss (ctc_loss-inl.h:195-215).

    data (T, N, A) activations (softmax applied internally, warp-ctc
    convention); label (N, L): with ``blank_label=first`` blank is 0 and
    labels are 1-based with 0-padding; with ``last`` blank is A-1, labels
    0-based with -1 padding.  Optional data_lengths (N,) and/or
    label_lengths (N,) follow in input order.  Outputs: (loss (N,),
    grad-ready log-alphas hidden output).
    """
    t_max, n, a = data.shape
    l_max = label.shape[1]
    use_dl, use_ll = attrs["use_data_lengths"], attrs["use_label_lengths"]
    rest = list(lengths)
    data_len = rest.pop(0) if use_dl else None
    label_len = rest.pop(0) if use_ll else None
    blank_first = attrs["blank_label"] == "first"
    blank = 0 if blank_first else a - 1

    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)  # (T, N, A)

    lab = label.astype(jnp.int32)
    if blank_first:
        pad = lab <= 0
        lab_ids = lab           # already 1-based with blank 0
    else:
        pad = lab < 0
        lab_ids = lab
    if label_len is not None:
        pad = pad | (jnp.arange(l_max)[None, :] >=
                     label_len.astype(jnp.int32)[:, None])
    num_lab = jnp.sum(~pad, axis=1)                      # (N,)

    # extended sequence: blank, l1, blank, l2, ..., blank  (len 2L+1)
    s_len = 2 * l_max + 1
    ext = jnp.full((n, s_len), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(jnp.where(pad, blank, lab_ids))
    valid_s = jnp.arange(s_len)[None, :] < (2 * num_lab + 1)[:, None]
    # can skip from s-2 to s when ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :s_len]
    can_skip = (ext != blank) & (ext != ext_m2)

    if data_len is not None:
        t_len = data_len.astype(jnp.int32)
    else:
        t_len = jnp.full((n,), t_max, jnp.int32)

    def step(alpha, inputs):
        lp_t, t = inputs                                  # lp_t (N, A)
        a_prev1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                          constant_values=NEG_INF)[:, :s_len]
        a_prev2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                          constant_values=NEG_INF)[:, :s_len]
        a_new = jnp.logaddexp(alpha, a_prev1)
        a_new = jnp.where(can_skip, jnp.logaddexp(a_new, a_prev2), a_new)
        emit = jnp.take_along_axis(lp_t, ext, axis=1)     # (N, s_len)
        a_new = a_new + emit
        a_new = jnp.where(valid_s, a_new, NEG_INF)
        # frozen once past this sample's length
        a_new = jnp.where((t < t_len)[:, None], a_new, alpha)
        return a_new, None

    alpha0 = jnp.full((n, s_len), NEG_INF)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
    first_emit = jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0]
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(num_lab > 0, first_emit, NEG_INF))
    alpha, _ = lax.scan(step, alpha0,
                        (logp[1:], jnp.arange(1, t_max)))
    # loss = -log(alpha[2L] + alpha[2L-1]) at the final valid frame
    idx_last = 2 * num_lab
    a_last = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(
        alpha, jnp.maximum(idx_last - 1, 0)[:, None], axis=1)[:, 0]
    ll = jnp.logaddexp(a_last,
                       jnp.where(num_lab > 0, a_prev, NEG_INF))
    loss = (-ll).astype(data.dtype)
    return loss, alpha.astype(data.dtype)


# ---------------------------------------------------------------------------
# FFT family (cuFFT semantics: interleaved complex, unnormalized inverse)
# ---------------------------------------------------------------------------
@register("_contrib_fft", nin=1, aliases=("fft",),
          params={"compute_size": param(int, 128)})
def _fft(attrs, data):
    """Batched FFT over the last dim (fft-inl.h:50-60): real (..., D) ->
    interleaved complex (..., 2D)."""
    f = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([f.real, f.imag], axis=-1).reshape(
        data.shape[:-1] + (2 * data.shape[-1],))
    return out.astype(data.dtype)


@register("_contrib_ifft", nin=1, aliases=("ifft",),
          params={"compute_size": param(int, 128)})
def _ifft(attrs, data):
    """Inverse FFT (ifft-inl.h): interleaved complex (..., 2D) -> real
    (..., D), unnormalized (cuFFT convention — caller divides by D)."""
    d = data.shape[-1] // 2
    c = data.reshape(data.shape[:-1] + (d, 2)).astype(jnp.float32)
    z = c[..., 0] + 1j * c[..., 1]
    out = jnp.fft.ifft(z, axis=-1).real * d
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# count sketch
# ---------------------------------------------------------------------------
@register("_contrib_count_sketch", nin=3,
          aliases=("count_sketch",),
          params={"out_dim": param(int, None, required=True),
                  "processing_batch_size": param(int, 32)})
def _count_sketch(attrs, data, h, s):
    """Count sketch projection (count_sketch-inl.h:45-55): data (N, D),
    hash bucket h (1, D) in [0, out_dim), sign s (1, D) in {+1, -1} ->
    (N, out_dim): out[n, h[d]] += s[d] * data[n, d]."""
    out_dim = attrs["out_dim"]
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1)
    signed = data * ss[None, :]
    out = jnp.zeros((data.shape[0], out_dim), data.dtype)
    return out.at[:, hh].add(signed)


@register("khatri_rao", nin=-1)
def _khatri_rao(attrs, *mats):
    """Column-wise Kronecker product (krprod.cc): inputs (n_i, K) ->
    (prod n_i, K)."""
    if not mats:
        raise MXNetError("khatri_rao needs at least one input")
    out = mats[0]
    for m in mats[1:]:
        k = out.shape[1]
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, k)
    return out


@register("_contrib_quadratic", nin=1, aliases=("quadratic",),
          params={"a": param(float, 0.0), "b": param(float, 0.0),
                  "c": param(float, 0.0)})
def _quadratic(attrs, data):
    """Elementwise a*x² + b*x + c (quadratic_op-inl.h)."""
    return attrs["a"] * data * data + attrs["b"] * data + attrs["c"]


@register("_contrib_div_sqrt_dim", nin=1, aliases=("div_sqrt_dim",))
def _div_sqrt_dim(attrs, data):
    """out = data / sqrt(data.shape[-1]) (transformer.cc:33-40, the fork's
    attention scaling helper)."""
    return data / np.sqrt(data.shape[-1]).astype(np.float32)


# ---------------------------------------------------------------------------
# adaptive pooling / bilinear resize — interpolation-matrix einsums
# ---------------------------------------------------------------------------
def _adaptive_pool_matrix(in_size: int, out_size: int) -> np.ndarray:
    """(out, in) averaging matrix with bin [floor(i*I/O), ceil((i+1)*I/O))."""
    m = np.zeros((out_size, in_size), np.float32)
    for i in range(out_size):
        lo = (i * in_size) // out_size
        hi = -(-((i + 1) * in_size) // out_size)  # ceil div
        m[i, lo:hi] = 1.0 / (hi - lo)
    return m


@register("_contrib_AdaptiveAvgPooling2D", nin=1,
          aliases=("AdaptiveAvgPooling2D",),
          params={"output_size": param("shape", ())})
def _adaptive_avg_pooling(attrs, data):
    """Adaptive average pooling (adaptive_avg_pooling-inl.h:50-56): NCHW ->
    NC(out_h)(out_w); empty output_size means global (1, 1)."""
    osize = attrs["output_size"] or (1, 1)
    if len(osize) == 1:
        osize = (osize[0], osize[0])
    h, w = data.shape[2], data.shape[3]
    mh = jnp.asarray(_adaptive_pool_matrix(h, osize[0]))
    mw = jnp.asarray(_adaptive_pool_matrix(w, osize[1]))
    out = jnp.einsum("oh,nchw,pw->ncop", mh, data.astype(jnp.float32), mw)
    return out.astype(data.dtype)


def _bilinear_matrix(in_size: int, out_size: int) -> np.ndarray:
    """(out, in) align-corners bilinear interpolation matrix
    (bilinear_resize-inl.h caffe2-style: scale = (in-1)/(out-1))."""
    m = np.zeros((out_size, in_size), np.float32)
    if out_size == 1 or in_size == 1:
        m[:, 0] = 1.0
        return m
    scale = (in_size - 1) / (out_size - 1)
    for i in range(out_size):
        src = i * scale
        lo = int(np.floor(src))
        hi = min(lo + 1, in_size - 1)
        frac = src - lo
        m[i, lo] += 1.0 - frac
        m[i, hi] += frac
    return m


@register("_contrib_BilinearResize2D", nin=1,
          aliases=("BilinearResize2D",),
          params={"height": param(int, None, required=True),
                  "width": param(int, None, required=True)})
def _bilinear_resize(attrs, data):
    """Bilinear resize (bilinear_resize-inl.h:50-58), align-corners
    semantics, as two 1-D interpolation matmuls."""
    h, w = data.shape[2], data.shape[3]
    mh = jnp.asarray(_bilinear_matrix(h, attrs["height"]))
    mw = jnp.asarray(_bilinear_matrix(w, attrs["width"]))
    out = jnp.einsum("oh,nchw,pw->ncop", mh, data.astype(jnp.float32), mw)
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# channel operator (the fork's R-FCN helper)
# ---------------------------------------------------------------------------
@register("_contrib_ChannelOperator", nin=-1,
          aliases=("ChannelOperator",),
          nout=lambda attrs: 2 if attrs["op_type"] == "Group_Max" else 1,
          visible=1,
          params={"op_type": param(["Group_Max", "Group_Pick",
                                    "Group_Softmax"], None, required=True),
                  "group": param(int, None, required=True),
                  "pick_type": param(["Label_Pick", "Score_Pick"],
                                     "Label_Pick")})
def _channel_operator(attrs, data, *rest):
    """Grouped channel ops (channel_operator-inl.h:32-50).

    - Group_Max: (N, C, ...) -> (N, C/group, ...) max within each group of
      ``group`` consecutive channels (+ argmax hidden output for backward).
    - Group_Softmax: softmax within each group, shape preserved.
    - Group_Pick: second input picks one channel per group:
      Label_Pick uses integer labels (N,), Score_Pick the per-group argmax
      of the picks input.
    """
    g = attrs["group"]
    op_type = attrs["op_type"]
    n, c = data.shape[0], data.shape[1]
    tail = data.shape[2:]
    grouped = data.reshape((n, c // g, g) + tail)
    if op_type == "Group_Max":
        out = jnp.max(grouped, axis=2)
        amax = jnp.argmax(grouped, axis=2).astype(data.dtype)
        return out, amax
    if op_type == "Group_Softmax":
        return jax.nn.softmax(grouped, axis=2).reshape(data.shape)
    # Group_Pick
    if not rest:
        raise MXNetError("ChannelOperator Group_Pick needs a pick input")
    pick = rest[0]
    if attrs["pick_type"] == "Score_Pick":
        idx = jnp.argmax(pick.reshape((n, c // g, g) + tail).mean(
            axis=tuple(range(3, 3 + len(tail)))), axis=2)
    else:
        idx = jnp.broadcast_to(
            pick.reshape(n, -1)[:, 0:1].astype(jnp.int32), (n, c // g))
    idx = idx.reshape((n, c // g) + (1,) * (len(tail) + 1))
    out = jnp.take_along_axis(grouped, idx, axis=2)
    return out[:, :, 0]
