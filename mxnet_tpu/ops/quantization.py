"""INT8 quantization operators.

Reference analogs (`src/operator/quantization/`, SURVEY.md N7 quantization/):
- ``_contrib_quantize`` — quantize-inl.h:90-145 (uint8 affine / int8
  zero-centered; emits (q, min, max)).
- ``_contrib_dequantize`` — dequantize-inl.h.
- ``_contrib_requantize`` — requantize-inl.h:40-90 (int32 -> int8 with
  calibrated or on-the-fly real range).
- ``_contrib_quantized_conv`` / ``_contrib_quantized_fully_connected`` —
  quantized_conv.cc / quantized_fully_connected.cc (int8 x int8 -> int32
  accumulation; output range = product ranges scaled to int32, the
  QuantizationRangeForMultiplication convention of quantization_utils.h).
- ``_contrib_quantized_pooling`` / ``_contrib_quantized_flatten`` —
  quantized_pooling.cc / quantized_flatten.cc (range pass-through).

Value convention (quantization_utils.h ``QuantizedToFloat``): a quantized
tensor q with float range (min, max) represents ``q * MaxAbs(min,max)/Q``
where Q = 127 for int8 and 2³¹-1 for int32.

TPU-native design: int8 convolution/matmul lower to XLA ``dot``/``conv``
HLOs with s8 operands and s32 accumulation — the MXU's native int8 path —
instead of the reference's cuDNN int8 or CPU reference kernels.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register, param

INT32_Q = float(2 ** 31 - 1)


def _max_abs(mn, mx):
    return jnp.maximum(jnp.abs(mn), jnp.abs(mx))


@register("_contrib_quantize", nin=3, nout=3,
          aliases=("quantize",),
          params={"out_type": param(["int8", "uint8"], "int8")})
def _quantize(attrs, data, min_range, max_range):
    """fp32 -> int8/uint8 (quantize-inl.h:90-145).  min/max_range are
    1-element float tensors (the observed/calibrated float range)."""
    mn = min_range.reshape(())
    mx = max_range.reshape(())
    if attrs["out_type"] == "int8":
        # zero-centered: scale = 127 / MaxAbs(min, max)
        t = _max_abs(mn, mx)
        scale = 127.0 / jnp.maximum(t, 1e-30)
        q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
        return q, -t.reshape(1), t.reshape(1)
    # uint8 affine
    scale = 255.0 / jnp.maximum(mx - mn, 1e-30)
    q = jnp.clip(jnp.round((data - mn) * scale), 0, 255).astype(jnp.uint8)
    return q, mn.reshape(1), mx.reshape(1)


@register("_contrib_dequantize", nin=3,
          aliases=("dequantize",),
          params={"out_type": param(["float32"], "float32")})
def _dequantize(attrs, data, min_range, max_range):
    """int8/uint8/int32 -> fp32 (dequantize-inl.h)."""
    mn = min_range.reshape(())
    mx = max_range.reshape(())
    if data.dtype == jnp.uint8:
        scale = (mx - mn) / 255.0
        return data.astype(jnp.float32) * scale + mn
    q = INT32_Q if data.dtype == jnp.int32 else 127.0
    return data.astype(jnp.float32) * (_max_abs(mn, mx) / q)


@register("_contrib_requantize", nin=3, nout=3,
          aliases=("requantize",),
          params={"min_calib_range": param(float, None),
                  "max_calib_range": param(float, None)})
def _requantize(attrs, data, min_range, max_range):
    """int32 -> int8 (requantize-inl.h:71-90): real range from calibration
    when given, else from the actual tensor extrema."""
    real = data.astype(jnp.float32) * \
        (_max_abs(min_range.reshape(()), max_range.reshape(())) / INT32_Q)
    if attrs["min_calib_range"] is not None and \
            attrs["max_calib_range"] is not None:
        t = jnp.asarray(max(abs(attrs["min_calib_range"]),
                            abs(attrs["max_calib_range"])), jnp.float32)
    else:
        t = jnp.maximum(jnp.max(jnp.abs(real)), 1e-30)
    q = jnp.clip(jnp.round(real * (127.0 / t)), -127, 127).astype(jnp.int8)
    return q, (-t).reshape(1), t.reshape(1)


def _range_for_multiplication(td, tw):
    """Output float range of an int32 accumulator holding products of two
    int8 tensors (quantization_utils.h QuantizationRangeForMultiplication):
    s32 * T_out/(2³¹-1) == s32 * (Td/127) * (Tw/127)."""
    return td * tw * INT32_Q / (127.0 * 127.0)


def _bias_to_int32(bias_q, tb, td, tw):
    """Re-scale an int8 bias (range Tb) into the s32 accumulator scale."""
    scale = (tb / 127.0) / ((td / 127.0) * (tw / 127.0))
    return jnp.round(bias_q.astype(jnp.float32) * scale).astype(jnp.int32)


@register("_contrib_quantized_conv", nin=-1, nout=3,
          params={"kernel": param("shape", None, required=True),
                  "stride": param("shape", ()),
                  "dilate": param("shape", ()),
                  "pad": param("shape", ()),
                  "num_filter": param(int, None, required=True),
                  "num_group": param(int, 1),
                  "no_bias": param(bool, False),
                  "layout": param(str, None)})
def _quantized_conv(attrs, data, weight, *rest):
    """int8 conv -> int32 (quantized_conv.cc).  Inputs: data, weight,
    [bias], min_data, max_data, min_weight, max_weight, [min_bias,
    max_bias]."""
    no_bias = attrs["no_bias"]
    if no_bias:
        (min_d, max_d, min_w, max_w), bias = rest, None
    else:
        bias, min_d, max_d, min_w, max_w, min_b, max_b = rest
    stride = attrs["stride"] or (1, 1)
    dilate = attrs["dilate"] or (1, 1)
    pad = attrs["pad"] or (0, 0)
    out = lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=tuple(stride), padding=[(p, p) for p in pad],
        rhs_dilation=tuple(dilate),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=attrs["num_group"],
        preferred_element_type=jnp.int32)
    td = _max_abs(min_d.reshape(()), max_d.reshape(()))
    tw = _max_abs(min_w.reshape(()), max_w.reshape(()))
    if bias is not None:
        tb = _max_abs(min_b.reshape(()), max_b.reshape(()))
        out = out + _bias_to_int32(bias, tb, td, tw).reshape(1, -1, 1, 1)
    t_out = _range_for_multiplication(td, tw)
    return out, (-t_out).reshape(1), t_out.reshape(1)


@register("_contrib_quantized_fully_connected", nin=-1, nout=3,
          params={"num_hidden": param(int, None, required=True),
                  "no_bias": param(bool, False),
                  "flatten": param(bool, True)})
def _quantized_fully_connected(attrs, data, weight, *rest):
    """int8 FC -> int32 (quantized_fully_connected.cc)."""
    no_bias = attrs["no_bias"]
    if no_bias:
        (min_d, max_d, min_w, max_w), bias = rest, None
    else:
        bias, min_d, max_d, min_w, max_w, min_b, max_b = rest
    x = data.reshape(data.shape[0], -1) if attrs["flatten"] else data
    out = jax.lax.dot_general(
        x.astype(jnp.int8), weight.astype(jnp.int8),
        dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    td = _max_abs(min_d.reshape(()), max_d.reshape(()))
    tw = _max_abs(min_w.reshape(()), max_w.reshape(()))
    if bias is not None:
        tb = _max_abs(min_b.reshape(()), max_b.reshape(()))
        out = out + _bias_to_int32(bias, tb, td, tw)
    t_out = _range_for_multiplication(td, tw)
    return out, (-t_out).reshape(1), t_out.reshape(1)


# ---------------------------------------------------------------------------
# fused static-scale int8 inference ops (the TPU analog of the reference's
# MKLDNN int8 subgraph ops, src/operator/subgraph/mkldnn/mkldnn_conv.cc and
# quantize_v2 of src/operator/quantization/quantize_v2-inl.h).  Design: after
# BN folding + calibration every scale is a STATIC attr, so the whole network
# is s8->s32->s8 with one fused multiply/round/clip epilogue per layer — no
# per-layer min/max reductions, no f32 round-trips, XLA fuses the epilogue
# into the conv.  Scale convention: q represents q * t/127 for threshold t.
# ---------------------------------------------------------------------------
@register("_contrib_quantize_v2", nin=1, nout=3,
          aliases=("quantize_v2",),
          params={"min_calib_range": param(float, None),
                  "max_calib_range": param(float, None),
                  "out_type": param(["int8"], "int8")})
def _quantize_v2(attrs, data):
    """fp32 -> int8 with a calibrated STATIC range (quantize_v2-inl.h):
    no on-the-fly min/max reduction; falls back to dynamic extrema when no
    calib range is given."""
    if attrs["min_calib_range"] is not None and \
            attrs["max_calib_range"] is not None:
        t = jnp.float32(max(abs(attrs["min_calib_range"]),
                            abs(attrs["max_calib_range"])))
    else:
        t = jnp.maximum(jnp.max(jnp.abs(data.astype(jnp.float32))), 1e-30)
    q = jnp.clip(jnp.round(data.astype(jnp.float32) * (127.0 / t)),
                 -127, 127).astype(jnp.int8)
    return q, jnp.reshape(-t, (1,)), jnp.reshape(t, (1,))


@register("_contrib_dequantize_v2", nin=1,
          params={"threshold": param(float, None, required=True)})
def _dequantize_v2(attrs, data):
    """int8 -> fp32 with a static symmetric threshold."""
    return data.astype(jnp.float32) * (attrs["threshold"] / 127.0)


def _requant_epilogue(s32, scale_out, fuse_relu, dequant_out):
    """Shared s32 epilogue: one static multiply + round + clip to s8, or a
    straight dequantize to f32 when the consumer is a float op."""
    real = s32.astype(jnp.float32) * scale_out
    if dequant_out:
        return real
    lo = 0.0 if fuse_relu else -127.0
    return jnp.clip(jnp.round(real), lo, 127.0).astype(jnp.int8)


@register("_sg_int8_conv", nin=-1,
          params={"kernel": param("shape", None, required=True),
                  "stride": param("shape", ()),
                  "dilate": param("shape", ()),
                  "pad": param("shape", ()),
                  "num_filter": param(int, None, required=True),
                  "num_group": param(int, 1),
                  "no_bias": param(bool, False),
                  "layout": param(str, None),
                  "scale_out": param(float, None, required=True),
                  "fuse_relu": param(bool, False),
                  "dequant_out": param(bool, False)})
def _sg_int8_conv(attrs, data, weight, *maybe_bias):
    """Fused s8 conv + s32 bias + requantize(+ReLU) -> s8 in ONE op
    (the _sg_mkldnn_conv analog).  ``scale_out`` = t_in*t_w/(127*t_out)
    (or t_in*t_w/127^2 with dequant_out); bias arrives pre-scaled s32 in
    accumulator units."""
    out = lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=tuple(attrs["stride"] or (1, 1)),
        padding=[(p, p) for p in (attrs["pad"] or (0, 0))],
        rhs_dilation=tuple(attrs["dilate"] or (1, 1)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=attrs["num_group"],
        preferred_element_type=jnp.int32)
    if maybe_bias:
        out = out + maybe_bias[0].astype(jnp.int32).reshape(1, -1, 1, 1)
    return _requant_epilogue(out, attrs["scale_out"], attrs["fuse_relu"],
                             attrs["dequant_out"])


@register("_sg_int8_fully_connected", nin=-1,
          params={"num_hidden": param(int, None, required=True),
                  "no_bias": param(bool, False),
                  "flatten": param(bool, True),
                  "scale_out": param(float, None, required=True),
                  "fuse_relu": param(bool, False),
                  "dequant_out": param(bool, False)})
def _sg_int8_fully_connected(attrs, data, weight, *maybe_bias):
    """Fused s8 FC + s32 bias + requantize(+ReLU) (one op, static scale)."""
    x = data.reshape(data.shape[0], -1) if attrs["flatten"] else data
    out = jax.lax.dot_general(
        x.astype(jnp.int8), weight.astype(jnp.int8),
        dimension_numbers=(((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    if maybe_bias:
        out = out + maybe_bias[0].astype(jnp.int32)
    return _requant_epilogue(out, attrs["scale_out"], attrs["fuse_relu"],
                             attrs["dequant_out"])


@register("_sg_int8_elemwise_add", nin=2,
          params={"scale_a": param(float, None, required=True),
                  "scale_b": param(float, None, required=True),
                  "fuse_relu": param(bool, False)})
def _sg_int8_elemwise_add(attrs, a, b):
    """int8 residual add (quantized_elemwise_add.cc analog): both operands
    rescaled into the OUTPUT threshold's units with static scales, so skip
    connections never leave int8."""
    real = a.astype(jnp.float32) * attrs["scale_a"] \
        + b.astype(jnp.float32) * attrs["scale_b"]
    lo = 0.0 if attrs["fuse_relu"] else -127.0
    return jnp.clip(jnp.round(real), lo, 127.0).astype(jnp.int8)


@register("_sg_int8_pooling", nin=1,
          params={"kernel": param("shape", ()),
                  "pool_type": param(["max"], "max"),
                  "global_pool": param(bool, False),
                  "stride": param("shape", ()),
                  "pad": param("shape", ()),
                  "pooling_convention": param(["valid", "full"], "valid"),
                  "count_include_pad": param(bool, True),
                  "p_value": param(int, 2)})
def _sg_int8_pooling(attrs, data):
    """Max pooling directly on s8 (range-preserving, no requantize)."""
    from .nn import _pooling
    return _pooling(attrs, data.astype(jnp.int8))


@register("_sg_int8_global_avg_pool", nin=1)
def _sg_int8_global_avg_pool(attrs, data):
    """Global average pool on s8: s32 accumulate over HxW, round back to
    s8.  The mean of values in [-t, t] stays in [-t, t], so the output
    rides the input threshold unchanged — no requantize step (round-5
    head probe: keeps the s8 chain alive into the final FC so
    _sg_int8_fully_connected gets a quantized input instead of falling
    back to f32)."""
    axes = tuple(range(2, data.ndim))   # all spatial dims (1-D/2-D/3-D)
    s = jnp.sum(data.astype(jnp.int32), axis=axes, keepdims=True)
    hw = int(np.prod([data.shape[a] for a in axes]))
    return jnp.clip(jnp.rint(s / hw), -127, 127).astype(jnp.int8)


@register("_contrib_quantized_pooling", nin=3, nout=3,
          params={"kernel": param("shape", ()),
                  "pool_type": param(["max", "avg"], "max"),
                  "global_pool": param(bool, False),
                  "stride": param("shape", ()),
                  "pad": param("shape", ()),
                  "pooling_convention": param(["valid", "full"], "valid"),
                  "count_include_pad": param(bool, True),
                  "p_value": param(int, 2)})
def _quantized_pooling(attrs, data, min_range, max_range):
    """int8 pooling, range pass-through (quantized_pooling.cc)."""
    from .nn import _pooling
    if attrs["pool_type"] == "max":
        out = _pooling(attrs, data.astype(jnp.int8))
    else:
        out = jnp.clip(jnp.round(_pooling(attrs, data.astype(jnp.float32))),
                       -127, 127).astype(jnp.int8)
    return out, min_range, max_range


@register("_contrib_quantized_flatten", nin=3, nout=3)
def _quantized_flatten(attrs, data, min_range, max_range):
    """Flatten on quantized data, range pass-through
    (quantized_flatten.cc)."""
    return (data.reshape(data.shape[0], -1), min_range, max_range)
