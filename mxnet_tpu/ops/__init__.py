"""Operator library: registry + op families.

The TPU-native replacement for ``src/operator/**`` (SURVEY.md N7): each op is
one pure jittable JAX function registered in :mod:`.registry`; gradients come
from jax.vjp, shape/type inference from jax.eval_shape.
"""
from .registry import (Operator, register, get_op, list_ops, apply_op, param,
                       OPS)

# importing the families populates the registry
from . import elemwise      # noqa: F401
from . import reduce        # noqa: F401
from . import matrix        # noqa: F401
from . import nn            # noqa: F401
from . import rnn           # noqa: F401
from . import init_random   # noqa: F401
from . import optimizer_ops # noqa: F401
from . import shape_hints   # noqa: F401  (installs arg names + infer hints)
from . import vision_fork   # noqa: F401  (yangyu12 fork custom vision ops)
from . import contrib_det   # noqa: F401  (SSD/RCNN detection contrib ops)
from . import contrib_misc  # noqa: F401  (CTC/FFT/resize/… contrib ops)
from . import linalg        # noqa: F401  (_linalg_* BLAS3/LAPACK family)
from . import spatial       # noqa: F401  (STN/correlation/SVM ops)
from . import control_flow  # noqa: F401  (_foreach scan op)
from . import quantization  # noqa: F401  (INT8 quantize/quantized_* ops)
from . import image_ops     # noqa: F401  (_image_* transform ops)
from . import misc_parity   # noqa: F401  (histogram/ravel/scatter/… tails)
