"""Operator registry: the TPU-native replacement for the NNVM op registry.

Reference analog: ``NNVM_REGISTER_OP`` sites across ``src/operator/**`` with
typed attributes (``include/mxnet/op_attr_types.h``): ``FCompute``,
``FInferShape/Type``, ``FGradient``, resource requests.  TPU-native design:

- Each op is ONE pure, jittable JAX function ``fn(attrs, *inputs) -> outputs``.
  Forward AND backward come from this single definition: gradients are derived
  with ``jax.vjp`` (the analog of FGradient), and shape/type inference is
  ``jax.eval_shape`` (the analog of FInferShape/FInferType) — one source of
  truth instead of four hand-written attribute functions per op.
- ``attrs`` is a hashable :class:`~mxnet_tpu.base.AttrDict` parsed by a typed
  parameter spec (the ``dmlc::Parameter`` analog), so compiled executables can
  be cached on ``(op, attrs)`` — XLA then caches per input shape under `jit`.
- Ops needing randomness declare ``needs_rng``; the dispatch layer threads an
  explicit threefry key (SURVEY.md §7.3 "RNG parity").

Eager dispatch cost (SURVEY.md §7.3): every op call goes through a
``jax.jit``-wrapped callable cached on ``(name, attrs)``; XLA executable reuse
across calls with equal shapes makes the imperative path cheap, and fused
multi-op regions come from CachedOp/Executor jitting whole graphs.
"""
from __future__ import annotations

import ast
import functools
import os
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from ..base import AttrDict, MXNetError
from .. import atlas as _atlas
from .. import profiler as _profiler
from .. import telemetry as _telemetry
from .. import program_cache as _program_cache

__all__ = ["Operator", "register", "get_op", "list_ops", "apply_op",
           "param", "OPS"]

OPS: Dict[str, "Operator"] = {}

# jit-cache observability: recompiles are the classic silent TPU perf bug
# (a drifting shape or env flag turns every step into a compile).  Hit/miss
# counts and the compile-duration histogram make them visible in a /metrics
# scrape; the XLA::Compile trace span makes them visible in Perfetto.
_JIT_HITS = _telemetry.counter(
    "op_jit_cache_hits_total",
    "Operator jit-cache lookups served by an existing entry", ("op",))
_JIT_MISSES = _telemetry.counter(
    "op_jit_cache_misses_total",
    "Operator jit-cache lookups that built a new entry", ("op",))
_JIT_ENTRIES = _telemetry.gauge(
    "op_jit_cache_entries", "Live operator jit-cache entries (all ops)")
_COMPILE_TIME = _telemetry.histogram(
    "op_compile_seconds",
    "First-invocation duration of a fresh jit-cache entry (where jax "
    "traces and XLA compiles — jax.jit construction itself is lazy)",
    ("op",))


# --------------------------------------------------------------------------
# typed parameter spec — the dmlc::Parameter analog
# --------------------------------------------------------------------------
class param:
    """One typed op parameter: ``param(type, default)``.

    type is one of: int, float, bool, str, 'shape' (tuple of ints),
    'dtype' (numpy dtype name).  Values arriving as strings (reference C-API
    convention; also what Symbol JSON stores) are coerced.
    """

    def __init__(self, ptype, default=None, required=False):
        self.ptype = ptype
        self.default = default
        self.required = required

    def coerce(self, v):
        t = self.ptype
        if v is None:
            return None
        if t == "shape":
            if isinstance(v, str):
                v = ast.literal_eval(v)
            if isinstance(v, (int, np.integer)):
                return (int(v),)
            return tuple(int(x) for x in v)
        if t == "floats":
            if isinstance(v, str):
                v = ast.literal_eval(v)
            if isinstance(v, (int, float, np.floating, np.integer)):
                return (float(v),)
            return tuple(float(x) for x in v)
        if t == "dtype":
            if v in (None, "None"):
                return None
            return np.dtype(v).name
        if t is bool:
            if isinstance(v, str):
                return v.lower() in ("1", "true", "yes", "on")
            return bool(v)
        if t is int:
            return int(v)
        if t is float:
            return float(v)
        if t is str:
            return str(v)
        if isinstance(t, (list, tuple)):  # enum
            v = str(v)
            if v not in t:
                raise MXNetError("invalid enum value %r (expected one of %s)" % (v, t))
            return v
        return v


class Operator:
    """A registered operator."""

    def __init__(self, name: str, fn: Callable, *,
                 params: Optional[Dict[str, param]] = None,
                 nin: Optional[int] = None, nout: Any = 1,
                 needs_rng: bool = False,
                 train_aware: bool = False,
                 aux_writeback: Optional[Dict[int, int]] = None,
                 arg_names: Optional[Sequence[str]] = None,
                 aliases: Sequence[str] = (),
                 mutate_inputs: Sequence[int] = (),
                 env_keys: Sequence[str] = (),
                 doc: str = ""):
        self.name = name
        self.fn = fn
        self.params = params or {}
        self.nin = nin          # None = from arg_names; -1 = variadic
        self.nout = nout        # int or callable(attrs)->int
        self.needs_rng = needs_rng
        # train_aware ops receive attrs['__train__'] from the dispatch layer
        # (the analog of the reference's OpContext.is_train, op_attr_types.h).
        self.train_aware = train_aware
        # {output_idx: input_idx}: the dispatch layer writes these outputs
        # back into the given inputs — how BatchNorm's moving-stat mutation
        # and optimizer-state updates are expressed functionally on TPU.
        self.aux_writeback = aux_writeback or {}
        # user-visible output count (reference FNumVisibleOutputs): int,
        # callable(attrs)->int, or None = all outputs visible.
        self.visible = None
        # indices of auxiliary inputs (reference FListAuxiliaryStates —
        # BatchNorm's moving stats): not gradient targets, not arguments.
        self.aux_inputs: Tuple[int, ...] = ()
        # partial shape inference hook: fn(attrs, in_shapes) -> in_shapes
        # with None entries filled (the FInferShape analog for inferring
        # parameter shapes from data shape, e.g. conv weights).
        self.shape_hint = None
        self.arg_names = list(arg_names) if arg_names else None
        self.aliases = tuple(aliases)
        self.mutate_inputs = tuple(mutate_inputs)  # e.g. optimizer update ops
        # env vars the op's fn reads at TRACE time (formulation flags like
        # MXNET_TPU_PALLAS_CONV).  Their current values join the jit-cache
        # key, so toggling a flag mid-process can never serve a stale
        # executable compiled under the old value.
        self.env_keys = tuple(env_keys)
        self.doc = doc
        self._jit_cache: Dict[Any, Callable] = {}

    # ---- attrs ----------------------------------------------------------
    def parse_attrs(self, kwargs: Dict[str, Any]) -> AttrDict:
        out = {}
        for k, spec in self.params.items():
            if k in kwargs:
                out[k] = spec.coerce(kwargs.pop(k))
            elif spec.required:
                raise MXNetError("op %s: required param %r missing" % (self.name, k))
            else:
                out[k] = spec.default
        # pass through unknown attrs untouched (reference tolerates extra
        # attrs like __layout__ on symbols); keep only hashable ones
        for k, v in list(kwargs.items()):
            if k.startswith("__") or k in ("name", "ctx", "out"):
                continue
            out[k] = tuple(v) if isinstance(v, list) else v
        return AttrDict(out)

    def num_outputs(self, attrs: AttrDict) -> int:
        return self.nout(attrs) if callable(self.nout) else self.nout

    def get_aux_writeback(self, attrs: AttrDict) -> Dict[int, int]:
        """aux_writeback may be a static dict or callable(attrs)->dict
        (ops like Custom whose aux count depends on attrs)."""
        wb = self.aux_writeback
        return wb(attrs) if callable(wb) else wb

    def num_visible_outputs(self, attrs: AttrDict) -> int:
        if self.visible is None:
            return self.num_outputs(attrs)
        return self.visible(attrs) if callable(self.visible) else self.visible

    # ---- execution ------------------------------------------------------
    def compiled(self, attrs: AttrDict) -> Callable:
        """jit-compiled entry for these attrs (shape-specialized by XLA).

        Cache key is ``attrs`` alone, or ``(attrs, env-values)`` when the
        op declares ``env_keys`` — trace-time formulation flags then take
        effect immediately instead of being baked into a stale executable.

        Observability: hit/miss counters and a per-op compile-duration
        histogram when telemetry is enabled.  jax.jit is lazy — tracing
        and XLA compilation happen at the first *invocation* — so a fresh
        entry is a self-replacing wrapper that times that first call and
        records an ``XLA::Compile`` span, then swaps in the raw jitted
        callable: steady state pays nothing beyond the cache lookup.
        """
        key = attrs if not self.env_keys else (
            attrs, tuple(os.environ.get(k) for k in self.env_keys))
        c = self._jit_cache.get(key)
        if c is not None:
            if _telemetry.enabled:
                _JIT_HITS.labels(op=self.name).inc()
                _program_cache.note_memory_hit()
            return c
        if _telemetry.enabled:
            _JIT_MISSES.labels(op=self.name).inc()
        _program_cache.ensure_enabled()
        fn = self.fn
        # Scope choke point: per-op jitted programs carry an anonymous
        # atlas scope ("<OpType>:~" — no graph node here) so single-op
        # lowerings attribute the same way fused plans do.
        scope = _atlas.scope_name(self.name)

        def _scoped(*arrays):
            with jax.named_scope(scope):
                return fn(attrs, *arrays)

        jfn = jax.jit(_scoped)
        name, cache = self.name, self._jit_cache

        def _first_call(*arrays):
            begin = _profiler._now_us()
            t0 = time.perf_counter()
            puts0 = _program_cache.put_count()
            try:
                return jfn(*arrays)
            finally:
                cache[key] = jfn
                if _telemetry.enabled:
                    _COMPILE_TIME.labels(op=name).observe(
                        time.perf_counter() - t0)
                # warm restart visibility: when the persistent program
                # cache served every module this call needed (no put),
                # the span is a restore, not a compile — zero
                # XLA::Compile spans is the deploy-prefill contract
                restored = (puts0 is not None
                            and _program_cache.put_count() == puts0)
                _profiler.record_span(
                    "XLA::%s %s" % ("Restore" if restored else "Compile",
                                    name),
                    begin, _profiler._now_us(), "compile")

        self._jit_cache[key] = _first_call
        if _telemetry.enabled:
            _JIT_ENTRIES.inc()
        return _first_call

    def __call__(self, attrs: AttrDict, *arrays):
        return self.compiled(attrs)(*arrays)

    def abstract_eval(self, attrs: AttrDict, *avals):
        """Shape/dtype inference = jax.eval_shape (replaces FInferShape/Type)."""
        fn = self.fn
        return jax.eval_shape(lambda *xs: fn(attrs, *xs), *avals)

    def __repr__(self):
        return "<Operator %s>" % self.name


def register(name: str, *, params=None, nin=None, nout=1, needs_rng=False,
             train_aware=False, aux_writeback=None, visible=None,
             arg_names=None, aliases=(), mutate_inputs=(), env_keys=(),
             doc=""):
    """Decorator: register a pure JAX function as an operator."""

    def deco(fn):
        op = Operator(name, fn, params=params, nin=nin, nout=nout,
                      needs_rng=needs_rng, train_aware=train_aware,
                      aux_writeback=aux_writeback, arg_names=arg_names,
                      aliases=aliases, mutate_inputs=mutate_inputs,
                      env_keys=env_keys,
                      doc=doc or (fn.__doc__ or ""))
        op.visible = visible
        OPS[name] = op
        for a in aliases:
            OPS[a] = op
        return fn

    return deco


def get_op(name: str) -> Operator:
    op = OPS.get(name)
    if op is None:
        raise MXNetError("Operator %r is not registered (have %d ops)"
                         % (name, len(OPS)))
    return op


def list_ops():
    return sorted(OPS)


def apply_op(name: str, *arrays, **kwargs):
    """Low-level functional invoke: parse attrs, run, return raw jax arrays."""
    op = get_op(name)
    attrs = op.parse_attrs(dict(kwargs))
    return op(attrs, *arrays)
