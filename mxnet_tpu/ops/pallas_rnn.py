"""Pallas fused LSTM recurrence — the cuDNN-RNN role on TPU.

Reference analog: ``src/operator/cudnn_rnn-inl.h`` (fused GPU RNN) and the
2,357-LoC CPU fallback ``src/operator/rnn_impl.h``.  The reference fuses the
whole recurrence into one cuDNN call; the TPU design fuses it into ONE
Pallas kernel whose grid iterates the time axis with the hidden/cell state
resident in VMEM scratch — zero per-timestep dispatch, per-gate h2h matmuls
on the MXU, all gate elementwise math fused on the VPU.

Layout notes:
  * gates are carried on a leading dim of 4 (``(T, 4, B, H)``) instead of a
    packed ``4H`` lane axis, so no lane-slicing at non-128-aligned
    boundaries (the reference packs ``[i f g o]`` along the feature dim,
    which would force misaligned lane shifts for H like 650);
  * recurrent weights arrive pre-transposed per gate ``(4, H, H)``;
  * cell state is f32 in VMEM (bf16 h, f32 c — cuDNN's fp16-RNN split);
  * forward saves gate activations + raw cell states (the cuDNN
    "reserve space") for the reverse-time backward kernel, which
    accumulates ``dR``/``db`` in VMEM f32 across the whole sequence,
    seeds its state grads from the terminal cotangents (exact dhT/dcT
    handling), and emits per-step pre-activation gate grads; their
    projection back to the layer input is one large MXU matmul outside
    (ops/rnn.py).

Used when ``MXNET_TPU_PALLAS_RNN`` != "0" on TPU, dims are tile-aligned,
and sizes fit VMEM; otherwise ops/rnn.py falls back to ``lax.scan``.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["lstm_scan", "lstm_scan_available"]

# set True (tests) to run kernels through the Pallas interpreter on CPU
INTERPRET = False


def lstm_scan_available(B, H, dtype=None) -> bool:
    """Pallas path SIZE/ENV eligibility (platform is NOT checked here).

    The TPU-vs-other choice happens at lowering time: callers wrap the
    kernel in ``jax.lax.platform_dependent`` (ops/rnn.py:_cell_scan), so a
    CPU-context LSTM on a TPU host lowers the ``lax.scan`` branch and
    never reaches Mosaic — selection by committed device or default
    backend was unsound for traced data (advisor r03).  This predicate
    only answers "would the kernel compile if the target IS a TPU".

    VMEM bound actually enforced: the estimate below < 28 MB.  The
    RESIDENT terms are the per-gate weights rt4 (model dtype) and the
    outside-kernel dr4 story (f32 dR lives outside; see _bwd_kernel), plus
    double-buffered per-step blocks; Mosaic streams the (T, ...) blocks,
    so the 16 MB scoped-VMEM limit applies to residents + two step
    buffers, not the raw sum.  The 28 MB cut-off is the empirical compile
    envelope measured on v5e: H=650/B=128 (estimate ~17.5 MB) compiles
    and runs; the first failing config measured was ~29 MB by this
    estimate.
    """
    if os.environ.get("MXNET_TPU_PALLAS_RNN", "1") == "0":
        return False
    if dtype is not None and jnp.dtype(dtype) not in (
            jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float16),
            jnp.dtype(jnp.float32)):
        return False           # f64 (x64 mode) has no kernel path
    if H > 2048 or B > 1024:   # all blocks are whole-array (no tile
        return False           # alignment constraints); VMEM only
    es = 2 if dtype is None or jnp.dtype(dtype).itemsize == 2 else 4
    # backward kernel is the VMEM high-water mark: rt4 (model dtype) +
    # double-buffered per-step blocks (gates in model dtype, 4x f32 (B,H)
    # inputs, f32 dxp out) + f32 scratch pair
    vmem = (4 * H * H * (es + 4)
            + 2 * B * H * (4 * es + 4 * 4 + 4 * 4)
            + 2 * B * H * 4)
    return vmem < 28 * 1024 * 1024


# --------------------------------------------------------------- forward
def _fwd_kernel(xp_ref, h0_ref, c0_ref, rt_ref, b_ref,
                ys_ref, gates_ref, cs_ref, hT_ref, cT_ref,
                h_scr, c_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h = h_scr[:]
    pre = [None] * 4
    for k in range(4):
        pre[k] = (xp_ref[0, k].astype(jnp.float32)
                  + jax.lax.dot_general(
                      h, rt_ref[k], (((1,), (0,)), ((), ())),
                      preferred_element_type=jnp.float32)
                  + b_ref[k].astype(jnp.float32))
    i = jax.nn.sigmoid(pre[0])
    f = jax.nn.sigmoid(pre[1])
    g = jnp.tanh(pre[2])
    o = jax.nn.sigmoid(pre[3])
    c = f * c_scr[:] + i * g
    h_new = (o * jnp.tanh(c)).astype(ys_ref.dtype)
    c_scr[:] = c
    h_scr[:] = h_new
    ys_ref[0] = h_new
    # reserve space for backward
    gates_ref[0, 0] = i.astype(gates_ref.dtype)
    gates_ref[0, 1] = f.astype(gates_ref.dtype)
    gates_ref[0, 2] = g.astype(gates_ref.dtype)
    gates_ref[0, 3] = o.astype(gates_ref.dtype)
    cs_ref[0] = c
    # constant-index outputs: the final grid step's value is what lands
    hT_ref[:] = h_new
    cT_ref[:] = c


def _lstm_fwd_impl(xp4, h0, c0, rt4, b4):
    T, _, B, H = xp4.shape
    dt = xp4.dtype
    ys, gates, cs, hT, cT = pl.pallas_call(
        _fwd_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, 4, B, H), lambda t: (t, 0, 0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
            pl.BlockSpec((4, H, H), lambda t: (0, 0, 0)),
            pl.BlockSpec((4, 1, H), lambda t: (0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, 4, B, H), lambda t: (t, 0, 0, 0)),
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), dt),
            jax.ShapeDtypeStruct((T, 4, B, H), dt),
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), dt),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, H), dt),
            pltpu.VMEM((B, H), jnp.float32),
        ],
        interpret=INTERPRET,
    )(xp4, h0, c0, rt4, b4)
    return (ys, hT, cT.astype(c0.dtype)), (gates, cs, ys, h0, c0)


# -------------------------------------------------------------- backward
def _bwd_kernel(gates_ref, cs_ref, cprev_ref, dys_ref,
                dhT_ref, dcT_ref, rt_ref,
                dxp_ref, dh0_ref, dc0_ref,
                dh_scr, dc_scr):
    """Grid step j processes t = T-1-j (reversed via index maps).

    Emits only the per-step pre-activation gate grads; the dR/db
    reductions happen OUTSIDE as 4 large MXU GEMMs over (T*B, H) — keeping
    them in-kernel needs a (4,H,H) f32 VMEM accumulator that blows the
    16 MB scoped-vmem limit at H=650 (measured: 19.4 M requested)."""
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _():
        # seed the reverse recursion with the terminal-state cotangents
        dh_scr[:] = dhT_ref[:]
        dc_scr[:] = dcT_ref[:]

    i = gates_ref[0, 0].astype(jnp.float32)
    f = gates_ref[0, 1].astype(jnp.float32)
    g = gates_ref[0, 2].astype(jnp.float32)
    o = gates_ref[0, 3].astype(jnp.float32)
    tc = jnp.tanh(cs_ref[0])
    c_prev = cprev_ref[0].astype(jnp.float32)

    dh = dh_scr[:] + dys_ref[0].astype(jnp.float32)
    dct = dh * o * (1.0 - tc * tc) + dc_scr[:]
    d_pre = [
        (dct * g) * i * (1.0 - i),           # di_pre
        (dct * c_prev) * f * (1.0 - f),      # df_pre
        (dct * i) * (1.0 - g * g),           # dg_pre
        (dh * tc) * o * (1.0 - o),           # do_pre
    ]
    dc_new = dct * f
    dc_scr[:] = dc_new

    cdt = rt_ref.dtype
    dh_new = None
    for k in range(4):
        dk = d_pre[k]
        dxp_ref[0, k] = dk.astype(dxp_ref.dtype)
        # dh_prev += d_pre_k @ Rt_k^T  (contract Rt dim 1)
        part = jax.lax.dot_general(
            dk.astype(cdt), rt_ref[k], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dh_new = part if dh_new is None else dh_new + part
    dh_scr[:] = dh_new
    # after the final grid step (t=0) these hold d h0 / d c0
    dh0_ref[:] = dh_new
    dc0_ref[:] = dc_new


@jax.custom_vjp
def _lstm_pallas(xp4, h0, c0, rt4, b4):
    out, _ = _lstm_fwd_impl(xp4, h0, c0, rt4, b4)
    return out


def _lstm_vjp_fwd(xp4, h0, c0, rt4, b4):
    out, res = _lstm_fwd_impl(xp4, h0, c0, rt4, b4)
    return out, res + (rt4,)


def _lstm_vjp_bwd(res, cts):
    gates, cs, ys, h0, c0, rt4 = res
    dys, dhT, dcT = cts
    T, _, B, H = gates.shape
    dt = gates.dtype

    cprev = jnp.concatenate(
        [c0[None].astype(jnp.float32), cs[:-1]], axis=0).astype(ys.dtype)
    dys = dys.astype(ys.dtype)
    zero = jnp.zeros((B, H), jnp.float32)
    dhT = zero if dhT is None else dhT.astype(jnp.float32)
    dcT = zero if dcT is None else dcT.astype(jnp.float32)

    rev4 = lambda j: (T - 1 - j, 0, 0, 0)   # noqa: E731
    rev3 = lambda j: (T - 1 - j, 0, 0)      # noqa: E731
    dxp, dh0, dc0 = pl.pallas_call(
        _bwd_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, 4, B, H), rev4),
            pl.BlockSpec((1, B, H), rev3),
            pl.BlockSpec((1, B, H), rev3),
            pl.BlockSpec((1, B, H), rev3),
            pl.BlockSpec((B, H), lambda j: (0, 0)),
            pl.BlockSpec((B, H), lambda j: (0, 0)),
            pl.BlockSpec((4, H, H), lambda j: (0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 4, B, H), rev4),
            pl.BlockSpec((B, H), lambda j: (0, 0)),
            pl.BlockSpec((B, H), lambda j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, 4, B, H), dt),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ],
        interpret=INTERPRET,
    )(gates, cs, cprev, dys, dhT, dcT, rt4)

    # dR_k = h_prev^T @ d_pre_k and db_k = sum_B d_pre_k — big MXU GEMMs
    # over the whole (T*B, H) sequence (the hoisted-projection trick in
    # reverse; doing this in-kernel needs a VMEM accumulator that exceeds
    # the 16 MB scoped limit)
    hprev = jnp.concatenate([h0[None].astype(ys.dtype), ys[:-1]], axis=0)
    hp2 = hprev.reshape(T * B, H)
    dxp2 = dxp.transpose(1, 0, 2, 3).reshape(4, T * B, H)
    dr4 = jax.lax.dot_general(
        hp2, dxp2, (((0,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # (H, 4, H)
    dr4 = dr4.transpose(1, 0, 2)                     # (4, H, H)
    db4 = jnp.sum(dxp2.astype(jnp.float32), axis=1)[:, None, :]

    return (dxp.astype(dt), dh0.astype(h0.dtype), dc0.astype(c0.dtype),
            dr4.astype(rt4.dtype), db4.astype(jnp.float32))


_lstm_pallas.defvjp(_lstm_vjp_fwd, _lstm_vjp_bwd)


def lstm_scan(xproj, h0, c0, R, bR):
    """Drop-in replacement for the lax.scan LSTM recurrence.

    xproj: (T, B, 4H) packed [i f g o] input projections (x @ W^T + bW);
    h0, c0: (B, H); R: (4H, H); bR: (4H,).
    Returns ys (T, B, H), hT, cT — matching ops/rnn.py:_cell_scan.
    """
    T, B, H4 = xproj.shape
    H = H4 // 4
    xp4 = xproj.reshape(T, B, 4, H).transpose(0, 2, 1, 3)   # (T,4,B,H)
    rt4 = R.reshape(4, H, H).transpose(0, 2, 1)             # per-gate R^T
    b4 = bR.reshape(4, 1, H).astype(jnp.float32)
    ys, hT, cT = _lstm_pallas(xp4, h0, c0, rt4.astype(xproj.dtype), b4)
    return ys, hT, cT
