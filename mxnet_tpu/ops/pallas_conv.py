"""Pallas implicit-GEMM 2-D convolution for the ResNet bottleneck shapes.

docs/perf_analysis.md (rounds 2-5) established that ResNet-50 training is
bound by XLA's in-graph conv efficiency (~35-45 TF aggregate) while the
same chip sustains 125 TF on matmuls, and that both pure-XLA
reformulations (9-shifted-GEMM forward, per-tap GEMM wgrad) were
e2e-measured and rejected.  This module is the remaining lever — the
hand-written kernel path `ops/pallas_attention.py` / `ops/pallas_rnn.py`
already proved out — productionized from the round-3 probe prototype
(`tools/probe_pallas_conv.py`, measured 87-171 TF on the eligible
3x3 shapes, real chip).

Formulation: implicit GEMM over flattened padded row-frames.  The NHWC
activation is padded to (Hp, WP) per image and flattened to rows of C;
an output position k = h*WP + w then reads input row k + dh*WP + dw for
tap (dh, dw) — so each tap is ONE contiguous row-slice matmul
(TILE, C) @ (C, O) on the MXU, accumulated in f32 across the KH*KW taps
with no im2col materialization in HBM and zero in-kernel relayouts.
Images are laid out on a common 8-aligned frame stride L so NB of them
stack into one grid step (small-spatial shapes keep the MXU fed); the
input BlockSpec is element-indexed (``pl.unblocked``) because tap halos
overlap tiles.

Backward is a ``custom_vjp`` whose both arms are also Pallas kernels
(mirroring ``flash_attention_bwd``'s two-pass structure):

  dgrad: dx = conv_s1(dy, flip(W)^T) — the SAME forward kernel on the
         cotangent with spatially-flipped, io-swapped taps (exact for
         stride-1 SAME).
  wgrad: dw[tap] = x_tap^T @ dy — one (TILE, C)^T @ (TILE, O) GEMM per
         tap per grid step, accumulated across the sequential TPU grid
         into a VMEM-resident (KH*KW, C, O) f32 output (the revisited-
         block reduction pattern).

Eligibility (`conv3x3_same_available` / `conv3x3_s2_available`) mirrors
``flash_attention_available``: env flag + lane/VMEM size gates only;
non-TPU platforms are ineligible unless ``INTERPRET`` (tests run the
same jaxpr on CPU via interpret mode).  The lane gate requires
C % 128 == 0: the round-3 probe measured the C=64 56px shape at 10 TF
(lane-starved contraction) vs 96-171 TF for the 128/256/512-channel
shapes.  Stride-2 3x3 convs ride the same stride-1 core through an
exact space-to-depth(2) rewrite (2x2 taps on 4C channels — the same
transform as ``ops/nn.py:_stem_s2d_conv``); their backward stays on
XLA's transposed-conv lowering.

``MXNET_TPU_PALLAS_CONV`` defaults OFF: every prior hand-conv probe
(r3 forward, r4 shifted-GEMM, r5 GEMM-wgrad) won isolated chains and
lost e2e to whole-graph scheduling, so per the repo's wire-and-re-bench
discipline the flag ships off until a chip session measures an e2e win
(tools/probe_pallas_conv.py emits the per-shape JSON for that session).
The flag is part of the Convolution jit-cache key (ops/registry.py), so
toggling it takes effect immediately — no cache clearing or process
restart.
"""
from __future__ import annotations

import functools
import os
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["INTERPRET", "conv3x3_same", "conv3x3_same_available",
           "conv3x3_s2", "conv3x3_s2_available"]

#: tests flip this to run the kernels' jaxpr on CPU (same pattern as
#: pallas_attention.INTERPRET); it also lifts the TPU-platform gate.
INTERPRET = False

#: conservative per-kernel VMEM budget (the 16 MB scoped limit minus
#: headroom for Mosaic's own spills — same margin pallas_rnn uses).
_VMEM_BUDGET = 12 * 1024 * 1024

_PadsT = Tuple[Tuple[int, int], Tuple[int, int]]


def _align(v: int, m: int) -> int:
    return (v + m - 1) // m * m


class _Plan(NamedTuple):
    """Static frame geometry for one (shape, taps, pads) conv instance."""
    NB: int        # images stacked per grid step
    G: int         # grid size (N // NB)
    L: int         # 8-aligned per-image frame stride, rows of channels
    TILE: int      # output rows per grid step (NB * L)
    SLAB: int      # input rows fetched per grid step (TILE + tap halo)
    WP: int        # padded width (frame row length)
    Hp: int        # padded height
    Ho: int        # output height
    Wo: int        # output width
    F_in: int      # valid input frame rows (Hp * WP)
    F_out: int     # output frame rows (Ho * WP)
    total: int     # padded flat input length


def _frame_geometry(H, W, KH, KW, pads):
    (pt, pb), (pw_l, pw_r) = pads
    Hp, WP = H + pt + pb, W + pw_l + pw_r
    Ho, Wo = Hp - KH + 1, WP - KW + 1
    return Hp, WP, Ho, Wo


def _est_bytes(plan: _Plan, C, O, KH, KW, esize):
    """Worst-case VMEM residency across the fwd/dgrad/wgrad kernels:
    double-buffered input slab + output tile, f32 accumulator, and either
    the tap weights (fwd/dgrad) or the grid-resident wgrad accumulator."""
    cm = max(C, O)
    fwd = (2 * plan.SLAB * cm * esize + 2 * plan.TILE * cm * esize
           + plan.TILE * cm * 4 + KH * KW * C * O * esize)
    wgrad = (2 * plan.SLAB * C * esize + 2 * plan.TILE * O * esize
             + KH * KW * C * O * 4)
    return max(fwd, wgrad)


def _plan(N, H, W, C, O, KH, KW, pads: _PadsT, esize) -> Optional[_Plan]:
    """Largest batch-stacking NB whose VMEM estimate fits the budget."""
    Hp, WP, Ho, Wo = _frame_geometry(H, W, KH, KW, pads)
    F_in, F_out = Hp * WP, Ho * WP
    L = _align(max(F_in, F_out), 8)
    halo = (KH - 1) * WP + (KW - 1)
    for NB in (16, 8, 4, 2, 1):
        if N % NB:
            continue
        TILE = NB * L
        SLAB = _align(TILE + halo, 8)
        G = N // NB
        total = _align((G - 1) * TILE + SLAB, 8)
        p = _Plan(NB, G, L, TILE, SLAB, WP, Hp, Ho, Wo, F_in, F_out, total)
        if _est_bytes(p, C, O, KH, KW, esize) <= _VMEM_BUDGET:
            return p
    return None


def _flatten_frames(x, pads: _PadsT, plan: _Plan, total=None):
    """(N, H, W, C) -> (rows, C) padded row-frames on the L stride."""
    N = x.shape[0]
    C = x.shape[-1]
    (pt, pb), (pw_l, pw_r) = pads
    xp = jnp.pad(x, ((0, 0), (pt, pb), (pw_l, pw_r), (0, 0)))
    F = xp.shape[1] * xp.shape[2]
    xf = xp.reshape(N, F, C)
    xf = jnp.pad(xf, ((0, 0), (0, plan.L - F), (0, 0))).reshape(N * plan.L, C)
    if total is not None and total > N * plan.L:
        xf = jnp.pad(xf, ((0, total - N * plan.L), (0, 0)))
    return xf


# ------------------------------------------------------------------ kernels
def _taps_kernel(x_ref, w_ref, o_ref, *, TILE, WP, KH, KW):
    """Implicit-GEMM forward: one row-slice matmul per tap, f32 acc."""
    acc = None
    for dh in range(KH):
        for dw in range(KW):
            xs = x_ref[pl.ds(dh * WP + dw, TILE), :]
            p = jax.lax.dot_general(
                xs, w_ref[dh * KW + dw], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            acc = p if acc is None else acc + p
    o_ref[:] = acc.astype(o_ref.dtype)


def _wgrad_kernel(x_ref, g_ref, o_ref, *, TILE, WP, KH, KW):
    """dw[tap] += x_tap^T @ dy, accumulated across the sequential grid
    into the VMEM-resident (KH*KW, C, O) f32 output block."""
    @pl.when(pl.program_id(0) == 0)
    def _():
        o_ref[...] = jnp.zeros_like(o_ref)
    gt = g_ref[:]
    for dh in range(KH):
        for dw in range(KW):
            xs = x_ref[pl.ds(dh * WP + dw, TILE), :]
            o_ref[dh * KW + dw] += jax.lax.dot_general(
                xs, gt, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)


def _conv_s1(x, w_taps, pads: _PadsT, KH, KW, plan: _Plan = None):
    """Stride-1 implicit-GEMM conv.  x: (N, H, W, C) NHWC;
    w_taps: (KH*KW, C, O); returns (N, Ho, Wo, O) in x.dtype."""
    N, H, W, C = x.shape
    O = w_taps.shape[-1]
    p = plan or _plan(N, H, W, C, O, KH, KW, pads,
                      jnp.dtype(x.dtype).itemsize)
    if p is None:
        raise ValueError("pallas_conv: no VMEM-feasible plan for shape "
                         f"{x.shape} x {w_taps.shape}")
    xf = _flatten_frames(x, pads, p, total=p.total)
    kern = functools.partial(_taps_kernel, TILE=p.TILE, WP=p.WP,
                             KH=KH, KW=KW)
    out = pl.pallas_call(
        kern,
        grid=(p.G,),
        in_specs=[
            # element-indexed: tap halos make consecutive slabs overlap
            pl.BlockSpec((p.SLAB, C), lambda g, _p=p: (g * _p.TILE, 0),
                         indexing_mode=pl.unblocked),
            pl.BlockSpec((KH * KW, C, O), lambda g: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((p.TILE, O), lambda g: (g, 0)),
        out_shape=jax.ShapeDtypeStruct((N * p.L, O), x.dtype),
        interpret=INTERPRET,
    )(xf, w_taps)
    return (out.reshape(N, p.L, O)[:, :p.F_out]
            .reshape(N, p.Ho, p.WP, O)[:, :, :p.Wo])


def _wgrad_s1(x, g, pads: _PadsT, KH, KW, plan: _Plan = None):
    """Per-tap GEMM weight gradient.  x: (N, H, W, C); g: (N, Ho, Wo, O)
    cotangent; returns (KH*KW, C, O) f32."""
    N, H, W, C = x.shape
    O = g.shape[-1]
    p = plan or _plan(N, H, W, C, O, KH, KW, pads,
                      jnp.dtype(x.dtype).itemsize)
    if p is None:
        raise ValueError("pallas_conv: no VMEM-feasible wgrad plan for "
                         f"shape {x.shape}")
    xf = _flatten_frames(x, pads, p, total=p.total)
    # the cotangent rides the SAME L-stride frame layout, zero outside
    # (Ho, Wo) — garbage input rows then multiply a zero cotangent row
    gp = jnp.pad(g, ((0, 0), (0, 0), (0, p.WP - p.Wo), (0, 0)))
    gf = gp.reshape(N, p.F_out, O)
    gf = jnp.pad(gf, ((0, 0), (0, p.L - p.F_out), (0, 0)))
    gf = gf.reshape(N * p.L, O)
    kern = functools.partial(_wgrad_kernel, TILE=p.TILE, WP=p.WP,
                             KH=KH, KW=KW)
    return pl.pallas_call(
        kern,
        grid=(p.G,),
        in_specs=[
            pl.BlockSpec((p.SLAB, C), lambda g_, _p=p: (g_ * _p.TILE, 0),
                         indexing_mode=pl.unblocked),
            pl.BlockSpec((p.TILE, O), lambda g_: (g_, 0)),
        ],
        out_specs=pl.BlockSpec((KH * KW, C, O), lambda g_: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((KH * KW, C, O), jnp.float32),
        interpret=INTERPRET,
    )(xf, gf)


# -------------------------------------------------------------- eligibility
def _platform_ok() -> bool:
    """Mosaic kernels only lower on TPU; interpret mode runs anywhere."""
    return INTERPRET or jax.default_backend() == "tpu"


def _flag_on() -> bool:
    return os.environ.get("MXNET_TPU_PALLAS_CONV", "0") == "1"


def conv3x3_same_available(N, H, W, C, O, dtype=None) -> bool:
    """ENV/size eligibility for the 3x3 / stride-1 / SAME kernel class.

    Gates, each measured (docs/perf_analysis.md round 3/6):
    - lane gate C % 128 == 0 and O % 128 == 0 — the MXU pads the
      contraction/output dims to full lane tiles; C=64 measured 10 TF.
    - VMEM plan exists (slab + taps + accumulators within budget).
    """
    if not (_flag_on() and _platform_ok()):
        return False
    if C % 128 or O % 128:
        return False
    esize = jnp.dtype(dtype).itemsize if dtype is not None else 2
    return _plan(N, H, W, C, O, 3, 3, ((1, 1), (1, 1)), esize) is not None


def conv3x3_s2_available(N, H, W, C, O, dtype=None) -> bool:
    """Eligibility for 3x3 / stride-2 / pad-1 via the space-to-depth
    rewrite: even spatial dims, 4C lanes full, VMEM plan for the
    (2x2-tap, 4C-channel) stride-1 form on the halved grid."""
    if not (_flag_on() and _platform_ok()):
        return False
    if H % 2 or W % 2 or (4 * C) % 128 or O % 128:
        return False
    esize = jnp.dtype(dtype).itemsize if dtype is not None else 2
    return _plan(N, H // 2, W // 2, 4 * C, O, 2, 2,
                 ((1, 0), (1, 0)), esize) is not None


# ---------------------------------------------------- 3x3 / s1 / SAME class
_S1_PADS: _PadsT = ((1, 1), (1, 1))


def _nhwc(x):
    return jnp.transpose(x, (0, 2, 3, 1))


def _nchw(x):
    return jnp.transpose(x, (0, 3, 1, 2))


@jax.custom_vjp
def conv3x3_same(data, weight):
    """3x3 / stride-1 / SAME / ungrouped conv, NCHW data + OIHW weight,
    all three directions on Pallas implicit-GEMM kernels."""
    O = weight.shape[0]
    taps = weight.transpose(2, 3, 1, 0).reshape(9, weight.shape[1], O)
    out = _conv_s1(_nhwc(data), taps.astype(data.dtype), _S1_PADS, 3, 3)
    return _nchw(out)


def _c3s_fwd(data, weight):
    return conv3x3_same(data, weight), (data, weight)


def _c3s_bwd(res, g):
    data, weight = res
    O, C = weight.shape[:2]
    gh = _nhwc(g)
    # dgrad = the forward kernel on the cotangent with spatially-flipped,
    # io-swapped taps (exact for stride-1 SAME)
    taps_d = (jnp.flip(weight, (2, 3)).transpose(2, 3, 0, 1)
              .reshape(9, O, C))
    dx = _conv_s1(gh, taps_d.astype(g.dtype), _S1_PADS, 3, 3)
    # wgrad = per-tap GEMM kernel, f32 accumulation across the grid
    dwf = _wgrad_s1(_nhwc(data), gh, _S1_PADS, 3, 3)
    dw = dwf.reshape(3, 3, C, O).transpose(3, 2, 0, 1)
    return _nchw(dx).astype(data.dtype), dw.astype(weight.dtype)


conv3x3_same.defvjp(_c3s_fwd, _c3s_bwd)


# ------------------------------------------------- 3x3 / s2 / pad-1 class
def _s2d_data(x):
    """(N, C, H, W) -> (N, 4C, H/2, W/2), parity-major (p, q, c) layout
    (matches ops/nn.py:_stem_s2d_conv)."""
    N, C, H, W = x.shape
    xs = x.reshape(N, C, H // 2, 2, W // 2, 2)
    return xs.transpose(0, 3, 5, 1, 2, 4).reshape(N, 4 * C, H // 2, W // 2)


def _s2d_weight(w):
    """(O, C, 3, 3) stride-2 pad-1 kernel -> (O, 4C, 2, 2) stride-1
    equivalent with per-side pads ((1, 0), (1, 0)) on the s2d input."""
    O, C = w.shape[:2]
    wp = jnp.pad(w, ((0, 0), (0, 0), (1, 0), (1, 0)))
    w4 = wp.reshape(O, C, 2, 2, 2, 2)
    return w4.transpose(0, 3, 5, 1, 2, 4).reshape(O, 4 * C, 2, 2)


_S2_PADS: _PadsT = ((1, 0), (1, 0))


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def conv3x3_s2(data, weight):
    """3x3 / stride-2 / pad-1 / ungrouped conv via the exact s2d(2)
    rewrite: Pallas stride-1 forward on (2x2 taps, 4C channels);
    backward stays on XLA's transposed-conv lowering (the dilated dgrad
    shapes have no stride-1 implicit-GEMM form)."""
    w4 = _s2d_weight(weight)
    O, C4 = w4.shape[:2]
    taps = w4.transpose(2, 3, 1, 0).reshape(4, C4, O)
    out = _conv_s1(_nhwc(_s2d_data(data)), taps.astype(data.dtype),
                   _S2_PADS, 2, 2)
    return _nchw(out)


def _lax_s2_ref(data, weight):
    dn = jax.lax.conv_dimension_numbers(
        data.shape, weight.shape, ("NCHW", "OIHW", "NCHW"))
    return jax.lax.conv_general_dilated(
        data, weight, (2, 2), [(1, 1), (1, 1)], dimension_numbers=dn)


def _c3s2_fwd(data, weight):
    return conv3x3_s2(data, weight), (data, weight)


def _c3s2_bwd(res, g):
    data, weight = res
    _, vjp = jax.vjp(_lax_s2_ref, data, weight)
    dx, dw = vjp(g.astype(data.dtype))
    return dx.astype(data.dtype), dw.astype(weight.dtype)


conv3x3_s2.defvjp(_c3s2_fwd, _c3s2_bwd)
