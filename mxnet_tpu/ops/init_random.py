"""Initialization and random-sampling operators.

Reference analog: ``src/operator/tensor/init_op.cc`` (_zeros/_ones/_full/
_arange/_eye) and ``src/operator/random/sample_op.cc`` + ``multisample``/
``shuffle``/``multinomial``.  RNG design (SURVEY.md §7.3 "RNG parity"): the
reference gives each op a ``kRandom`` resource of device RNG states; here
every random op takes an explicit threefry key threaded by the dispatch layer
from the global seed state (``mxnet_tpu.random``), preserving the
``mx.random.seed`` UX while staying functional under jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, param

_INIT_PARAMS = {
    "shape": param("shape", ()),
    "dtype": param("dtype", "float32"),
    "ctx": param(str, None),
}


def _dt(attrs, default="float32"):
    return np.dtype(attrs.get("dtype") or default)


register("_zeros", params=dict(_INIT_PARAMS), nin=0, aliases=("zeros",))(
    lambda attrs: jnp.zeros(attrs["shape"], _dt(attrs)))
register("_ones", params=dict(_INIT_PARAMS), nin=0, aliases=("ones",))(
    lambda attrs: jnp.ones(attrs["shape"], _dt(attrs)))
register("_full", params={**_INIT_PARAMS, "value": param(float, 0.0)},
         nin=0, aliases=("full",))(
    lambda attrs: jnp.full(attrs["shape"], attrs["value"], _dt(attrs)))


@register("_arange", nin=0, aliases=("arange",),
          params={**_INIT_PARAMS,
                  "start": param(float, 0.0), "stop": param(float, None),
                  "step": param(float, 1.0), "repeat": param(int, 1),
                  "infer_range": param(bool, False)})
def _arange(attrs, ):
    out = jnp.arange(attrs["start"],
                     attrs["stop"], attrs["step"], dtype=_dt(attrs))
    if attrs["repeat"] > 1:
        out = jnp.repeat(out, attrs["repeat"])
    return out


@register("_linspace", nin=0, aliases=("linspace",),
          params={**_INIT_PARAMS, "start": param(float, 0.0),
                  "stop": param(float, 1.0), "num": param(int, 50),
                  "endpoint": param(bool, True)})
def _linspace(attrs):
    return jnp.linspace(attrs["start"], attrs["stop"], attrs["num"],
                        endpoint=attrs["endpoint"], dtype=_dt(attrs))


@register("_eye", nin=0, aliases=("eye",),
          params={**_INIT_PARAMS, "N": param(int, 0), "M": param(int, 0),
                  "k": param(int, 0)})
def _eye(attrs):
    return jnp.eye(attrs["N"], attrs["M"] or None, attrs["k"], dtype=_dt(attrs))


# --------------------------------------------------------------------------
# samplers — attrs carry distribution params; key threaded by dispatch
# --------------------------------------------------------------------------
_SAMPLE_COMMON = {"shape": param("shape", ()), "dtype": param("dtype", None),
                  "ctx": param(str, None)}


def _sample_shape(attrs):
    return attrs["shape"] or ()


@register("_random_uniform", nin=0, needs_rng=True,
          aliases=("uniform", "random_uniform"),
          params={**_SAMPLE_COMMON, "low": param(float, 0.0),
                  "high": param(float, 1.0)})
def _uniform(attrs, key):
    return jax.random.uniform(key, _sample_shape(attrs),
                              _dt(attrs), attrs["low"], attrs["high"])


@register("_random_normal", nin=0, needs_rng=True,
          aliases=("normal", "random_normal"),
          params={**_SAMPLE_COMMON, "loc": param(float, 0.0),
                  "scale": param(float, 1.0)})
def _normal(attrs, key):
    return attrs["loc"] + attrs["scale"] * \
        jax.random.normal(key, _sample_shape(attrs), _dt(attrs))


@register("_random_gamma", nin=0, needs_rng=True, aliases=("random_gamma",),
          params={**_SAMPLE_COMMON, "alpha": param(float, 1.0),
                  "beta": param(float, 1.0)})
def _gamma(attrs, key):
    return attrs["beta"] * jax.random.gamma(
        key, attrs["alpha"], _sample_shape(attrs), _dt(attrs))


@register("_random_exponential", nin=0, needs_rng=True,
          aliases=("random_exponential",),
          params={**_SAMPLE_COMMON, "lam": param(float, 1.0)})
def _exponential(attrs, key):
    return jax.random.exponential(key, _sample_shape(attrs), _dt(attrs)) \
        / attrs["lam"]


@register("_random_poisson", nin=0, needs_rng=True, aliases=("random_poisson",),
          params={**_SAMPLE_COMMON, "lam": param(float, 1.0)})
def _poisson(attrs, key):
    return jax.random.poisson(key, attrs["lam"], _sample_shape(attrs)) \
        .astype(_dt(attrs))


@register("_random_negative_binomial", nin=0, needs_rng=True,
          aliases=("random_negative_binomial",),
          params={**_SAMPLE_COMMON, "k": param(int, 1), "p": param(float, 1.0)})
def _neg_binomial(attrs, key):
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, attrs["k"], _sample_shape(attrs)) \
        * (1 - attrs["p"]) / attrs["p"]
    return jax.random.poisson(k2, lam).astype(_dt(attrs))


@register("_random_generalized_negative_binomial", nin=0, needs_rng=True,
          aliases=("random_generalized_negative_binomial",),
          params={**_SAMPLE_COMMON, "mu": param(float, 1.0),
                  "alpha": param(float, 1.0)})
def _gen_neg_binomial(attrs, key):
    k1, k2 = jax.random.split(key)
    a = 1.0 / max(attrs["alpha"], 1e-12)
    lam = jax.random.gamma(k1, a, _sample_shape(attrs)) * attrs["mu"] / a
    return jax.random.poisson(k2, lam).astype(_dt(attrs))


@register("_random_randint", nin=0, needs_rng=True, aliases=("random_randint",),
          params={**_SAMPLE_COMMON, "low": param(int, 0),
                  "high": param(int, 1)})
def _randint(attrs, key):
    return jax.random.randint(key, _sample_shape(attrs), attrs["low"],
                              attrs["high"],
                              dtype=_dt(attrs, "int32"))


@register("_sample_multinomial", nin=1, needs_rng=True,
          aliases=("sample_multinomial",), nout=1,
          params={"shape": param("shape", ()), "get_prob": param(bool, False),
                  "dtype": param("dtype", "int32")})
def _multinomial(attrs, key, data):
    n = int(np.prod(attrs["shape"])) if attrs["shape"] else 1
    logits = jnp.log(jnp.maximum(data, 1e-37))
    if data.ndim == 1:
        out = jax.random.categorical(key, logits, shape=(n,))
        out = out.reshape(attrs["shape"]) if attrs["shape"] else out.reshape(())
    else:
        out = jax.random.categorical(key, logits[:, None, :].repeat(n, 1),
                                     axis=-1)
        out = out.reshape((data.shape[0],) + (attrs["shape"] or ()))
    return out.astype(_dt(attrs, "int32"))


@register("_shuffle", nin=1, needs_rng=True, aliases=("shuffle",))
def _shuffle(attrs, key, data):
    return jax.random.permutation(key, data, axis=0)


# sample_* variants: per-element distribution params as input arrays
@register("_sample_uniform", nin=2, needs_rng=True, aliases=("sample_uniform",),
          params={"shape": param("shape", ()), "dtype": param("dtype", None)})
def _sample_uniform(attrs, key, low, high):
    sh = low.shape + (attrs["shape"] or ())
    u = jax.random.uniform(key, sh, _dt(attrs))
    extra = (1,) * (len(sh) - low.ndim)
    return low.reshape(low.shape + extra) + \
        (high - low).reshape(low.shape + extra) * u


@register("_sample_normal", nin=2, needs_rng=True, aliases=("sample_normal",),
          params={"shape": param("shape", ()), "dtype": param("dtype", None)})
def _sample_normal(attrs, key, mu, sigma):
    sh = mu.shape + (attrs["shape"] or ())
    extra = (1,) * (len(sh) - mu.ndim)
    return mu.reshape(mu.shape + extra) + \
        sigma.reshape(sigma.shape + extra) * \
        jax.random.normal(key, sh, _dt(attrs))


@register("_sample_gamma", nin=2, needs_rng=True, aliases=("sample_gamma",),
          params={"shape": param("shape", ()), "dtype": param("dtype", None)})
def _sample_gamma(attrs, key, alpha, beta):
    sh = alpha.shape + (attrs["shape"] or ())
    extra = (1,) * (len(sh) - alpha.ndim)
    return jax.random.gamma(key, alpha.reshape(alpha.shape + extra), sh) \
        * beta.reshape(beta.shape + extra)
