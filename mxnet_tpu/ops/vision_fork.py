"""The yangyu12-fork custom vision ops, TPU-native.

Reference analogs (the fork's additions on top of upstream MXNet 1.2,
SURVEY.md "Version/identity"):

- ``AttentionConvolution`` — src/operator/nn/attention_convolution.cc:368,
  attention_convolution-inl.h:178-284: convolution where the im2col patch
  matrix is elementwise-masked by a per-position attention input before the
  weight GEMM: ``out = W @ (im2col(data) * attention)``.
- ``DynamicConvolution`` — src/operator/nn/dynamic_convolution.cc:293,
  dynamic_convolution.cu:172-212 (``dynconv_inprod_gpu_kernel``): convolution
  whose filter is *predicted per output position*: an "across" weight mixes
  input channels at the centre tap, a "within" weight applies a per-position
  spatial kernel summed over channels.
- ``RadiateSample`` — src/operator/nn/radiate_sample.cc:117,
  radiate_sample.cu:14-64 (``RadSamForwardKernel``): channel groups sample
  rings of increasing radius; group ``g`` averages the ``8g`` pixels on the
  perimeter of a ``(2g+1)²`` square (group 0 takes the centre pixel).

TPU-native design: all three are expressed as XLA-fusable tensor programs —
``conv_general_dilated_patches`` (im2col on the MXU) + einsum for the two
dynamic convs, and a *fixed-weight depthwise convolution* for RadiateSample
(the ring average is a constant stencil, so XLA lowers it straight to the
MXU instead of the reference's scalar gather loop).  Backward passes come
from ``jax.vjp`` of these definitions; the reference's hand-written backward
GEMMs (attention_convolution-inl.h:286-428) are exactly the VJPs of the
forward math, so gradients match by construction.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from .registry import register, param
from .nn import _CONV_PARAMS


def _patches(data, kernel, stride, pad, dilate):
    """im2col: (N, C, H, W) -> (N, C*prod(k), H', W'), feature dim ordered
    channel-major (c, kh, kw) — same layout as the reference's caffe-style
    im2col buffer (attention_convolution-inl.h:218-222)."""
    return jax.lax.conv_general_dilated_patches(
        data, filter_shape=tuple(kernel), window_strides=tuple(stride),
        padding=[(p, p) for p in pad], rhs_dilation=tuple(dilate),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


@register("AttentionConvolution", nin=-1,
          params=dict(_CONV_PARAMS))
def _attention_convolution(attrs, data, attention, weight, *maybe_bias):
    """out = weight @ (im2col(data) * attention), per group.

    attention has one mask value per (input-patch element, output position):
    shape (N, Cin*prod(kernel), H'*W') — any shape with that many elements is
    accepted, mirroring the reference's ``get_with_shape`` reshape
    (attention_convolution-inl.h:196).
    """
    k = attrs["kernel"]
    nd = len(k)
    if nd != 2:
        raise MXNetError("AttentionConvolution: only 2D kernels supported "
                         "(reference GPU path is 2D-only)")
    stride = attrs["stride"] or (1,) * nd
    dilate = attrs["dilate"] or (1,) * nd
    pad = attrs["pad"] or (0,) * nd
    g = attrs["num_group"]
    nf = attrs["num_filter"]

    n, c = data.shape[0], data.shape[1]
    cols = _patches(data, k, stride, pad, dilate)      # (N, C*kk, H', W')
    ho, wo = cols.shape[2], cols.shape[3]
    kdim = (c // g) * int(np.prod(k))                  # K = Cin/g * k*k
    cols = cols.reshape(n, g, kdim, ho * wo)
    att = attention.reshape(n, g, kdim, ho * wo)
    w3 = weight.reshape(g, nf // g, kdim)              # (g, M, K)
    # masked patches then one big GEMM per group — rides the MXU
    out = jnp.einsum("gmk,ngkp->ngmp", w3, cols * att,
                     preferred_element_type=jnp.float32).astype(data.dtype)
    out = out.reshape(n, nf, ho, wo)
    if not attrs["no_bias"] and maybe_bias:
        out = out + maybe_bias[0].reshape(1, -1, 1, 1)
    return out


@register("DynamicConvolution", nin=3,
          params={**_CONV_PARAMS,
                  "sample": param("shape", ()),
                  "s_stride": param("shape", ())})
def _dynamic_convolution(attrs, data, across_weight, within_weight):
    """Position-dependent dynamic filtering (dynamic_convolution.cu:172-212):

    out[n,o,p] = sum_c across[n,o,c,p] * centre_patch[n,c,p]
               + sum_k within[n,o,k,p] * (sum_c patches[n,c,k,p])

    across_weight: (N, num_filter*Cin, H', W'); within_weight:
    (N, num_filter*prod(kernel), H', W').  The reference supports only
    stride 1 / num_group 1 (dynamic_convolution-inl.h:36-37 "NOT SUPPORT");
    its ``sample`` extension writes an output layout inconsistent with the
    op's declared shape, so only the default sample=(1,1) is provided.
    """
    k = attrs["kernel"]
    nd = len(k)
    if nd != 2:
        raise MXNetError("DynamicConvolution: only 2D kernels supported")
    if attrs["num_group"] != 1:
        raise MXNetError("DynamicConvolution: num_group != 1 unsupported "
                         "(matches reference dynamic_convolution-inl.h:37)")
    stride = attrs["stride"] or (1,) * nd
    if tuple(stride) != (1,) * nd:
        raise MXNetError("DynamicConvolution: stride != 1 unsupported "
                         "(matches reference dynamic_convolution-inl.h:36)")
    sample = attrs["sample"] or ()
    if any(int(s) != 1 for s in sample):
        raise MXNetError("DynamicConvolution: sample != 1 unsupported")
    dilate = attrs["dilate"] or (1,) * nd
    pad = attrs["pad"] or (0,) * nd
    nf = attrs["num_filter"]

    n, c = data.shape[0], data.shape[1]
    kk = int(np.prod(k))
    cols = _patches(data, k, stride, pad, dilate)      # (N, C*kk, H', W')
    ho, wo = cols.shape[2], cols.shape[3]
    cols = cols.reshape(n, c, kk, ho * wo)
    centre = (k[0] - 1) // 2 * k[1] + (k[1] - 1) // 2  # centre tap index
    aw = across_weight.reshape(n, nf, c, ho * wo)
    ww = within_weight.reshape(n, nf, kk, ho * wo)
    out = (jnp.einsum("nocp,ncp->nop", aw, cols[:, :, centre, :],
                      preferred_element_type=jnp.float32)
           + jnp.einsum("nokp,nkp->nop", ww, cols.sum(axis=1),
                        preferred_element_type=jnp.float32))
    return out.astype(data.dtype).reshape(n, nf, ho, wo)


def _ring_kernel(num_group, group_size, dtype):
    """Constant depthwise stencil: channel block g gets the radius-g ring
    average (1/(8g) on the perimeter of the centred (2g+1)² square; g=0 is
    the identity tap).  Shape (num_group*group_size, 1, S, S), S=2G-1."""
    radius = num_group - 1
    size = 2 * radius + 1
    w = np.zeros((num_group * group_size, 1, size, size), dtype=dtype)
    for g in range(num_group):
        if g == 0:
            w[0:group_size, 0, radius, radius] = 1.0
        else:
            ring = np.zeros((size, size), dtype=dtype)
            lo, hi = radius - g, radius + g
            ring[lo, lo:hi + 1] = 1.0
            ring[hi, lo:hi + 1] = 1.0
            ring[lo:hi + 1, lo] = 1.0
            ring[lo:hi + 1, hi] = 1.0
            w[g * group_size:(g + 1) * group_size, 0] = ring / (8.0 * g)
    return jnp.asarray(w)


@register("RadiateSample", nin=1,
          params={"pad": param("shape", (0, 0)),
                  "num_group": param(int, 1)})
def _radiate_sample(attrs, data):
    """Ring-average sampling (radiate_sample.cu:14-64) as a fixed depthwise
    conv: out spatial = in + 2*pad - 2*(num_group-1); channels not divisible
    by num_group are dropped (radiate_sample.cc:45-49)."""
    num_group = attrs["num_group"]
    pad = attrs["pad"] or (0, 0)
    n, c, h, w = data.shape
    keep = c - c % num_group
    group_size = c // num_group
    data = data[:, :keep]
    kern = _ring_kernel(num_group, group_size, np.float32).astype(data.dtype)
    out = jax.lax.conv_general_dilated(
        data, kern,
        window_strides=(1, 1),
        padding=[(int(pad[0]), int(pad[0])), (int(pad[1]), int(pad[1]))],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=keep)
    return out.astype(data.dtype)


def _attconv_hint(attrs, shapes):
    data = shapes[0]
    if data is None:
        return shapes
    k = attrs["kernel"]
    nf, g = attrs["num_filter"], attrs["num_group"]
    stride = attrs["stride"] or (1,) * len(k)
    dilate = attrs["dilate"] or (1,) * len(k)
    pad = attrs["pad"] or (0,) * len(k)
    sp = [(data[2 + i] + 2 * pad[i] - (dilate[i] * (k[i] - 1) + 1))
          // stride[i] + 1 for i in range(len(k))]
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (data[0], data[1] * int(np.prod(k)), sp[0], sp[1])
    if len(out) > 2 and out[2] is None:
        out[2] = (nf, data[1] // g) + tuple(k)
    if len(out) > 3 and out[3] is None and not attrs["no_bias"]:
        out[3] = (nf,)
    return out


def _dynconv_hint(attrs, shapes):
    data = shapes[0]
    if data is None:
        return shapes
    k = attrs["kernel"]
    nf = attrs["num_filter"]
    dilate = attrs["dilate"] or (1,) * len(k)
    pad = attrs["pad"] or (0,) * len(k)
    sp = [data[2 + i] + 2 * pad[i] - (dilate[i] * (k[i] - 1) + 1) + 1
          for i in range(len(k))]
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (data[0], nf * data[1], sp[0], sp[1])
    if len(out) > 2 and out[2] is None:
        out[2] = (data[0], nf * int(np.prod(k)), sp[0], sp[1])
    return out


def install_hints():
    from .registry import OPS
    cfg = {
        "AttentionConvolution": (("data", "attention", "weight", "bias"),
                                 _attconv_hint),
        "DynamicConvolution": (("data", "across_weight", "within_weight"),
                               _dynconv_hint),
        "RadiateSample": (("data",), None),
    }
    for name, (arg_names, hint) in cfg.items():
        op = OPS[name]
        op.arg_names = list(arg_names)
        if hint is not None:
            op.shape_hint = hint


install_hints()
