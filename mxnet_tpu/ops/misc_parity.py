"""Remaining op-registry parity: histogram, ravel, slice-assign, scatter,
sampling tails, square_sum, sparse adagrad, KL sparse-reg, aliases.

Reference analogs: src/operator/tensor/histogram.cc (_histogram),
ravel.cc (_ravel_multi_index/_unravel_index), matrix_op.cc
(_slice_assign/_slice_assign_scalar, the ``x[a:b] = y`` lowering),
indexing_op.cc (_scatter_set_nd), elemwise_binary_op_basic.cc (_grad_add),
elemwise ops' sparse "scatter" variants (_scatter_plus_scalar etc. — on the
dense TPU representation these coincide with the dense ops),
square_sum.cc (_square_sum), optimizer_op.cc (_sparse_adagrad_update),
identity_attach_KL_sparse_reg.cc (IdentityAttachKLSparseReg),
multisample_op.cc (_sample_exponential/_sample_poisson/
_sample_negative_binomial/_sample_generalized_negative_binomial).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from .registry import register, param, OPS


@register("_grad_add", nin=2)
def _grad_add(attrs, lhs, rhs):
    """Gradient accumulation add (elemwise_binary_op_basic.cc)."""
    return lhs + rhs


@register("_identity_with_attr_like_rhs", nin=2)
def _identity_with_attr_like_rhs(attrs, lhs, rhs):
    """Identity on lhs, attrs (storage/shape) taken from rhs — graph-pass
    helper (elemwise_op_common.h)."""
    return lhs


@register("_histogram", nin=-1, nout=2,
          params={"bin_cnt": param(int, None),
                  "range": param("floats", None)})
def _histogram(attrs, data, *maybe_bins):
    """Histogram (histogram.cc): either uniform bins from
    (bin_cnt, range) or explicit bin-edge input."""
    flat = data.reshape(-1)
    if attrs["bin_cnt"] is not None:
        if attrs["range"] is None:
            raise MXNetError("_histogram: bin_cnt requires range=(lo, hi)")
        lo, hi = attrs["range"]
        cnt = attrs["bin_cnt"]
        edges = jnp.linspace(lo, hi, cnt + 1)
    elif maybe_bins:
        edges = maybe_bins[0]
        cnt = edges.shape[0] - 1
        lo, hi = edges[0], edges[-1]
    else:
        raise MXNetError("_histogram needs bin_cnt+range or a bins input")
    idx = jnp.clip(jnp.searchsorted(edges, flat, side="right") - 1, 0,
                   cnt - 1)
    inb = (flat >= edges[0]) & (flat <= edges[-1])
    counts = jnp.zeros((cnt,), jnp.int32).at[idx].add(
        inb.astype(jnp.int32))
    return counts, edges.astype(data.dtype)


@register("_ravel_multi_index", nin=1, aliases=("ravel_multi_index",),
          params={"shape": param("shape", None, required=True)})
def _ravel_multi_index(attrs, data):
    """(N, K) coordinate rows -> flat indices (ravel.cc)."""
    shape = attrs["shape"]
    strides = np.cumprod([1] + list(shape[::-1][:-1]))[::-1]
    return jnp.sum(data * jnp.asarray(strides.copy(), data.dtype)[:, None],
                   axis=0)


@register("_unravel_index", nin=1, aliases=("unravel_index",),
          params={"shape": param("shape", None, required=True)})
def _unravel_index(attrs, data):
    """Flat indices -> (K, N) coordinates (ravel.cc)."""
    shape = attrs["shape"]
    idx = data.astype(jnp.int32)
    coords = []
    for dim in reversed(shape):
        coords.append(idx % dim)
        idx = idx // dim
    return jnp.stack(coords[::-1], axis=0).astype(data.dtype)


def _slice_tuple(attrs, ndim):
    begin = attrs["begin"]
    end = attrs["end"]
    step = attrs.get("step") or ()
    out = []
    for i in range(len(begin)):
        st = step[i] if i < len(step) and step[i] else 1
        out.append(slice(begin[i], None if end[i] is None else end[i], st))
    return tuple(out)


@register("_slice_assign", nin=2,
          params={"begin": param("shape", None, required=True),
                  "end": param("shape", None, required=True),
                  "step": param("shape", ())})
def _slice_assign(attrs, lhs, rhs):
    """out = lhs with lhs[begin:end:step] = rhs (matrix_op.cc
    _slice_assign — the functional form of ``x[a:b] = y``)."""
    return lhs.at[_slice_tuple(attrs, lhs.ndim)].set(rhs)


@register("_slice_assign_scalar", nin=1,
          params={"scalar": param(float, 0.0),
                  "begin": param("shape", None, required=True),
                  "end": param("shape", None, required=True),
                  "step": param("shape", ())})
def _slice_assign_scalar(attrs, lhs):
    return lhs.at[_slice_tuple(attrs, lhs.ndim)].set(
        jnp.asarray(attrs["scalar"], lhs.dtype))


@register("_scatter_set_nd", nin=3,
          params={"shape": param("shape", None, required=True)})
def _scatter_set_nd(attrs, lhs, rhs, indices):
    """The ``x[idx] = y`` lowering (indexing_op.cc:680 _scatter_set_nd,
    3 inputs): set rhs into LHS at indices, leaving non-indexed elements
    of lhs untouched."""
    idx = tuple(indices[i].astype(jnp.int32)
                for i in range(indices.shape[0]))
    return lhs.at[idx].set(rhs)


@register("_square_sum", nin=1, aliases=("square_sum",),
          params={"axis": param("shape", None),
                  "keepdims": param(bool, False),
                  "exclude": param(bool, False)})
def _square_sum(attrs, data):
    """sum(data²) over axis (square_sum.cc — the row_sparse-optimized
    reduction; dense XLA form here).  Axis semantics shared with the
    reduce family (including ``exclude``)."""
    from .reduce import _resolve_axes
    axes = _resolve_axes(attrs, data.ndim)
    return jnp.sum(data * data, axis=axes, keepdims=attrs["keepdims"])


@register("_sparse_adagrad_update", nin=3, nout=2, visible=1,
          aux_writeback={1: 2},
          params={"lr": param(float, None, required=True),
                  "epsilon": param(float, 1e-7),
                  "wd": param(float, 0.0),
                  "rescale_grad": param(float, 1.0),
                  "clip_gradient": param(float, -1.0)})
def _sparse_adagrad_update(attrs, weight, grad, history):
    """AdaGrad update (optimizer_op.cc _sparse_adagrad_update): on TPU the
    row-sparse update is a dense masked update (rows with zero grad are
    untouched by construction)."""
    if attrs["wd"] != 0.0:
        # reference optimizer_op-inl.h:1751: CHECK(wd == 0) — decay would
        # also touch zero-gradient rows, breaking the sparse invariant
        raise MXNetError("sparse adagrad_update does not support wd")
    g = grad * attrs["rescale_grad"]
    if attrs["clip_gradient"] >= 0:   # >= 0, the *_update op convention
        g = jnp.clip(g, -attrs["clip_gradient"], attrs["clip_gradient"])
    new_hist = history + g * g
    upd = attrs["lr"] * g / (jnp.sqrt(new_hist) + attrs["epsilon"])
    return weight - upd, new_hist


@register("IdentityAttachKLSparseReg", nin=-1, nout=2, visible=1,
          aux_writeback={1: 1},
          params={"sparseness_target": param(float, 0.1),
                  "penalty": param(float, 0.001),
                  "momentum": param(float, 0.9)})
def _identity_attach_kl_sparse_reg(attrs, data, *maybe_avg):
    """Identity forward with a KL-sparseness gradient penalty
    (identity_attach_KL_sparse_reg.cc): moving average of the mean
    activation rho_hat; backward adds penalty * (-target/rho_hat +
    (1-target)/(1-rho_hat))."""
    rho = attrs["sparseness_target"]
    penalty = attrs["penalty"]
    mom = attrs["momentum"]
    nunit = data.shape[1] if data.ndim > 1 else data.shape[0]
    if maybe_avg:
        avg = maybe_avg[0].reshape(-1)
    else:
        avg = jnp.full((nunit,), rho, data.dtype)
    # per-HIDDEN-UNIT mean activation (reference sums all dims except 1)
    unit_axes = tuple(a for a in range(data.ndim) if a != 1) \
        if data.ndim > 1 else ()
    rho_hat = jnp.clip(jnp.mean(data, axis=unit_axes), 1e-6, 1 - 1e-6)
    new_avg = mom * avg + (1 - mom) * rho_hat

    bshape = [1] * data.ndim
    if data.ndim > 1:
        bshape[1] = -1
    else:
        bshape[0] = -1

    @jax.custom_vjp
    def _fwd(d, a):
        return d

    def _fwd_fwd(d, a):
        # gradient uses the UPDATED per-unit moving average (reference
        # identity_attach_KL_sparse_reg-inl.h backward); recomputed inside
        # the vjp so no outer tracer is captured
        rh = jnp.clip(jnp.mean(d, axis=unit_axes), 1e-6, 1 - 1e-6)
        na = mom * a + (1 - mom) * rh
        return d, jnp.clip(na, 1e-6, 1 - 1e-6)

    def _fwd_bwd(rh, g):
        grad_reg = penalty * (-rho / rh + (1 - rho) / (1 - rh))
        return g + grad_reg.reshape(bshape), jnp.zeros_like(rh)

    _fwd.defvjp(_fwd_fwd, _fwd_bwd)
    return _fwd(data, avg), new_avg


@register("cast_storage", nin=1, aliases=("_cast_storage",),
          params={"stype": param(["default", "row_sparse", "csr"],
                                 "default")})
def _cast_storage_op(attrs, data):
    """Storage-type cast (cast_storage.cc).  Dense XLA arrays are the
    device representation for every stype (SURVEY.md §7.3 sparse note);
    the sparse *container* conversion happens at the NDArray layer
    (ndarray.sparse.cast_storage) — as a graph op this is identity."""
    return data


def _samplers():
    """Per-row sampling tails (multisample_op.cc): each row of the param
    tensor(s) draws ``shape`` samples.  Shares the shape-broadcast + dtype
    idiom of the init_random sample_* family."""
    from jax import random as jrand
    from .init_random import _dt

    def _bcast(arr, shape):
        out_shape = tuple(arr.shape) + tuple(shape)
        return jnp.broadcast_to(
            arr.reshape(arr.shape + (1,) * len(tuple(shape))),
            out_shape), out_shape

    def sample_exponential(attrs, key, lam):
        shape = attrs["shape"] or ()
        lam_b, out_shape = _bcast(lam, shape)
        u = jrand.uniform(key, out_shape, minval=1e-7, maxval=1.0)
        return (-jnp.log(u) / lam_b).astype(_dt(attrs))

    def sample_poisson(attrs, key, lam):
        shape = attrs["shape"] or ()
        lam_b, out_shape = _bcast(lam, shape)
        return jrand.poisson(key, lam_b, out_shape).astype(_dt(attrs))

    def sample_negative_binomial(attrs, key, k, p):
        shape = attrs["shape"] or ()
        kk, kg = jrand.split(key)
        kb, out_shape = _bcast(k, shape)
        pb, _ = _bcast(p, shape)
        # NB(k, p) = Poisson(Gamma(k, (1-p)/p))
        lam = jrand.gamma(kg, kb, out_shape) * (1 - pb) / pb
        return jrand.poisson(kk, lam, out_shape).astype(_dt(attrs))

    def sample_generalized_negative_binomial(attrs, key, mu, alpha):
        shape = attrs["shape"] or ()
        kk, kg = jrand.split(key)
        mub, out_shape = _bcast(mu, shape)
        ab, _ = _bcast(alpha, shape)
        # GNB(mu, alpha) = Poisson(Gamma(1/alpha, mu*alpha))
        r = 1.0 / jnp.maximum(ab, 1e-8)
        lam = jrand.gamma(kg, r, out_shape) * mub * ab
        return jrand.poisson(kk, lam, out_shape).astype(_dt(attrs))

    shape_p = {"shape": param("shape", ()),
               "dtype": param("dtype", None)}
    register("_sample_exponential", nin=1, needs_rng=True,
             aliases=("sample_exponential",),
             params=dict(shape_p))(sample_exponential)
    register("_sample_poisson", nin=1, needs_rng=True,
             aliases=("sample_poisson",),
             params=dict(shape_p))(sample_poisson)
    register("_sample_negative_binomial", nin=2, needs_rng=True,
             aliases=("sample_negative_binomial",),
             params=dict(shape_p))(sample_negative_binomial)
    register("_sample_generalized_negative_binomial", nin=2, needs_rng=True,
             aliases=("sample_generalized_negative_binomial",),
             params=dict(shape_p))(sample_generalized_negative_binomial)


_samplers()

# ---------------------------------------------------------------------------
# pure aliases for reference registration names
# ---------------------------------------------------------------------------
_ALIASES = {
    "MakeLoss": "make_loss",
    "Reorg": "reorg",
    "NewReorg": "newreorg",
    "_scatter_plus_scalar": "_plus_scalar",
    "_scatter_minus_scalar": "_minus_scalar",
    "_scatter_elemwise_div": "elemwise_div",
    "_sparse_retain": None,  # handled at the NDArray layer (sparse.retain)
}
for alias, target in _ALIASES.items():
    if target is not None and alias not in OPS:
        OPS[alias] = OPS[target]
