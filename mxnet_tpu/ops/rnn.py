"""Fused multi-layer RNN/LSTM/GRU operator.

Reference analog: ``src/operator/rnn-inl.h:149`` (RNNParam), ``rnn_impl.h``
(CPU impl), ``cudnn_rnn-inl.h`` (fused cuDNN path).  Same packed-parameter
convention: ONE flat vector holding, per layer & direction, [i2h_W, h2h_W]
for all layers, then [i2h_bias, h2h_bias] for all layers.

TPU-native design: per layer the input projection ``x @ W_i2h^T + b`` is ONE
large MXU matmul over the whole (T*B, in) sequence, hoisted OUT of the time
loop; only the inherently sequential hidden-to-hidden recurrence runs in a
``lax.scan`` (compiled once, no per-step dispatch).  Bidirectional runs a
second scan over the reversed sequence.  Gate orders match the reference:
LSTM [i, f, g, o], GRU [r, z, n] (cuDNN variant).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register, param

_NGATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, state_size, input_size, bidirectional, mode):
    """Total packed parameter count (reference: rnn-inl.h GetParamSize)."""
    ng = _NGATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        size += dirs * ng * state_size * (in_sz + state_size)
    size += num_layers * dirs * 2 * ng * state_size
    return size


def _unpack(params, num_layers, h, input_size, dirs, ng):
    """Split the flat vector into per-(layer,dir) W/R/bW/bR."""
    out = []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else h * dirs
        for d in range(dirs):
            W = params[off:off + ng * h * in_sz].reshape(ng * h, in_sz)
            off += ng * h * in_sz
            R = params[off:off + ng * h * h].reshape(ng * h, h)
            off += ng * h * h
            out.append([W, R, None, None])
    for layer in range(num_layers):
        for d in range(dirs):
            i = layer * dirs + d
            out[i][2] = params[off:off + ng * h]
            off += ng * h
            out[i][3] = params[off:off + ng * h]
            off += ng * h
    return out


def _cell_scan(mode, xproj, h0, c0, R, bR):
    """Scan the recurrence over time.  xproj: (T, B, ng*h)."""
    h_sz = h0.shape[-1]

    if mode == "lstm":
        from . import pallas_rnn

        def _lstm_scan_xla(xp, h, c):
            def step(carry, row):
                hh, cc = carry
                gates = row + hh @ R.T + bR
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                c_new = jax.nn.sigmoid(f) * cc \
                    + jax.nn.sigmoid(i) * jnp.tanh(g)
                h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
                return (h_new, c_new), h_new

            (hT, cT), ys = jax.lax.scan(step, (h, c), xp)
            return ys, hT, cT

        if pallas_rnn.lstm_scan_available(xproj.shape[1], h_sz,
                                          xproj.dtype) \
                and h0.dtype == xproj.dtype and c0.dtype == xproj.dtype:
            # mixed-dtype states (e.g. f64 zeros against f32 activations
            # under x64) take the promoting scan; the kernel is monodtype
            if pallas_rnn.INTERPRET:   # test hook: force the interpreter
                return pallas_rnn.lstm_scan(xproj, h0, c0, R, bR)
            # fused Pallas recurrence (cuDNN-RNN role): whole time loop in
            # one kernel, h/c resident in VMEM, custom VJP.  The platform
            # branch is resolved at LOWERING time, so CPU-committed arrays
            # on a TPU host compile the scan, never Mosaic (advisor r03).
            # The axon PJRT plugin registers platform name "tpu" (verified:
            # the compiled LM step carries the Mosaic custom-call through
            # the tunnel), so the tpu= key covers it.
            from ..parallel._compat import platform_dependent
            return platform_dependent(
                xproj, h0, c0,
                tpu=lambda xp, h, c: pallas_rnn.lstm_scan(xp, h, c, R, bR),
                default=_lstm_scan_xla)
        return _lstm_scan_xla(xproj, h0, c0)

    if mode == "gru":
        Rr, Rz, Rn = jnp.split(R, 3, axis=0)
        bRr, bRz, bRn = jnp.split(bR, 3)

        def step(h, xp):
            xr, xz, xn = jnp.split(xp, 3, axis=-1)
            r = jax.nn.sigmoid(xr + h @ Rr.T + bRr)
            z = jax.nn.sigmoid(xz + h @ Rz.T + bRz)
            n = jnp.tanh(xn + r * (h @ Rn.T + bRn))
            h_new = (1 - z) * n + z * h
            return h_new, h_new

        hT, ys = jax.lax.scan(step, h0, xproj)
        return ys, hT, None

    act = jnp.tanh if mode == "rnn_tanh" else jax.nn.relu

    def step(h, xp):
        h_new = act(xp + h @ R.T + bR)
        return h_new, h_new

    hT, ys = jax.lax.scan(step, h0, xproj)
    return ys, hT, None


@register("RNN", nin=-1, aliases=("rnn",), nout=3, needs_rng=True,
          train_aware=True,
          env_keys=("MXNET_TPU_PALLAS_RNN",),
          visible=lambda a: (3 if a["mode"] == "lstm" else 2)
          if a["state_outputs"] else 1,
          params={"state_size": param(int, required=True),
                  "num_layers": param(int, required=True),
                  "bidirectional": param(bool, False),
                  "mode": param(["rnn_relu", "rnn_tanh", "lstm", "gru"],
                                required=True),
                  "p": param(float, 0.0),
                  "state_outputs": param(bool, False),
                  "lstm_state_clip_min": param(float, None),
                  "lstm_state_clip_max": param(float, None),
                  "lstm_state_clip_nan": param(bool, False),
                  "__train__": param(bool, False)})
def _rnn(attrs, key, data, params, state, *maybe_cell):
    """Fused RNN forward.  data: (T, B, F) [TNC]; state: (L*dirs, B, h)."""
    mode = attrs["mode"]
    h = attrs["state_size"]
    L = attrs["num_layers"]
    dirs = 2 if attrs["bidirectional"] else 1
    ng = _NGATES[mode]
    T, B, F = data.shape
    wr = _unpack(params, L, h, F, dirs, ng)
    cell = maybe_cell[0] if maybe_cell else None

    x = data
    hTs, cTs = [], []
    dropout = attrs["p"] if attrs.get("__train__") else 0.0
    for layer in range(L):
        outs = []
        for d in range(dirs):
            i = layer * dirs + d
            W, R, bW, bR = wr[i]
            xin = x if d == 0 else jnp.flip(x, axis=0)
            xproj = xin @ W.T + bW          # one MXU pass for all timesteps
            h0 = state[i]
            c0 = cell[i] if cell is not None else None
            ys, hT, cT = _cell_scan(mode, xproj, h0, c0, R, bR)
            if d == 1:
                ys = jnp.flip(ys, axis=0)
            outs.append(ys)
            hTs.append(hT)
            if cT is not None:
                if attrs["lstm_state_clip_min"] is not None and \
                        attrs["lstm_state_clip_max"] is not None:
                    cT = jnp.clip(cT, attrs["lstm_state_clip_min"],
                                  attrs["lstm_state_clip_max"])
                cTs.append(cT)
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if dropout > 0 and layer < L - 1:
            sub = jax.random.fold_in(key, layer)
            keep = jax.random.bernoulli(sub, 1 - dropout, x.shape)
            x = jnp.where(keep, x / (1 - dropout), 0)
    out_h = jnp.stack(hTs)
    out_c = jnp.stack(cTs) if cTs else jnp.zeros_like(out_h)
    return x, out_h, out_c
