"""Global RNG state preserving the ``mx.random.seed`` UX over threefry keys.

Reference analog: per-device RNG resources (``src/common/random_generator.h:
45-97``, ``src/resource.cc``) seeded by ``mx.random.seed``.  TPU-native: one
global threefry key; every random op call splits a fresh subkey (functional,
reproducible, parallel-safe — SURVEY.md §7.3 "RNG parity").
"""
from __future__ import annotations

import threading

import jax

__all__ = ["seed", "next_key", "current_key"]

_lock = threading.Lock()
_key = None


def seed(seed_state: int, ctx=None):
    """Seed the global generator (parity: mxnet.random.seed)."""
    global _key
    with _lock:
        _key = jax.random.PRNGKey(int(seed_state) & 0x7FFFFFFF)


def next_key():
    """Split and return a fresh subkey for one random-op call."""
    global _key
    with _lock:
        if _key is None:
            _key = jax.random.PRNGKey(0)
        _key, sub = jax.random.split(_key)
        return sub


def current_key():
    global _key
    with _lock:
        if _key is None:
            _key = jax.random.PRNGKey(0)
        return _key


# re-exported sampling functions are generated into mxnet_tpu.ndarray.random
