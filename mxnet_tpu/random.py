"""``mx.random.seed`` UX over the per-context resource RNG streams.

Reference analog: per-device RNG resources (``src/common/random_generator.h:
45-97``, ``src/resource.cc``) seeded by ``mx.random.seed``.  TPU-native:
the :class:`mxnet_tpu.resource.ResourceManager` owns one threefry key
stream per context; every random op call draws a fresh subkey from the
current context's ``kRandom`` resource (functional, reproducible,
parallel-safe — SURVEY.md §7.3 "RNG parity").
"""
from __future__ import annotations

import threading

__all__ = ["seed", "next_key", "current_key"]

# kRandom resources are long-lived handles on the per-context stream, so we
# cache one per context and pay a single lock on the hot path (op dispatch
# draws a key per random op — executor/fused/cached_op/ndarray sites).
_res_lock = threading.Lock()
_res_cache = {}


def _manager():
    from . import resource as _resource
    return _resource.ResourceManager.get()


def seed(seed_state: int, ctx=None):
    """Seed RNG generators (parity: mxnet.random.seed).

    With no ``ctx`` every context's generator is reseeded from the global
    seed (resource.cc SeedRandom); with ``ctx`` only that device's stream
    is reseeded (reference per-device seeding).
    """
    _manager().seed(int(seed_state), ctx)


def _krandom_resource():
    from . import resource as _resource
    from . import context as _context
    ctx = _context.current_context()
    key = (ctx.device_typeid, ctx.device_id)
    with _res_lock:
        res = _res_cache.get(key)
        if res is None:
            res = _manager().request(ctx, _resource.ResourceRequest(
                _resource.ResourceRequest.kRandom))
            _res_cache[key] = res
        return res


def next_key():
    """Split and return a fresh subkey for one random-op call, drawn from
    the current context's kRandom resource."""
    return _krandom_resource().get_random()


def current_key():
    """Peek the current context's stream head without consuming a key
    (stable: two consecutive peeks return the same key)."""
    return _krandom_resource().peek_random()


# re-exported sampling functions are generated into mxnet_tpu.ndarray.random
