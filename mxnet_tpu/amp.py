"""bf16 mixed-precision policy for the fused training stack.

The TPU's MXU multiplies natively in bfloat16: storing params and
activations in bf16 halves their HBM footprint (visible on the memwatch
owner ledger) and roughly doubles effective matmul throughput on real
chips.  This module is the single source of the dtype policy, gated by
``MXNET_TPU_BF16`` (default OFF):

- params, activations and gradients are bf16;
- every trained low-precision weight carries a master-fp32 copy in its
  optimizer state (``Optimizer.create_state_multi_precision``), the
  update runs in fp32 against the master, and the bf16 weight is re-cast
  from the new master (``Optimizer.fused_update_mp`` on the fused path,
  the generic ``update_multi_precision`` as the eager parity oracle);
- loss reduction, softmax, batchnorm statistics and normalization
  scale/shift (``*_gamma``/``*_beta``) stay fp32.

The flag is read at BIND time (it decides array dtypes) and joins every
fused-program jit-cache key through ``Executor.STEP_ENV_KEYS`` (GL001),
so a mid-process toggle recompiles instead of serving a stale program.
Traced code never reads it — op-level behavior is driven purely by input
dtypes (GL002), e.g. BatchNorm's f32-accumulated-stats fast path keys on
``data.dtype``.
"""
from __future__ import annotations

import os

import numpy as np

__all__ = ["ENV_FLAG", "enabled", "is_low_precision", "compute_dtype",
           "type_dict_for"]

ENV_FLAG = "MXNET_TPU_BF16"

# dtypes that carry a master-fp32 copy through the optimizer
_LOW_PRECISION = ("bfloat16", "float16")


def enabled():
    """MXNET_TPU_BF16 gate; default OFF."""
    return os.environ.get(ENV_FLAG, "0").lower() not in \
        ("0", "false", "off", "")


def is_low_precision(dtype):
    """Whether ``dtype`` is a storage dtype that needs an fp32 master."""
    try:
        return np.dtype(dtype).name in _LOW_PRECISION
    except TypeError:
        return False


def compute_dtype():
    """The low-precision storage/compute dtype of the policy (bf16 —
    ml_dtypes registers it with numpy, so ``np.dtype`` round-trips)."""
    import jax.numpy as jnp
    return np.dtype(jnp.bfloat16)


def type_dict_for(symbol, data_names, label_names):
    """Binding ``type_dict`` for a symbol under the bf16 policy.

    Data and weights go bf16 (grads inherit the arg dtype at bind, so
    backward runs bf16 too); labels stay fp32 (the loss head reduces in
    fp32) as do ``*_gamma``/``*_beta`` normalization params — their
    per-channel scale math is fp32-accumulated regardless of activation
    dtype, and keeping them fp32 costs nothing (channel-sized).  Aux
    states (moving stats) are fp32 by ``infer_type`` default.
    """
    bf16 = compute_dtype()
    label_set = set(label_names or ())
    td = {}
    for n in symbol.list_arguments():
        if n in label_set or n.endswith("_gamma") or n.endswith("_beta"):
            td[n] = np.float32
        else:
            td[n] = bf16
    return td
