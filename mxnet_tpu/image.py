"""Image loading / augmentation utilities + ImageIter.

Reference analog: ``python/mxnet/image/image.py`` (pure-Python ImageIter +
augmenter zoo) and the imdecode op.  Decode/augment here is host-side
OpenCV/numpy work (it feeds the device pipeline; it is NOT part of the XLA
program), matching the reference's CPU-side augmentation design.
"""
from __future__ import annotations

import os
import random

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray
from . import io as _io
from . import recordio

__all__ = ["imread", "imdecode", "imresize", "scale_down", "resize_short",
           "fixed_crop", "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "Augmenter", "SequentialAug", "RandomOrderAug",
           "ResizeAug", "ForceResizeAug", "RandomCropAug", "RandomSizedCropAug",
           "CenterCropAug", "BrightnessJitterAug", "ContrastJitterAug",
           "SaturationJitterAug", "HueJitterAug", "ColorJitterAug",
           "LightingAug", "ColorNormalizeAug", "RandomGrayAug",
           "HorizontalFlipAug", "CastAug", "CreateAugmenter", "ImageIter"]


def _cv2():
    import cv2
    return cv2


def imread(filename, flag=1, to_rgb=True):
    """Read and decode an image to NDArray (HWC, RGB by default)."""
    cv2 = _cv2()
    img = cv2.imread(filename,
                     cv2.IMREAD_COLOR if flag else cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError("cannot read image %s" % filename)
    if flag and to_rgb:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    if img.ndim == 2:
        img = img[:, :, None]
    return nd.array(img, dtype=np.uint8)


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an image byte buffer (ref: image_io.cc imdecode op)."""
    cv2 = _cv2()
    raw = np.frombuffer(buf, dtype=np.uint8) \
        if isinstance(buf, (bytes, bytearray)) else np.asarray(buf, np.uint8)
    img = cv2.imdecode(raw, cv2.IMREAD_COLOR if flag
                       else cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError("cannot decode image")
    if flag and to_rgb:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    if img.ndim == 2:
        img = img[:, :, None]
    return nd.array(img, dtype=np.uint8)


def imresize(src, w, h, interp=1):
    """Resize to (w, h)."""
    cv2 = _cv2()
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    out = cv2.resize(arr, (w, h), interpolation=_get_interp(interp))
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out, dtype=arr.dtype)


def _get_interp(interp):
    cv2 = _cv2()
    return {0: cv2.INTER_NEAREST, 1: cv2.INTER_LINEAR, 2: cv2.INTER_CUBIC,
            3: cv2.INTER_AREA, 4: cv2.INTER_LANCZOS4}.get(interp,
                                                          cv2.INTER_LINEAR)


def scale_down(src_size, size):
    """Scale down crop size if it's larger than image size."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize shorter edge to size."""
    h, w = src.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Crop src at fixed location, optionally resize to size."""
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(out, size[0], size[1], interp)
    return nd.array(out, dtype=arr.dtype)


def random_crop(src, size, interp=2):
    """Random crop with (w, h) = size, upscaling if needed."""
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = random.randint(0, w - new_w)
    y0 = random.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """Crop centered area of (w, h) = size."""
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    """Normalize with mean and optionally std."""
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return src


def random_size_crop(src, size, min_area, ratio, interp=2, **kwargs):
    """Random crop with random area & aspect ratio (Inception-style)."""
    h, w = src.shape[:2]
    area = h * w
    for _ in range(10):
        target_area = random.uniform(min_area, 1.0) * area
        new_ratio = random.uniform(*ratio)
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if random.random() < 0.5:
            new_h, new_w = new_w, new_h
        if new_w <= w and new_h <= h:
            x0 = random.randint(0, w - new_w)
            y0 = random.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


class Augmenter:
    """Image augmenter base class."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        for k, v in kwargs.items():
            if np.isscalar(v) or isinstance(v, (tuple, list, str)):
                continue
            self._kwargs[k] = v

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs],
                          default=str)

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        random.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, min_area, ratio, interp=2):
        super().__init__(size=size, min_area=min_area, ratio=ratio,
                         interp=interp)
        self.size = size
        self.min_area = min_area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.min_area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.brightness, self.brightness)
        return src * alpha


class ContrastJitterAug(Augmenter):
    coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.contrast, self.contrast)
        gray = float((src.asnumpy() * self.coef).sum()) / src.size * 3.0
        return src * alpha + gray * (1.0 - alpha)


class SaturationJitterAug(Augmenter):
    coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.saturation, self.saturation)
        arr = src.asnumpy()
        gray = (arr * self.coef).sum(axis=2, keepdims=True)
        return nd.array(arr * alpha + gray * (1.0 - alpha))


class HueJitterAug(Augmenter):
    tyiq = np.array([[0.299, 0.587, 0.114],
                     [0.596, -0.274, -0.321],
                     [0.211, -0.523, 0.311]], np.float32)
    ityiq = np.array([[1.0, 0.956, 0.621],
                      [1.0, -0.272, -0.647],
                      [1.0, -1.107, 1.705]], np.float32)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        alpha = random.uniform(-self.hue, self.hue)
        u, w = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                      np.float32)
        t = (self.ityiq @ bt @ self.tyiq).T
        return nd.array(src.asnumpy() @ t)


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return src + nd.array(rgb.reshape(1, 1, 3))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=list(np.ravel(mean)) if mean is not None else None,
                         std=list(np.ravel(std)) if std is not None else None)
        self.mean = nd.array(np.asarray(mean, np.float32)) \
            if mean is not None else None
        self.std = nd.array(np.asarray(std, np.float32)) \
            if std is not None else None

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class RandomGrayAug(Augmenter):
    coef = np.array([[0.299], [0.587], [0.114]], np.float32)

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if random.random() < self.p:
            arr = src.asnumpy()
            gray = arr @ self.coef
            src = nd.array(np.broadcast_to(gray, arr.shape).copy())
        return src


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if random.random() < self.p:
            src = nd.array(np.ascontiguousarray(src.asnumpy()[:, ::-1]))
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Create an augmenter list (ref image.py:CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(
            crop_size, 0.08, (3.0 / 4.0, 4.0 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    elif mean is not None:
        mean = np.asarray(mean)
        assert mean.shape[0] in [1, 3]
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    elif std is not None:
        std = np.asarray(std)
        assert std.shape[0] in [1, 3]
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(_io.DataIter):
    """Image iterator over .rec files or .lst/image folders, with
    augmentation (ref image.py:ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=None,
                 num_parts=None, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__()
        assert path_imgrec or path_imglist or (isinstance(imglist, list)) \
            or path_root, "must provide a data source"
        # per-mesh-host sharding defaults (single-process => whole set):
        # each host walks only its 1/num_parts stride of the sequence
        if num_parts is None and part_index is None:
            from .parallel.mesh import host_shard_hint
            part_index, num_parts = host_shard_hint()
        num_parts = 1 if num_parts is None else int(num_parts)
        part_index = 0 if part_index is None else int(part_index)
        if not 0 <= part_index < num_parts:
            raise MXNetError("ImageIter: part_index %d out of range for "
                             "num_parts %d" % (part_index, num_parts))
        if path_imgrec:
            if path_imgidx is None:
                path_imgidx = os.path.splitext(path_imgrec)[0] + ".idx"
            self.imgrec = recordio.MXIndexedRecordIO(
                path_imgidx, path_imgrec, "r")
            self.imgidx = list(self.imgrec.keys)
        else:
            self.imgrec = None
        self.imglist = {}
        if path_imglist:
            with open(path_imglist) as fin:
                for line in fin:
                    parts = line.strip().split("\t")
                    label = np.array(
                        [float(i) for i in parts[1:-1]], np.float32)
                    self.imglist[int(parts[0])] = (label, parts[-1])
            self.seq = list(self.imglist.keys())
        elif isinstance(imglist, list):
            for i, item in enumerate(imglist):
                self.imglist[i] = (np.array(item[0], np.float32)
                                   if isinstance(item[0], (list, tuple))
                                   else np.array([item[0]], np.float32),
                                   item[1])
            self.seq = list(self.imglist.keys())
        elif self.imgrec is not None:
            self.seq = self.imgidx
        else:
            raise MXNetError("path_root-only mode requires path_imglist")
        if num_parts > 1:
            self.seq = self.seq[part_index::num_parts]
        self.path_root = path_root
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        self.cur = 0
        self._data_name = data_name
        self._label_name = label_name
        self.reset()

    @property
    def provide_data(self):
        return [_io.DataDesc(self._data_name,
                             (self.batch_size,) + self.data_shape,
                             np.float32)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        return [_io.DataDesc(self._label_name, shape, np.float32)]

    def reset(self):
        if self.shuffle:
            random.shuffle(self.seq)
        self.cur = 0

    def next_sample(self):
        if self.cur >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cur]
        self.cur += 1
        if self.imgrec is not None:
            s = self.imgrec.read_idx(idx)
            header, img = recordio.unpack(s)
            if idx in self.imglist:
                return self.imglist[idx][0], img
            return header.label, img
        label, fname = self.imglist[idx]
        with open(os.path.join(self.path_root or "", fname), "rb") as fin:
            img = fin.read()
        return label, img

    def next(self):
        batch_data = np.zeros(
            (self.batch_size,) + self.data_shape, np.float32)
        shape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        batch_label = np.zeros(shape, np.float32)
        i = 0
        try:
            while i < self.batch_size:
                label, s = self.next_sample()
                data = imdecode(s)
                for aug in self.auglist:
                    data = aug(data)
                arr = data.asnumpy() if isinstance(data, NDArray) \
                    else np.asarray(data)
                batch_data[i] = arr.transpose(2, 0, 1)
                batch_label[i] = label if self.label_width > 1 \
                    else (label[0] if hasattr(label, "__len__") else label)
                i += 1
        except StopIteration:
            if i == 0:
                raise
        return _io.DataBatch(
            [nd.array(batch_data)], [nd.array(batch_label)],
            pad=self.batch_size - i)


# detection pipeline lives in image_detection.py; re-exported here for the
# reference namespace layout (mx.image.ImageDetIter, mx.image.CreateDetAugmenter)
from .image_detection import (  # noqa: E402,F401
    DetAugmenter, DetBorrowAug, DetRandomSelectAug, DetHorizontalFlipAug,
    DetRandomCropAug, DetRandomPadAug, CreateMultiRandCropAugmenter,
    CreateDetAugmenter, ImageDetIter)
