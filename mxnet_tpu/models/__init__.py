"""First-class model zoo (symbol-level workloads).

Reference analog: ``example/`` model definitions in the reference tree —
promoted here into the library because the transformer LM is the
workload class the TPU benches and the parallel/ subsystems exist for
(ROADMAP item 1).  ``transformer`` builds decoder-only LMs as Symbol
graphs that train through Module's fused/mesh step; ``configs`` is the
size ladder.
"""
from . import configs
from . import transformer
from .configs import TransformerConfig, CONFIGS, get_config
from .transformer import (transformer_lm, transformer_block,
                          init_block_params, block_apply,
                          pipeline_transformer, long_context_attention,
                          moe_transformer_ffn)

__all__ = ["configs", "transformer", "TransformerConfig", "CONFIGS",
           "get_config", "transformer_lm", "transformer_block",
           "init_block_params", "block_apply", "pipeline_transformer",
           "long_context_attention", "moe_transformer_ffn"]
