"""Decoder-LM size ladder: tiny (CI/CPU) up to gpt2-small-ish.

The ladder exists so every consumer — tests, bench.py --transformer,
serving — names shapes the same way instead of re-inventing ad-hoc
dims.  ``flops_per_token`` uses the standard dense-training accounting
(6N weight-FLOPs + attention score/value terms, PaLM appendix B
convention, causal masking NOT halved) so MFU numbers are comparable
across published results.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class TransformerConfig:
    name: str
    vocab_size: int
    n_layers: int
    d_model: int
    n_heads: int
    d_ff: int
    seq_len: int

    def __post_init__(self):
        if self.d_model % self.n_heads:
            raise ValueError("d_model %d not divisible by n_heads %d"
                             % (self.d_model, self.n_heads))

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def n_params(self) -> int:
        """Weight count of the matmul-bearing parameters (embedding +
        per-block QKVO/FFN + untied LM head; norms excluded — noise)."""
        d, f, L, v = self.d_model, self.d_ff, self.n_layers, self.vocab_size
        return v * d + L * (4 * d * d + 2 * d * f) + d * v

    def flops_per_token(self) -> float:
        """Training (fwd+bwd) FLOPs per token: 6 per matmul weight plus
        the attention score/value matmuls, 12·L·T·d_model."""
        d, L, f, v = (self.d_model, self.n_layers, self.d_ff,
                      self.vocab_size)
        matmul_params = v * d + L * (4 * d * d + 2 * d * f)
        return 6.0 * matmul_params + 12.0 * L * self.seq_len * d


CONFIGS = {
    # CI / CPU smoke shape: compiles in seconds, exercises every layer
    "tiny": TransformerConfig("tiny", vocab_size=256, n_layers=2,
                              d_model=64, n_heads=4, d_ff=256, seq_len=64),
    # CPU bench shape: big enough that tokens/s has signal
    "mini": TransformerConfig("mini", vocab_size=1024, n_layers=4,
                              d_model=128, n_heads=4, d_ff=512,
                              seq_len=128),
    # single-chip dev shape
    "small": TransformerConfig("small", vocab_size=8192, n_layers=6,
                               d_model=384, n_heads=6, d_ff=1536,
                               seq_len=256),
    # gpt2-small-ish (124M): the chip target for bench.py --transformer
    "gpt2-small": TransformerConfig("gpt2-small", vocab_size=50257,
                                    n_layers=12, d_model=768, n_heads=12,
                                    d_ff=3072, seq_len=1024),
}


def get_config(name: str, **overrides) -> TransformerConfig:
    """Ladder lookup with field overrides (e.g. a shorter seq_len)."""
    from dataclasses import replace
    try:
        cfg = CONFIGS[name]
    except KeyError:
        raise KeyError("unknown transformer config %r (have: %s)"
                       % (name, ", ".join(sorted(CONFIGS))))
    return replace(cfg, **overrides) if overrides else cfg
