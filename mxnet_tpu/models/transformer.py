"""Decoder-only transformer LM as a first-class Symbol workload.

Reference analog: none in-tree — the reference (2018) stops at
example/rnn word LMs; this is the beyond-parity workload ROADMAP item 1
names.  Two layers of API:

* **Symbol graph** (``transformer_lm`` / ``transformer_block``): the
  training graph that binds through Module and runs the fused/mesh step
  end to end — Embedding, pre-norm blocks around the
  ``MultiHeadAttention`` op (Pallas flash kernel behind
  ``MXNET_TPU_FLASH_ATTENTION``), gelu FFN, streaming-CE loss.
  Parameter names are chosen so ``parallel.mesh.megatron_rules`` shards
  a DP×TP mesh with zero configuration: ``*_query/key/value_weight`` and
  ``*_fc1_weight`` column-parallel, ``*_out_proj_weight`` and
  ``*_down_weight`` row-parallel, ``*_embedding_weight`` vocab-split.

* **Functional block** (``init_block_params`` / ``block_apply`` +
  the composition helpers): the SAME block math as pure jax functions
  reusing the registered op implementations, which is what the
  parallel/ subsystems compose — ``pipeline_transformer`` runs blocks as
  GPipe stages, ``long_context_attention`` shards the sequence over a
  mesh ``sp`` axis via ring attention, ``moe_transformer_ffn`` swaps the
  dense FFN for the expert-parallel MoE layer.  Reusing the op fns (not
  a re-implementation) is what makes the parity tests in
  tests/test_transformer.py bit-exact.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .configs import TransformerConfig
from ..ops.registry import OPS


# ---------------------------------------------------------------------------
# Symbol graph
# ---------------------------------------------------------------------------
def transformer_block(x, cfg: TransformerConfig, idx: int, prefix: str):
    """One pre-norm decoder block: x + Attn(LN(x)); x + FFN(LN(x))."""
    from .. import symbol as sym
    n = "%sl%d_" % (prefix, idx)
    h = sym.LayerNorm(x, name=n + "ln1")
    a = sym.MultiHeadAttention(h, num_heads=cfg.n_heads, causal=True,
                               name=n + "attn")
    x = sym.elemwise_add(x, a, name=n + "attn_res")
    h = sym.LayerNorm(x, name=n + "ln2")
    f = sym.FullyConnected(h, num_hidden=cfg.d_ff, flatten=False,
                           no_bias=False, name=n + "ffn_fc1")
    f = sym.Activation(f, act_type="gelu", name=n + "ffn_gelu")
    f = sym.FullyConnected(f, num_hidden=cfg.d_model, flatten=False,
                           no_bias=False, name=n + "ffn_down")
    return sym.elemwise_add(x, f, name=n + "ffn_res")


def transformer_lm(cfg: TransformerConfig, prefix: str = "tfm_",
                   loss: bool = True):
    """Build the decoder LM Symbol.

    ``loss=True`` (training): returns ``make_loss(mean(streaming CE))``
    — a scalar loss head whose implicit backward seeds ones, so
    ``Module.forward_backward`` / the fused step train it directly and
    ``get_outputs()[0]`` IS the batch loss.  ``loss=False``: returns the
    ``(B, T, vocab)`` logits (serving / eval).

    Positions are encoded with a learned table added post-embedding
    (gpt2 style); data is ``(B, T)`` token ids, label ``(B, T)`` next
    tokens.
    """
    from .. import symbol as sym
    data = sym.Variable("data")                       # (B, T) token ids
    tok = sym.Embedding(data, input_dim=cfg.vocab_size,
                        output_dim=cfg.d_model,
                        name=prefix + "tok_embedding")
    # learned positions: arange(T) broadcast over the batch rides the
    # same Embedding op — slice_axis of a (1, T) iota variable would need
    # a T-sized input; instead embed positions of `data*0 + iota` shape
    pos_ids = sym.broadcast_like(
        sym.expand_dims(sym.arange(0, cfg.seq_len, name=prefix + "iota"),
                        axis=0),
        data, name=prefix + "pos_ids")
    pos = sym.Embedding(pos_ids, input_dim=cfg.seq_len,
                        output_dim=cfg.d_model,
                        name=prefix + "pos_embedding")
    x = sym.broadcast_add(tok, pos, name=prefix + "embed_sum")
    for i in range(cfg.n_layers):
        x = transformer_block(x, cfg, i, prefix)
    x = sym.LayerNorm(x, name=prefix + "final_ln")
    logits = sym.FullyConnected(x, num_hidden=cfg.vocab_size,
                                flatten=False, no_bias=True,
                                name=prefix + "lm_head")
    if not loss:
        return logits
    label = sym.Variable("softmax_label")             # (B, T) next ids
    ce = sym.streaming_softmax_ce(logits, label, axis=-1,
                                  name=prefix + "ce")
    return sym.make_loss(sym.mean(ce), name=prefix + "loss")


# ---------------------------------------------------------------------------
# Functional block (shared math with the Symbol graph via the op registry)
# ---------------------------------------------------------------------------
_LN_ATTRS = {"axis": -1, "eps": 1e-5, "output_mean_var": False}


def _ln(x, gamma, beta):
    return OPS["LayerNorm"].fn(_LN_ATTRS, x, gamma, beta)[0]


def _mha(cfg, x, wq, wk, wv, wo):
    return OPS["MultiHeadAttention"].fn(
        {"num_heads": cfg.n_heads, "causal": True}, x, wq, wk, wv, wo)


def init_block_params(cfg: TransformerConfig, rng: np.random.RandomState,
                      dtype=jnp.float32):
    """One block's parameter dict (same shapes/orientation as the Symbol
    graph's auto-allocated args: weights are (out, in))."""
    d, f = cfg.d_model, cfg.d_ff

    def w(*shape, scale=0.02):
        return jnp.asarray(rng.standard_normal(shape) * scale, dtype)

    return {
        "ln1_gamma": jnp.ones((d,), dtype), "ln1_beta": jnp.zeros((d,), dtype),
        "query_weight": w(d, d), "key_weight": w(d, d),
        "value_weight": w(d, d), "out_proj_weight": w(d, d),
        "ln2_gamma": jnp.ones((d,), dtype), "ln2_beta": jnp.zeros((d,), dtype),
        "fc1_weight": w(f, d), "fc1_bias": jnp.zeros((f,), dtype),
        "down_weight": w(d, f), "down_bias": jnp.zeros((d,), dtype),
    }


def block_apply(cfg: TransformerConfig, params, x):
    """Functional pre-norm block — identical math to ``transformer_block``
    (same op implementations out of the registry)."""
    h = _ln(x, params["ln1_gamma"], params["ln1_beta"])
    x = x + _mha(cfg, h, params["query_weight"], params["key_weight"],
                 params["value_weight"], params["out_proj_weight"])
    h = _ln(x, params["ln2_gamma"], params["ln2_beta"])
    h = jnp.matmul(h, params["fc1_weight"].T) + params["fc1_bias"]
    h = jax.nn.gelu(h, approximate=False)
    h = jnp.matmul(h, params["down_weight"].T) + params["down_bias"]
    return x + h


# ---------------------------------------------------------------------------
# Parallel composition
# ---------------------------------------------------------------------------
def long_context_attention(q, k, v, mesh, axis: str = "sp",
                           causal: bool = True,
                           block_size: int = 512,
                           scale: Optional[float] = None):
    """Sequence-parallel exact attention for contexts that don't fit one
    chip: ``parallel.ring_attention`` over the mesh ``axis`` — K/V shards
    rotate the ICI ring while each chip keeps its Q shard.  [B,H,T,D]
    with T sharded on ``axis``; bit-parity vs ``blockwise_attention`` is
    pinned by tests/test_transformer.py."""
    from ..parallel.ring_attention import ring_attention
    return ring_attention(q, k, v, mesh, axis=axis, causal=causal,
                          block_size=block_size, scale=scale)


def moe_transformer_ffn(x, moe_params, mesh=None, axis: str = "ep",
                        k: int = 2, capacity_factor: float = 1.25):
    """MoE FFN block body: drop-in replacement for the dense FFN half of
    ``block_apply`` (caller keeps the pre-norm + residual).  Experts are
    sharded over the mesh ``axis``; gelu to match the dense path."""
    from ..parallel.moe import moe_ffn
    T = x.shape[-2] if x.ndim > 2 else x.shape[0]
    del T
    flat = x.reshape(-1, x.shape[-1])
    out = moe_ffn(flat, moe_params, mesh=mesh, axis=axis, k=k,
                  capacity_factor=capacity_factor,
                  act=lambda a: jax.nn.gelu(a, approximate=False))
    return out.reshape(x.shape)


def pipeline_transformer(mesh, axis: str, cfg: TransformerConfig,
                         stage_params, x, n_micro: int):
    """Run transformer blocks as GPipe pipeline stages over ``mesh[axis]``:
    ``stage_params`` leaves carry a leading stage dim (one block per
    stage); microbatches stream through ``parallel.pipeline``.  Parity vs
    sequentially applying the same blocks is pinned by tests."""
    from ..parallel.pipeline import pipeline_apply

    def stage_fn(params, xb):
        return block_apply(cfg, params, xb)

    return pipeline_apply(mesh, axis, stage_fn, stage_params, x, n_micro)
