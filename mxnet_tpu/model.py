"""Checkpointing + legacy FeedForward model API.

Reference analog: ``python/mxnet/model.py`` — save_checkpoint/load_checkpoint
(prefix-symbol.json + prefix-%04d.params convention, SURVEY.md §5.4) and the
pre-Module FeedForward trainer.  Artifact semantics preserved: a graph JSON +
a named-array dict with ``arg:``/``aux:`` prefixes.
"""
from __future__ import annotations

import logging
from typing import Dict, Optional

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from . import symbol as sym_mod
from .context import cpu, current_context

__all__ = ["save_checkpoint", "load_checkpoint", "load_params",
           "FeedForward", "BatchEndParam"]

from .callback import BatchEndParam  # re-export for parity


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Write prefix-symbol.json + prefix-%04d.params (ref model.py)."""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    nd.save("%s-%04d.params" % (prefix, epoch), save_dict)
    logging.info('Saved checkpoint to "%s-%04d.params"', prefix, epoch)


def load_params(prefix, epoch):
    loaded = nd.load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    symbol = sym_mod.load("%s-symbol.json" % prefix)
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy model API (ref model.py:FeedForward) — thin shim over Module."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from . import initializer as init_mod
        self.symbol = symbol
        self.ctx = ctx or [current_context()]
        if not isinstance(self.ctx, list):
            self.ctx = [self.ctx]
        self.num_epoch = num_epoch
        self.optimizer = optimizer
        self.initializer = initializer or init_mod.Uniform(0.01)
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.begin_epoch = begin_epoch
        self.kwargs = dict(kwargs)
        self._module = None

    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        from .module import Module
        from .io import NDArrayIter, DataIter
        if not isinstance(X, DataIter):
            X = NDArrayIter(X, y, batch_size=128, shuffle=True)
        label_names = [d.name for d in (X.provide_label or [])]
        mod = Module(self.symbol,
                     data_names=[d.name for d in X.provide_data],
                     label_names=label_names, context=self.ctx)
        mod.fit(X, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer,
                optimizer_params=self.kwargs.get("optimizer_params",
                                                 (("learning_rate", 0.01),)),
                initializer=self.initializer, arg_params=self.arg_params,
                aux_params=self.aux_params, begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch, monitor=monitor)
        self._module = mod
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        from .io import NDArrayIter, DataIter
        if not isinstance(X, DataIter):
            X = NDArrayIter(X, batch_size=128)
        return self._module.predict(X, num_batch=num_batch, reset=reset) \
            .asnumpy()

    def save(self, prefix, epoch=None):
        epoch = epoch if epoch is not None else self.num_epoch or 0
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)
