"""Training callbacks (parity: python/mxnet/callback.py — Speedometer,
do_checkpoint, log_train_metric, ProgressBar)."""
from __future__ import annotations

import logging
import time

__all__ = ["Speedometer", "do_checkpoint", "log_train_metric", "ProgressBar",
           "module_checkpoint", "BatchEndParam"]


class BatchEndParam:
    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


class Speedometer:
    """Logs samples/sec every `frequent` batches (ref callback.Speedometer)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self.init = False
        self.tic = 0.0
        self.last_count = 0

    def __call__(self, param: BatchEndParam):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / \
                    (time.time() - self.tic)
                if param.eval_metric is not None:
                    nv = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s"
                    logging.info(msg, param.epoch, count, speed,
                                 "\t".join("%s=%f" % kv for kv in nv))
                else:
                    logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                                 param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


def do_checkpoint(prefix, period=1):
    """Epoch-end checkpoint callback (ref callback.do_checkpoint)."""
    from .model import save_checkpoint
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


module_checkpoint = do_checkpoint


def log_train_metric(period, auto_reset=False):
    def _callback(param: BatchEndParam):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            nv = param.eval_metric.get_name_value()
            logging.info("Iter[%d] Batch[%d] Train-%s", param.epoch,
                         param.nbatch,
                         "\t".join("%s=%f" % kv for kv in nv))
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class ProgressBar:
    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param: BatchEndParam):
        count = param.nbatch
        filled = int(round(self.bar_len * count / float(self.total)))
        pct = round(100.0 * count / float(self.total), 1)
        bar = "=" * filled + "-" * (self.bar_len - filled)
        logging.info("[%s] %s%s\r", bar, pct, "%")
