"""KVStore: data-parallel parameter/gradient communication façade.

Reference analog: ``include/mxnet/kvstore.h:47`` + ``src/kvstore/*``
(SURVEY.md N10-N13): ``local`` (CPU reduce), ``device`` (P2P GPU reduce
trees), ``nccl`` (collectives), ``dist_sync``/``dist_async`` (ps-lite
parameter server with optional server-side optimizer).

TPU-native design (SURVEY.md §5.8): single-process multi-device stores
(``local``/``device``/``nccl``) reduce over devices with XLA — a jitted
multi-device sum (the ICI all-reduce path once arrays live on a Mesh);
``dist_sync`` rides the multi-host JAX runtime (jax.distributed +
``parallel/``'s psum train steps) instead of a parameter server — rank/size
come from the JAX process group.  ``dist_async`` IS a parameter server
(``kvstore_server.py``: host-resident TCP, immediate per-push apply,
server-side pickled optimizer) because barrier-free staleness-tolerant
updates have no XLA-collective analog.  The Python API
(init/push/pull/row_sparse_pull/set_optimizer/compression) is preserved.
"""
from __future__ import annotations

import os
import pickle
import threading
import time as _time
from typing import Any, Dict, List, Optional, Union

import numpy as np

from .base import MXNetError, get_env
from . import ndarray as nd
from .ndarray.ndarray import NDArray
from . import optimizer as opt
from . import telemetry as _telemetry
from . import tracing as _tracing
from . import health as _health

__all__ = ["KVStore", "create"]

_KV_PUSH = _telemetry.counter(
    "kvstore_push_total", "KVStore push operations (one per key)",
    ("type",))
_KV_PULL = _telemetry.counter(
    "kvstore_pull_total", "KVStore pull operations (one per key)",
    ("type",))
_KV_PUSH_LAT = _telemetry.histogram(
    "kvstore_push_latency_seconds", "Wall time of one push() call",
    ("type",))
_KV_PULL_LAT = _telemetry.histogram(
    "kvstore_pull_latency_seconds", "Wall time of one pull() call",
    ("type",))
_KV_BYTES_TX = _telemetry.counter(
    "kvstore_bytes_sent_total",
    "Tensor payload bytes sent to the parameter server", ("key",))
_KV_BYTES_RX = _telemetry.counter(
    "kvstore_bytes_received_total",
    "Tensor payload bytes received from the parameter server", ("key",))
# Failure-path counters count unconditionally (like the server's frame
# errors): a reconnect storm is exactly what an operator must see even
# before opting into hot-path telemetry.
_KV_RECONNECTS = _telemetry.counter(
    "kvstore_reconnects_total",
    "Worker reconnects to the parameter server after a failed op")
_KV_RETRIES = _telemetry.counter(
    "kvstore_retries_total",
    "KVStore ops retried after a timeout/connection failure", ("op",))
_KV_OP_TIMEOUTS = _telemetry.counter(
    "kvstore_op_timeout_total",
    "KVStore ops whose reply missed MXNET_KVSTORE_OP_TIMEOUT")


def backoff_delay(attempt, base=0.05, cap=2.0, rng=None):
    """Exponential backoff with jitter for retry attempt ``attempt``
    (0-based): ``min(cap, base * 2**attempt)`` scaled by a uniform factor
    in [0.5, 1.5) so a gang of workers whose server died together does not
    reconnect in lockstep.  ``rng`` is a 0-arg callable returning [0, 1)
    (injectable for deterministic tests)."""
    if base <= 0:
        return 0.0
    import random as _random
    r = (rng or _random.random)()
    return min(float(cap), float(base) * (2.0 ** int(attempt))) * (0.5 + r)


def _key(k):
    return str(k)


# ---- gradient bucketing ---------------------------------------------------
# dist_async coalesces dense uncompressed push/pull traffic into flat
# dtype-segregated buckets: O(num_params) wire messages become
# O(total_bytes / bucket_bytes).  Per-key frames are untouched — a
# singleton bucket goes out as a plain "push"/"pull".

BUCKET_BYTES_ENV = "MXNET_KVSTORE_BUCKET_BYTES"
DEFAULT_BUCKET_BYTES = 4 << 20


def bucket_bytes():
    """Bucket byte budget; <= 0 disables bucketing."""
    raw = os.environ.get(BUCKET_BYTES_ENV)
    if raw is None or not raw.strip():
        return DEFAULT_BUCKET_BYTES
    try:
        return int(raw)
    except ValueError:
        return DEFAULT_BUCKET_BYTES


def pack_buckets(entries, budget, nbytes=None, group=None):
    """Greedily pack ``(key, payload)`` entries into buckets of at most
    ``budget`` payload bytes, segregated by ``group(payload)`` (dtype: a
    flat bucket is one contiguous array, so mixed dtypes can't share one).
    Order is preserved within a group; an oversized single payload gets a
    bucket of its own.  ``budget <= 0`` (or < 2 entries) disables packing.
    """
    if nbytes is None:
        nbytes = lambda a: a.nbytes
    if group is None:
        group = lambda a: np.dtype(a.dtype).str
    if budget <= 0 or len(entries) < 2:
        return [[e] for e in entries]
    groups, order = {}, []
    for e in entries:
        gk = group(e[1])
        if gk not in groups:
            groups[gk] = []
            order.append(gk)
        groups[gk].append(e)
    buckets = []
    for gk in order:
        cur, cur_b = [], 0
        for e in groups[gk]:
            b = nbytes(e[1])
            if cur and cur_b + b > budget:
                buckets.append(cur)
                cur, cur_b = [], 0
            cur.append(e)
            cur_b += b
        if cur:
            buckets.append(cur)
    return buckets


class KVStore:
    """Single-process store: local/device/nccl (all XLA-reduced on TPU)."""

    def __init__(self, kind="local"):
        self.kind = kind
        self._store: Dict[str, NDArray] = {}
        self._updater = None
        self._optimizer = None
        self._compression = None

    # ---- core API -------------------------------------------------------
    def _ledger(self, keys):
        # the store's aggregation buffers are repointed on every push —
        # keep them in the memory ledger or they census as untagged
        from . import memwatch as _memwatch
        if _memwatch.enabled:
            for k in keys:
                _memwatch.tag("opt_state", self._store[k], detail="kvstore")

    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError("key %r already initialized" % k)
            v0 = v[0] if isinstance(v, (list, tuple)) else v
            self._store[k] = v0.copy()
        self._ledger(keys)

    def push(self, key, value, priority=0):
        tel = _telemetry.enabled
        t0 = _time.perf_counter() if tel else 0.0
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            # reduce across devices (the CommDevice tree reduce of comm.h
            # becomes one XLA add chain; sparse lists stay sparse) —
            # shared with the dist stores' pre-wire reduce
            agg = _local_sum(v)
            if self._updater is not None:
                self._updater(k, agg, self._store[k])
            else:
                # default updater is ASSIGN (reference kvstore docs): the
                # aggregate replaces the stored value, cast to its stype
                dst = self._store[k]
                if dst.stype != agg.stype:
                    from .ndarray.sparse import cast_storage
                    agg = cast_storage(agg, dst.stype)
                agg.copyto(dst)
        self._ledger(keys)
        if tel:
            _KV_PUSH.labels(type=self.kind).inc(len(keys))
            _KV_PUSH_LAT.labels(type=self.kind).observe(
                _time.perf_counter() - t0)
            if _health.enabled:
                _health.monitor.note_phase(
                    "sync", _time.perf_counter() - t0)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        tel = _telemetry.enabled
        t0 = _time.perf_counter() if tel else 0.0
        keys, outs = self._normalize(key, out)
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            src = self._store[k]
            olist = o if isinstance(o, (list, tuple)) else [o]
            for dst in olist:
                src.copyto(dst)
        if tel:
            _KV_PULL.labels(type=self.kind).inc(len(keys))
            _KV_PULL_LAT.labels(type=self.kind).observe(
                _time.perf_counter() - t0)
            if _health.enabled:
                _health.monitor.note_phase(
                    "sync", _time.perf_counter() - t0)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (reference kvstore_local.h:109-247);
        dense-device TPU path gathers the rows; a RowSparseNDArray ``out``
        receives exactly the requested row set."""
        from .ndarray.sparse import (RowSparseNDArray, retain,
                                     row_sparse_array)
        import numpy as np
        keys, outs = self._normalize(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        for k, o in zip(keys, outs):
            src = self._store[k]
            olist = o if isinstance(o, (list, tuple)) else [o]
            rlist = _broadcast_row_ids(rids, olist)
            for dst, rid in zip(olist, rlist):
                if isinstance(dst, RowSparseNDArray):
                    if isinstance(src, RowSparseNDArray):
                        retain(src, rid).copyto(dst)
                    else:
                        ids = np.unique(rid.asnumpy().astype(np.int64))
                        rows = nd.take(src, nd.array(ids, dtype="int32"))
                        row_sparse_array((rows, ids),
                                         shape=src.shape).copyto(dst)
                    continue
                rows = nd.take(src, rid.astype("int32"))
                full = nd.zeros(src.shape, ctx=dst.context, dtype=src.dtype)
                idx = rid.astype("int32")
                full[idx] = rows.as_in_context(dst.context)
                full.copyto(dst)

    # ---- config ---------------------------------------------------------
    def set_optimizer(self, optimizer: opt.Optimizer):
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression (reference N13).  On TPU intra-host
        reduction is exact; accepted for API parity, applied on the dist
        path (DCN) where bandwidth matters.  ``None`` (or type 'none')
        turns compression off."""
        from .kvstore_compression import GradientCompression
        if compression_params is None:
            self._compression = None
            return
        params = dict(compression_params)
        ctype = params.pop("type", "2bit")
        if ctype in ("none", None):
            if params:      # typo'd keys must not pass silently here either
                raise MXNetError("unknown compression params %s"
                                 % list(params))
            self._compression = None
            return
        threshold = float(params.pop("threshold", 0.5))
        if params:
            raise MXNetError("unknown compression params %s" % list(params))
        self._compression = GradientCompression(type=ctype,
                                                threshold=threshold)

    @property
    def gradient_compression(self):
        return self._compression

    @property
    def type(self):
        return self.kind

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def barrier(self):
        nd.waitall()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("optimizer not set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer not set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def _send_command_to_servers(self, head, body):
        pass

    # ---- helpers --------------------------------------------------------
    @staticmethod
    def _normalize(key, value):
        if isinstance(key, (list, tuple)):
            return [_key(k) for k in key], list(value)
        return [_key(key)], [value]


def _broadcast_row_ids(rids, olist):
    """row_ids -> one-per-output: a single id array broadcasts; otherwise
    the counts must match exactly (a silent zip-truncate pairs outputs
    with the wrong rows — reference errors here too)."""
    if len(rids) == len(olist):
        return rids
    if len(rids) == 1:
        return rids * len(olist)
    raise MXNetError("row_sparse_pull: %d row_ids for %d outputs"
                     % (len(rids), len(olist)))


def _local_sum(v):
    """Sum a per-device value list into one array (the intra-worker
    reduce every dist push does before going on the wire).  Row-sparse
    lists reduce sparse-aware (union of rows), like the base store's
    push — an in-place dense += on RowSparseNDArray raises."""
    from .ndarray.sparse import RowSparseNDArray, add as _sparse_add
    vlist = v if isinstance(v, (list, tuple)) else [v]
    agg = vlist[0]
    if len(vlist) > 1:
        if all(isinstance(x, RowSparseNDArray) for x in vlist):
            for x in vlist[1:]:
                # co-locate before the sparse scatter-add: mixing arrays
                # committed to different devices raises in eager ops
                agg = _sparse_add(agg, x.as_in_context(agg.context))
        else:
            agg = vlist[0].tostype("default") \
                if isinstance(vlist[0], RowSparseNDArray) \
                else vlist[0].copy()
            for x in vlist[1:]:
                agg += x.as_in_context(agg.context)
    return agg


class DistKVStore(KVStore):
    """Multi-host store over the JAX distributed runtime (DCN).

    Reference: kvstore_dist.h worker + kvstore_dist_server.h (ps-lite).
    TPU-native: every host holds a replica; push performs a cross-process
    all-reduce via ``parallel.comm`` collectives (jax.distributed must be
    initialized — ``parallel.init_distributed()``); there are no separate
    server processes.  ``dist_async`` is handled by
    :class:`DistAsyncKVStore` (true parameter server) instead.
    """

    def __init__(self, kind="dist_sync"):
        super().__init__(kind)
        from . import parallel
        self._pg = parallel.process_group()

    def init(self, key, value):
        """Rank 0's value wins everywhere (reference semantics: worker 0
        initializes the parameter server, kvstore_dist.h InitImpl — other
        ranks' init values are discarded)."""
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError("key %r already initialized" % k)
            v0 = v[0] if isinstance(v, (list, tuple)) else v
            self._store[k] = self._pg.broadcast(v0.copy(), root=0)
        self._ledger(keys)

    @property
    def rank(self):
        return self._pg.rank

    @property
    def num_workers(self):
        return self._pg.size

    def push(self, key, value, priority=0):
        from .ndarray.sparse import RowSparseNDArray
        tel = _telemetry.enabled
        t0 = _time.perf_counter() if tel else 0.0
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            agg = _local_sum(v)
            if self._compression:
                if isinstance(agg, RowSparseNDArray):
                    # reference contract: sparse + compression errors —
                    # a silent densify-then-quantize would threshold-zero
                    # every untouched row
                    raise MXNetError(
                        "gradient compression does not support "
                        "row_sparse push (key %r)" % k)
                # each worker ships its quantized gradient (2-bit + error
                # feedback, N13); summing dequantized streams across ranks
                # == the reference PS aggregating decompressed pushes
                agg = NDArray(self._compression.compress(k, agg._data),
                              agg.context)
            agg = self._pg.allreduce(agg)
            if self._updater is not None:
                self._updater(k, agg, self._store[k])
            else:
                # default updater is ASSIGN (reference kvstore docs): the
                # aggregate replaces the stored value
                agg.copyto(self._store[k])
        self._ledger(keys)
        if tel:
            _KV_PUSH.labels(type=self.kind).inc(len(keys))
            _KV_PUSH_LAT.labels(type=self.kind).observe(
                _time.perf_counter() - t0)

    def barrier(self):
        self._pg.barrier()


class DistAsyncKVStore(KVStore):
    """``dist_async``: the true parameter-server path (kvstore_server.py).

    Reference semantics (kvstore_dist_server.h async mode): every worker
    pushes gradients to the server, which applies its optimizer
    IMMEDIATELY — no per-batch barrier, workers run at their own pace on
    possibly-stale weights; pull fetches whatever the weights currently
    are.  ``set_optimizer`` ships the pickled optimizer to the server
    (reference kvstore_server.py:55), after which ``update_on_kvstore``
    holds: push(grad) triggers the server-side update and the worker-side
    updater stays unused.
    """

    #: ops whose server-side apply is not idempotent: their frames carry a
    #: (rank, seq) context so a replay after reconnect is acked, not
    #: re-applied (mirror of KVStoreServer._MUTATING)
    _SEQ_OPS = frozenset(("push", "push_bucket", "push_rsp", "push_2bit",
                          "barrier"))

    def __init__(self, kind="dist_async"):
        super().__init__(kind)
        import socket as _socket
        from . import kvstore_server as _ps
        host, port = _ps.ps_address()
        self._ps = _ps
        self._socket_mod = _socket
        self._host, self._port = host, port
        self._sock = None
        # the server process may come up after the workers: retry connect
        deadline = _time.time() + float(
            get_env("MXNET_PS_CONNECT_TIMEOUT_SEC", 60))
        last_err = None
        while _time.time() < deadline:
            try:
                self._sock = _socket.create_connection((host, port),
                                                       timeout=60)
                break
            except OSError as e:
                last_err = e
                _time.sleep(0.2)
        if self._sock is None:
            raise MXNetError("cannot reach parameter server %s:%d: %s"
                             % (host, port, last_err))
        self._rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
        self._num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        self._lock = threading.Lock()
        # per-worker monotonic op sequence (rides the wire as the seq
        # context; assigned once per LOGICAL op, reused verbatim when the
        # frame is replayed after a reconnect).  The identity carries a
        # per-process incarnation suffix: a RELAUNCHED worker restarts at
        # seq 0, and without a fresh dedup lane a durable server that
        # remembers the previous incarnation's seqs would silently drop
        # every new push as a replay.
        self._seq = 0
        self._seq_ident = "%d.%s" % (self._rank, os.urandom(4).hex())

    def _rpc(self, *msg):
        if _tracing.enabled:
            # client span around the round-trip; flow_out() starts a
            # cross-process flow whose end the server handler span emits,
            # and returns the wire trace context the frame carries
            with _tracing.span("KVStore::%s" % (msg[0],), "kvstore") as sp:
                reply = self._roundtrip(msg, sp.flow_out())
        else:
            reply = self._roundtrip(msg, None)
        if reply is None:
            raise MXNetError("parameter server closed the connection")
        if reply[0] != "ok":
            raise MXNetError("parameter server: %s" % reply[1])
        return reply[1] if len(reply) > 1 else None

    def _op_timeout(self, op):
        """Per-attempt deadline: EVERY blocking wire call is bounded by
        this (a dead server must surface as a timeout, never a hang).
        barrier() legitimately waits for the slowest worker, so it gets
        its own larger knob instead of unbounded blocking."""
        t = float(get_env("MXNET_KVSTORE_OP_TIMEOUT", 120.0))
        if op == "barrier":
            t = max(t, float(get_env("MXNET_KVSTORE_BARRIER_TIMEOUT",
                                     600.0)))
        return t

    def _reconnect(self, timeout):
        """Drop the (possibly desynced) connection and dial a fresh one.
        Returns True on success; failure is left to the caller's retry
        budget — the server may still be restarting."""
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        try:
            self._sock = self._socket_mod.create_connection(
                (self._host, self._port), timeout=timeout)
        except OSError:
            return False
        _KV_RECONNECTS.inc()
        return True

    def _roundtrip(self, msg, trace_ctx):
        op = str(msg[0])
        health_ctx = None
        if _health.enabled:
            # piggyback this worker's latest step time on the wire header
            # (trace-context pattern) for the server's straggler table
            st = _health.monitor.last_step_seconds()
            if st is not None:
                health_ctx = {"r": str(self._rank), "st": float(st)}
        timeout = self._op_timeout(op)
        max_retries = int(get_env("MXNET_KVSTORE_MAX_RETRIES", 8))
        base = float(get_env("MXNET_KVSTORE_RETRY_BACKOFF", 0.05))
        with self._lock:
            seq_ctx = None
            if op in self._SEQ_OPS:
                self._seq += 1
                seq_ctx = {"r": self._seq_ident, "s": self._seq}
            last_err = None
            for attempt in range(max_retries + 1):
                if attempt:
                    _KV_RETRIES.labels(op=op).inc()
                    _time.sleep(backoff_delay(attempt - 1, base))
                if self._sock is None and not self._reconnect(timeout):
                    last_err = "parameter server unreachable"
                    continue
                try:
                    self._sock.settimeout(timeout)
                    # positional-compatible call when no context rides the
                    # frame: tests (and any wrapper) may substitute a
                    # two-argument send_msg
                    if trace_ctx or health_ctx or seq_ctx:
                        self._ps.send_msg(self._sock, msg,
                                          trace_ctx=trace_ctx,
                                          health_ctx=health_ctx,
                                          seq_ctx=seq_ctx)
                    else:
                        self._ps.send_msg(self._sock, msg)
                    reply = self._ps.recv_msg(self._sock)
                except self._socket_mod.timeout:
                    _KV_OP_TIMEOUTS.inc()
                    last_err = "no reply within %ss" % timeout
                    self._drop_connection(op, "timeout", attempt)
                    continue
                except OSError as e:
                    last_err = str(e) or type(e).__name__
                    self._drop_connection(op, "oserror", attempt)
                    continue
                except MXNetError as e:
                    # a corrupt/truncated REPLY frame: the stream may be
                    # desynced, so resync by reconnecting and replaying
                    last_err = str(e)
                    self._drop_connection(op, "bad_reply", attempt)
                    continue
                if reply is None:
                    # EOF mid-op: the server died (or a chaos drop ate the
                    # reply); the seq context makes the replay idempotent
                    last_err = "connection closed mid-op"
                    self._drop_connection(op, "eof", attempt)
                    continue
                if reply[0] == "err" and \
                        str(reply[1]).startswith("bad frame"):
                    # OUR frame arrived corrupted (chaos/flaky link); the
                    # server closes its end after this reply — replay
                    last_err = str(reply[1])
                    self._drop_connection(op, "bad_frame", attempt)
                    continue
                return reply
            raise MXNetError(
                "kvstore %s to %s:%d failed after %d attempts: %s"
                % (op, self._host, self._port, max_retries + 1, last_err))

    def _drop_connection(self, op, cause, attempt):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        try:
            from . import runlog as _runlog
            _runlog.event("kvstore_reconnect", worker_rank=str(self._rank),
                          op=op, cause=cause, attempt=int(attempt))
        except Exception:
            pass

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._num_workers

    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            v0 = v[0] if isinstance(v, (list, tuple)) else v
            self._rpc("init", k, v0.asnumpy())

    def push(self, key, value, priority=0):
        from .ndarray.sparse import RowSparseNDArray
        tel = _telemetry.enabled
        t0 = _time.perf_counter() if tel else 0.0
        keys, values = self._normalize(key, value)
        dense = []
        for k, v in zip(keys, values):
            agg = _local_sum(v)
            if isinstance(agg, RowSparseNDArray):
                if self._compression:
                    # reference contract: sparse + compression is an
                    # error, not a silent full-f32 fallback
                    raise MXNetError(
                        "gradient compression does not support "
                        "row_sparse push (key %r)" % k)
                # only touched rows cross the wire (reference
                # kvstore_dist.h:228-291 row-sparse push)
                ids = agg.indices.asnumpy().astype("int64")
                rows = agg.data.asnumpy()
                if tel:
                    _KV_BYTES_TX.labels(key=k).inc(ids.nbytes + rows.nbytes)
                self._rpc("push_rsp", k, ids, rows)
                continue
            if self._compression:
                # quantize with error feedback, then the PACKED 2-bit
                # form on the wire — 16 codes per uint32, 1/16th the f32
                # bytes (reference kvstore_dist.h:336-359, N13)
                q = self._compression.compress(k, agg._data)
                words = self._compression.pack(np.asarray(q))
                if tel:
                    _KV_BYTES_TX.labels(key=k).inc(words.nbytes)
                self._rpc("push_2bit", k, words,
                          self._compression.threshold)
                continue
            arr = np.ascontiguousarray(agg.asnumpy())
            if tel:
                _KV_BYTES_TX.labels(key=k).inc(arr.nbytes)
            dense.append((k, arr))
        bucketed = False
        for bucket in pack_buckets(dense, bucket_bytes()):
            if len(bucket) == 1:
                # singleton: unchanged per-key wire format
                self._rpc("push", bucket[0][0], bucket[0][1])
                continue
            bucketed = True
            bkeys = [k for k, _ in bucket]
            shapes = [list(a.shape) for _, a in bucket]
            flat = np.concatenate([a.ravel() for _, a in bucket])
            self._rpc("push_bucket", bkeys, shapes, flat)
        if tel:
            if dense:
                from .fused_step import STEP_DISPATCH
                STEP_DISPATCH.labels(
                    path="kvstore_bucketed" if bucketed
                    else "kvstore_perkey").inc()
            _KV_PUSH.labels(type=self.kind).inc(len(keys))
            _KV_PUSH_LAT.labels(type=self.kind).observe(
                _time.perf_counter() - t0)
            if _health.enabled:
                _health.monitor.note_phase(
                    "sync", _time.perf_counter() - t0)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        tel = _telemetry.enabled
        t0 = _time.perf_counter() if tel else 0.0
        from .ndarray.ndarray import array as _array
        keys, outs = self._normalize(key, out)
        # payload = (dsts, shape, dtype): the wire request carries shape +
        # dtype of the first destination; remaining dsts recast locally
        entries = []
        for k, dst in zip(keys, outs):
            dsts = list(dst) if isinstance(dst, (list, tuple)) else [dst]
            d0 = dsts[0]
            entries.append((k, (dsts, list(d0.shape), np.dtype(d0.dtype))))
        bucketed = False
        for bucket in pack_buckets(
                entries, bucket_bytes(),
                nbytes=lambda p: int(np.prod(p[1], dtype=np.int64))
                * p[2].itemsize,
                group=lambda p: p[2].str):
            if len(bucket) == 1:
                k, (dsts, _, _) = bucket[0]
                arr = self._rpc("pull", k)
                if tel:
                    _KV_BYTES_RX.labels(key=k).inc(
                        getattr(arr, "nbytes", 0))
                for d in dsts:
                    _array(arr, ctx=d.context, dtype=d.dtype).copyto(d)
                continue
            bucketed = True
            bkeys = [k for k, _ in bucket]
            shapes = [p[1] for _, p in bucket]
            dt = bucket[0][1][2]
            flat = np.asarray(self._rpc("pull_bucket", bkeys, shapes, dt.str))
            total = sum(int(np.prod(s, dtype=np.int64)) for s in shapes)
            if flat.ndim != 1 or flat.size != total:
                # malformed reply: count it as a frame error and refuse
                self._ps._frame_error(
                    "pull_bucket reply has %s values, expected %d"
                    % (getattr(flat, "size", None), total))
            off = 0
            for k, (dsts, shape, _) in bucket:
                n = int(np.prod(shape, dtype=np.int64))
                seg = flat[off:off + n].reshape(shape)
                off += n
                if tel:
                    _KV_BYTES_RX.labels(key=k).inc(seg.nbytes)
                for d in dsts:
                    _array(seg, ctx=d.context, dtype=d.dtype).copyto(d)
        if tel:
            if keys:
                from .fused_step import STEP_DISPATCH
                STEP_DISPATCH.labels(
                    path="kvstore_bucketed" if bucketed
                    else "kvstore_perkey").inc()
            _KV_PULL.labels(type=self.kind).inc(len(keys))
            _KV_PULL_LAT.labels(type=self.kind).observe(
                _time.perf_counter() - t0)
            if _health.enabled:
                _health.monitor.note_phase(
                    "sync", _time.perf_counter() - t0)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Fetch only the requested rows from the server (reference
        kvstore_dist.h row_sparse_pull -> kRowSparsePushPull)."""
        from .ndarray.sparse import RowSparseNDArray, row_sparse_array
        keys, outs = self._normalize(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        for k, o in zip(keys, outs):
            olist = o if isinstance(o, (list, tuple)) else [o]
            rlist = _broadcast_row_ids(rids, olist)
            for dst, rid in zip(olist, rlist):
                ids = np.unique(rid.asnumpy().astype("int64"))
                rows = self._rpc("pull_rows", k, ids)
                if _telemetry.enabled:
                    _KV_BYTES_RX.labels(key=k).inc(
                        getattr(rows, "nbytes", 0))
                if isinstance(dst, RowSparseNDArray):
                    row_sparse_array(
                        (rows, ids),
                        shape=(dst.shape[0],) + rows.shape[1:]).copyto(dst)
                else:
                    from .ndarray.ndarray import array as _array
                    full = nd.zeros(dst.shape, ctx=dst.context,
                                    dtype=dst.dtype)
                    full[_array(ids, dtype="int32")] = _array(
                        rows, ctx=dst.context, dtype=dst.dtype)
                    full.copyto(dst)

    def set_optimizer(self, optimizer):
        """Ship the pickled optimizer to the server (update_on_kvstore;
        the server keeps the first one it receives)."""
        self._rpc("set_optimizer", pickle.dumps(optimizer))

    def barrier(self):
        self._rpc("barrier")

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def send_command_to_servers(self, head, body):
        """kStopServer analog: head 0 stops the server (reference
        KVStore::SendCommandToServers)."""
        if int(head) == 0:
            self._rpc("stop")


def create(name="local") -> KVStore:
    """Factory (reference kvstore.cc:40-77 name dispatch).

    A process launched with ``DMLC_ROLE=server`` enters the parameter
    server loop here and exits when stopped (reference behavior: the same
    training script doubles as the server binary, kvstore_server.py:73).
    """
    name = name.lower()
    if name.startswith("dist") and os.environ.get("DMLC_ROLE") == "server":
        # server role precedes name dispatch: a server process must never
        # fall through into the worker rendezvous as a bogus participant
        from . import kvstore_server as _ps
        _ps.run_server()
        raise SystemExit(0)
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "local_allreduce_device", "device", "nccl"):
        return KVStore(name)
    if name == "dist_async":
        return DistAsyncKVStore(name)
    if name.startswith("dist"):
        return DistKVStore(name)
    raise MXNetError("unknown kvstore type %r" % name)
