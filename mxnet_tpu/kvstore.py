"""KVStore: data-parallel parameter/gradient communication façade.

Reference analog: ``include/mxnet/kvstore.h:47`` + ``src/kvstore/*``
(SURVEY.md N10-N13): ``local`` (CPU reduce), ``device`` (P2P GPU reduce
trees), ``nccl`` (collectives), ``dist_sync``/``dist_async`` (ps-lite
parameter server with optional server-side optimizer).

TPU-native design (SURVEY.md §5.8): single-process multi-device stores
(``local``/``device``/``nccl``) reduce over devices with XLA — a jitted
multi-device sum (the ICI all-reduce path once arrays live on a Mesh);
``dist_sync`` rides the multi-host JAX runtime (jax.distributed +
``parallel/``'s psum train steps) instead of a parameter server — rank/size
come from the JAX process group.  ``dist_async`` has no XLA analog
(documented: falls back to synchronous semantics).  The Python API
(init/push/pull/row_sparse_pull/set_optimizer/compression) is preserved.
"""
from __future__ import annotations

import pickle
from typing import Any, Dict, List, Optional, Union

from .base import MXNetError
from . import ndarray as nd
from .ndarray.ndarray import NDArray
from . import optimizer as opt

__all__ = ["KVStore", "create"]


def _key(k):
    return str(k)


class KVStore:
    """Single-process store: local/device/nccl (all XLA-reduced on TPU)."""

    def __init__(self, kind="local"):
        self.kind = kind
        self._store: Dict[str, NDArray] = {}
        self._updater = None
        self._optimizer = None
        self._compression = None

    # ---- core API -------------------------------------------------------
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError("key %r already initialized" % k)
            v0 = v[0] if isinstance(v, (list, tuple)) else v
            self._store[k] = v0.copy()

    def push(self, key, value, priority=0):
        from .ndarray.sparse import RowSparseNDArray, add as _sparse_add
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            vlist = v if isinstance(v, (list, tuple)) else [v]
            # reduce across devices: the CommDevice tree reduce of comm.h
            # becomes one XLA add chain (ICI all-reduce on a pod mesh)
            if all(isinstance(x, RowSparseNDArray) for x in vlist):
                agg = vlist[0]
                for x in vlist[1:]:
                    agg = _sparse_add(agg, x)
            else:
                agg = vlist[0]
                if len(vlist) > 1:
                    agg = vlist[0].tostype("default") \
                        if isinstance(vlist[0], RowSparseNDArray) \
                        else vlist[0].copy()
                    for x in vlist[1:]:
                        agg += x.as_in_context(agg.context)
            if self._updater is not None:
                self._updater(k, agg, self._store[k])
            else:
                # default updater is ASSIGN (reference kvstore docs): the
                # aggregate replaces the stored value, cast to its stype
                dst = self._store[k]
                if dst.stype != agg.stype:
                    from .ndarray.sparse import cast_storage
                    agg = cast_storage(agg, dst.stype)
                agg.copyto(dst)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        keys, outs = self._normalize(key, out)
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            src = self._store[k]
            olist = o if isinstance(o, (list, tuple)) else [o]
            for dst in olist:
                src.copyto(dst)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the requested rows (reference kvstore_local.h:109-247);
        dense-device TPU path gathers the rows; a RowSparseNDArray ``out``
        receives exactly the requested row set."""
        from .ndarray.sparse import (RowSparseNDArray, retain,
                                     row_sparse_array)
        import numpy as np
        keys, outs = self._normalize(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        for k, o in zip(keys, outs):
            src = self._store[k]
            olist = o if isinstance(o, (list, tuple)) else [o]
            rlist = rids if len(rids) == len(olist) else rids * len(olist)
            for dst, rid in zip(olist, rlist):
                if isinstance(dst, RowSparseNDArray):
                    if isinstance(src, RowSparseNDArray):
                        retain(src, rid).copyto(dst)
                    else:
                        ids = np.unique(rid.asnumpy().astype(np.int64))
                        rows = nd.take(src, nd.array(ids, dtype="int32"))
                        row_sparse_array((rows, ids),
                                         shape=src.shape).copyto(dst)
                    continue
                rows = nd.take(src, rid.astype("int32"))
                full = nd.zeros(src.shape, ctx=dst.context, dtype=src.dtype)
                idx = rid.astype("int32")
                full[idx] = rows.as_in_context(dst.context)
                full.copyto(dst)

    # ---- config ---------------------------------------------------------
    def set_optimizer(self, optimizer: opt.Optimizer):
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """2-bit gradient compression (reference N13).  On TPU intra-host
        reduction is exact; accepted for API parity, applied on the dist
        path (DCN) where bandwidth matters."""
        from .kvstore_compression import GradientCompression
        params = dict(compression_params)
        ctype = params.pop("type", "2bit")
        threshold = float(params.pop("threshold", 0.5))
        if params:
            raise MXNetError("unknown compression params %s" % list(params))
        self._compression = GradientCompression(type=ctype,
                                                threshold=threshold)

    @property
    def gradient_compression(self):
        return self._compression

    @property
    def type(self):
        return self.kind

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def barrier(self):
        nd.waitall()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("optimizer not set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer not set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    def _send_command_to_servers(self, head, body):
        pass

    # ---- helpers --------------------------------------------------------
    @staticmethod
    def _normalize(key, value):
        if isinstance(key, (list, tuple)):
            return [_key(k) for k in key], list(value)
        return [_key(key)], [value]


class DistKVStore(KVStore):
    """Multi-host store over the JAX distributed runtime (DCN).

    Reference: kvstore_dist.h worker + kvstore_dist_server.h (ps-lite).
    TPU-native: every host holds a replica; push performs a cross-process
    all-reduce via ``parallel.comm`` collectives (jax.distributed must be
    initialized — ``parallel.init_distributed()``); there are no separate
    server processes.  ``dist_async`` semantics (lock-free immediate apply)
    are approximated by synchronous all-reduce (documented deviation).
    """

    def __init__(self, kind="dist_sync"):
        super().__init__(kind)
        from . import parallel
        self._pg = parallel.process_group()

    def init(self, key, value):
        """Rank 0's value wins everywhere (reference semantics: worker 0
        initializes the parameter server, kvstore_dist.h InitImpl — other
        ranks' init values are discarded)."""
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError("key %r already initialized" % k)
            v0 = v[0] if isinstance(v, (list, tuple)) else v
            self._store[k] = self._pg.broadcast(v0.copy(), root=0)

    @property
    def rank(self):
        return self._pg.rank

    @property
    def num_workers(self):
        return self._pg.size

    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k not in self._store:
                raise MXNetError("key %r not initialized" % k)
            vlist = v if isinstance(v, (list, tuple)) else [v]
            agg = vlist[0]
            if len(vlist) > 1:
                agg = vlist[0].copy()
                for x in vlist[1:]:
                    agg += x.as_in_context(agg.context)
            if self._compression:
                # each worker ships its quantized gradient (2-bit + error
                # feedback, N13); summing dequantized streams across ranks
                # == the reference PS aggregating decompressed pushes
                agg = NDArray(self._compression.compress(k, agg._data),
                              agg.context)
            agg = self._pg.allreduce(agg)
            if self._updater is not None:
                self._updater(k, agg, self._store[k])
            else:
                # default updater is ASSIGN (reference kvstore docs): the
                # aggregate replaces the stored value
                agg.copyto(self._store[k])

    def barrier(self):
        self._pg.barrier()


def create(name="local") -> KVStore:
    """Factory (reference kvstore.cc:40-77 name dispatch)."""
    name = name.lower()
    if name in ("local", "local_update_cpu", "local_allreduce_cpu",
                "local_allreduce_device", "device", "nccl"):
        return KVStore(name)
    if name.startswith("dist"):
        return DistKVStore(name)
    raise MXNetError("unknown kvstore type %r" % name)
