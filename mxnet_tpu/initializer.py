"""Weight initializers (parity: python/mxnet/initializer.py — Zero/One/
Constant/Uniform/Normal/Orthogonal/Xavier/MSRAPrelu/Bilinear/LSTMBias/Mixed,
registry + InitDesc attribute protocol, initializer.py:377-678)."""
from __future__ import annotations

import json
import math
import re
from typing import Optional

import numpy as np

from .base import Registry, MXNetError
from . import ndarray as nd

__all__ = ["Initializer", "InitDesc", "Zero", "One", "Constant", "Uniform",
           "Normal", "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear",
           "LSTMBias", "FusedRNN", "Mixed", "Load", "register"]

_registry = Registry("initializer")


def register(klass, aliases=()):
    _registry.register(klass.__name__, klass, aliases=aliases)
    return klass


class InitDesc(str):
    """Name + attrs descriptor passed to initializers (ref InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        s = super().__new__(cls, name)
        s.attrs = attrs or {}
        s.global_init = global_init
        return s


class Initializer:
    """Base initializer; callable on (InitDesc/name, NDArray)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(str(desc))
        init_attr = desc.attrs.get("__init__", "")
        if init_attr:
            klass, kwargs = json.loads(init_attr) if init_attr.startswith("[") \
                else (init_attr, {})
            create(klass, **kwargs)._init_weight(desc, arr)
            return
        name = str(desc)
        if name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif "moving_mean" in name or "running_mean" in name:
            self._init_zero(desc, arr)
        elif ("moving_var" in name or "running_var" in name or
              "moving_inv_var" in name):
            self._init_one(desc, arr)
        else:
            self._init_default(desc, arr)

    def _init_weight(self, desc, arr):
        raise NotImplementedError

    def _init_bias(self, desc, arr):
        arr[:] = 0.0

    def _init_gamma(self, desc, arr):
        arr[:] = 1.0

    def _init_beta(self, desc, arr):
        arr[:] = 0.0

    def _init_zero(self, desc, arr):
        arr[:] = 0.0

    def _init_one(self, desc, arr):
        arr[:] = 1.0

    def _init_default(self, desc, arr):
        self._init_weight(desc, arr)


@register
class Zero(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 0.0


@register
class One(Initializer):
    def _init_weight(self, desc, arr):
        arr[:] = 1.0


register(Zero, aliases=("zeros",))
register(One, aliases=("ones",))


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, desc, arr):
        arr[:] = self.value


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, desc, arr):
        arr._data = nd.random.uniform(-self.scale, self.scale,
                                      shape=arr.shape)._data.astype(arr.dtype)


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, desc, arr):
        arr._data = nd.random.normal(0, self.sigma,
                                     shape=arr.shape)._data.astype(arr.dtype)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, desc, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1, 1, (nout, nin))
        else:
            tmp = np.random.normal(0, 1, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape).astype(np.float32)


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, desc, arr):
        shape = arr.shape
        hw_scale = float(np.prod(shape[2:])) if len(shape) > 2 else 1.0
        fan_in = shape[1] * hw_scale if len(shape) > 1 else shape[0]
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        else:
            factor = fan_out
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr._data = nd.random.uniform(
                -scale, scale, shape=shape)._data.astype(arr.dtype)
        else:
            arr._data = nd.random.normal(
                0, scale, shape=shape)._data.astype(arr.dtype)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (for UpSampling/Deconvolution)."""

    def _init_weight(self, desc, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype=np.float32)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)


@register
class LSTMBias(Initializer):
    """Forget-gate bias = custom value, rest 0 (ref initializer.py LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias
        arr[:] = b

    _init_default = _init_weight
    _init_bias = _init_weight


@register
class FusedRNN(Initializer):
    """Initialize the fused packed RNN parameter vector (ref
    initializer.py:FusedRNN): weights via ``init``, biases zero with the
    LSTM forget gate set to ``forget_bias`` (packed layout of
    ops/rnn.py: all [i2h_W, h2h_W] blocks, then all [i2h_b, h2h_b])."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            # reference parity (initializer.py FusedRNN.__init__): a string
            # init is the dumps() format '["klass", {kwargs}]', so
            # FusedRNN(Xavier().dumps(), ...) round-trips; a bare registry
            # name is accepted too
            if init.startswith("["):
                klass, kwargs = json.loads(init)
                init = create(klass, **kwargs)
            else:
                init = create(init)
        super().__init__(init=init.dumps() if init is not None else None,
                         num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        from .ops.rnn import _NGATES
        ng = _NGATES[self._mode]
        h = self._num_hidden
        L = self._num_layers
        dirs = 2 if self._bidirectional else 1
        total = arr.size
        n_bias = L * dirs * 2 * ng * h
        n_weight = total - n_bias
        # recover layer-0 input size from the packed length so each i2h/h2h
        # matrix can be initialized at its TRUE shape — the reference
        # (initializer.py FusedRNN via cell.unpack_weights) inits per
        # matrix; flat-vector init would give Xavier a bogus fan-in of the
        # whole packed size and near-zero recurrent weights
        deeper = (L - 1) * dirs * ng * h * (h * dirs + h)
        in0 = (n_weight - deeper) // (dirs * ng * h) - h
        flat = np.zeros(total, np.float32)
        if self._init is not None:
            from . import ndarray as nd
            off = 0
            for layer in range(L):
                in_sz = in0 if layer == 0 else h * dirs
                for _d in range(dirs):
                    for shape in ((ng * h, in_sz), (ng * h, h)):
                        blk = nd.zeros(shape)
                        self._init._init_weight(desc, blk)
                        flat[off:off + blk.size] = blk.asnumpy().ravel()
                        off += blk.size
            assert off == n_weight, (off, n_weight)
        if self._mode == "lstm":
            # bias region: per (layer, dir), [i2h_b, h2h_b] each ng*h long;
            # forget gate is gate index 1 of [i, f, g, o]
            bias = np.zeros(n_bias, np.float32)
            per = 2 * ng * h
            for blk in range(self._num_layers * dirs):
                for half in range(2):
                    off = blk * per + half * ng * h
                    bias[off + h:off + 2 * h] = self._forget_bias
            flat[n_weight:] = bias
        arr[:] = flat.reshape(arr.shape)

    _init_default = _init_weight


class Load:
    """Init from a dict of arrays, fall back to default (ref Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {k.replace("arg:", "").replace("aux:", ""): v
                      for k, v in param.items()}
        self.default_init = default_init

    def __call__(self, name, arr):
        name = str(name)
        if name in self.param:
            arr[:] = self.param[name]
        elif self.default_init is not None:
            self.default_init(name, arr)
        else:
            raise MXNetError("cannot init %r: not found and no default" % name)


@register
class Mixed:
    """Pattern-dispatch initializer (ref Mixed)."""

    def __init__(self, patterns, initializers):
        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for pat, init in self.map:
            if pat.match(str(name)):
                init(name, arr)
                return
        raise MXNetError("no initializer pattern matches %r" % name)


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    return _registry.get(name)(**kwargs)
