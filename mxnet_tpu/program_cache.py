"""Persistent compiled-program cache: restarts become a disk read.

Every process restart re-pays full XLA compilation today: serving warmup
compiles the whole bucket ladder, training recompiles the whole-step
program before step 1 — minutes of dead chip time per process at fleet
scale.  This module makes the (now sound — PRs 8/9) program cache key
*durable* by persisting compiled XLA executables on disk and loading
them on the next process's first call.

Design constraint (verified on this jax, see health.py): AOT
``lower().compile()`` objects do NOT share the jit call cache, so
serializing AOT executables cannot warm the call path.  Instead this
module hooks the **call-path compilation cache**: jax's
``compile_or_get_cached`` consults a pluggable persistent cache keyed by
the canonicalized HLO module + compile options + jax/jaxlib version +
device topology *before* invoking ``backend_compile``.  We install our
own :class:`CacheInterface` implementation there, so the exact trace the
call path builds — same donation, same shardings, same env-flag
formulation baked in by the sound cache-key contract — is the unit of
persistence, and a warm process reaches steady state with **zero** XLA
compiles.

Layered keying:

- **memory** tier: the in-process program caches (``Executor._jitted``,
  ``Operator._jit_cache``, ``CachedOp._jitted``) keyed by the sound
  contract — mesh_sig + ``STEP_ENV_KEYS`` + plan-wide op-env union.
- **disk** tier: jax's cache key (canonical HLO + compile options +
  jax/jaxlib version + devices).  The env flags are *baked into the
  traced HLO*, so a flag flip changes the traced program and therefore
  the disk key — stale programs cannot be served by construction.
- **environment fingerprint**: entries live under a
  ``fp-<digest>`` namespace directory derived from jax/jaxlib versions,
  backend platform, and device topology, and every entry embeds the
  digest.  An artifact shipped from a mismatched environment quarantines
  instead of deserializing.

Entry format (``*.mxpc``): ``b"MXPC1\\0"`` magic + 16-byte fingerprint
digest + 32-byte SHA-256 of the payload + payload (jax's compressed
``(executable, compile_time)`` blob).  Loads are checksum-validated;
any corruption (truncation, bit rot, foreign fingerprint) moves the file
to ``quarantine/``, counts ``program_cache_errors_total{kind}``, and
falls back to a fresh compile — a poisoned artifact can never take a
run down.

Activation: set ``MXNET_PROGRAM_CACHE_DIR`` (the compile sites call
:func:`ensure_enabled` lazily on their first miss) or call
:func:`enable` directly.  ``MXNET_PROGRAM_CACHE_MAX_BYTES`` (default
4 GiB) bounds the namespace with LRU eviction (mtime = recency, bumped
on every hit).  ``MXNET_PROGRAM_CACHE=0`` force-disables even when the
dir is set.  Deploy prefill: ``tools/cache_prefill.py`` compiles a
model's bucket ladder + training step into the cache dir once; ship the
directory with the model artifact and every replica restarts warm.
"""
from __future__ import annotations

import atexit
import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, Optional

from .base import get_env
from . import telemetry as _telemetry

__all__ = ["enable", "disable", "enabled", "ensure_enabled", "stats",
           "note_memory_hit", "fingerprint", "fingerprint_info",
           "cache_dir", "DiskProgramCache"]

ENV_DIR = "MXNET_PROGRAM_CACHE_DIR"
ENV_MAX_BYTES = "MXNET_PROGRAM_CACHE_MAX_BYTES"
ENV_GATE = "MXNET_PROGRAM_CACHE"

_MAGIC = b"MXPC1\0"
_FP_LEN = 16
_SHA_LEN = 32
_HEADER_LEN = len(_MAGIC) + _FP_LEN + _SHA_LEN
_SUFFIX = ".mxpc"
_QUARANTINE_DIR = "quarantine"
_QUARANTINE_CAP = 64

# Lookup tiers: `memory` = an in-process program-key lookup served from
# the live jit caches (per call site); `disk` / `miss` = an XLA compile
# request served from / missed by the persistent cache (per HLO module —
# one site miss can issue several).  The two granularities are
# documented in docs/observability.md.
_REQS = _telemetry.counter(
    "program_cache_requests_total",
    "Compiled-program lookups by serving tier (memory|disk|miss)",
    ("tier",))
# error paths count even with telemetry disabled (same convention as
# kvstore_frame_errors_total)
_ERRORS = _telemetry.counter(
    "program_cache_errors_total",
    "Cache artifacts rejected at load (truncated|magic|fingerprint|"
    "checksum|io) — rejected entries quarantine and recompile, never "
    "crash", ("kind",))
_EVICTIONS = _telemetry.counter(
    "program_cache_evictions_total",
    "Entries LRU-evicted to stay under MXNET_PROGRAM_CACHE_MAX_BYTES")
_COMPILES = _telemetry.counter(
    "program_cache_compiles_total",
    "Fresh XLA compiles persisted while the program cache was enabled "
    "(zero across a warm restart is the deploy-prefill contract)")
_BYTES = _telemetry.gauge(
    "program_cache_bytes", "Bytes in the program-cache namespace on disk")
_ENTRIES = _telemetry.gauge(
    "program_cache_entries", "Entries in the program-cache namespace")


def fingerprint_info() -> Dict[str, Any]:
    """Environment facts that must match for an executable to be safe to
    deserialize: jax/jaxlib versions, backend platform and version, and
    the device topology.  (The abstract arg signature and compile options
    are per-program and already part of jax's HLO cache key.)"""
    import jax
    info: Dict[str, Any] = {
        "jax": getattr(jax, "__version__", "?"),
        "jaxlib": "?",
        "platform": "?",
        "device_kind": "?",
        "n_devices": 0,
    }
    try:
        import jaxlib
        info["jaxlib"] = getattr(jaxlib, "version", jaxlib).__version__
    except Exception:
        pass
    try:
        devs = jax.devices()
        info["platform"] = devs[0].platform if devs else "none"
        info["device_kind"] = getattr(devs[0], "device_kind", "?") \
            if devs else "?"
        info["n_devices"] = len(devs)
        info["process_count"] = getattr(jax, "process_count", lambda: 1)()
    except Exception as e:  # backend init failed: still fingerprintable
        info["error"] = str(e)[:200]
    return info


def _digest_of(info: Dict[str, Any]) -> bytes:
    blob = json.dumps(info, sort_keys=True).encode()
    return hashlib.sha256(blob).digest()[:_FP_LEN]


def fingerprint() -> Optional[str]:
    """Hex fingerprint of the active cache namespace (None when
    disabled)."""
    c = _state.cache
    return c.fingerprint_hex if c is not None else None


def cache_dir() -> Optional[str]:
    """The active namespace directory (None when disabled)."""
    c = _state.cache
    return c.directory if c is not None else None


class DiskProgramCache:
    """Checksum-validated, LRU-capped on-disk executable cache.

    Implements jax's ``CacheInterface`` contract (``get(key)`` /
    ``put(key, value)``) so it can be installed as the persistent
    compilation cache consulted by ``compile_or_get_cached`` on the jit
    call path.  All failures degrade to a miss: the caller compiles
    fresh and training/serving continues.
    """

    def __init__(self, directory: str, fp_digest: bytes,
                 max_bytes: int) -> None:
        self.directory = directory
        self.fp_digest = fp_digest
        self.fingerprint_hex = fp_digest.hex()
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        # jax's CacheInterface exposes _path; keep parity for any caller
        # that introspects it
        self._path = directory
        self.stats: Dict[str, int] = {
            "disk_hits": 0, "misses": 0, "puts": 0, "errors": 0,
            "evictions": 0,
        }
        os.makedirs(os.path.join(directory, _QUARANTINE_DIR), exist_ok=True)
        self._refresh_usage_locked()

    # -- naming ------------------------------------------------------------
    def _entry_path(self, key: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in str(key))
        tag = hashlib.sha256(str(key).encode()).hexdigest()[:16]
        return os.path.join(self.directory,
                            "%s-%s%s" % (safe[:96], tag, _SUFFIX))

    def _entries_locked(self):
        """[(path, size, mtime)] for every live entry."""
        out = []
        try:
            with os.scandir(self.directory) as it:
                for de in it:
                    if not de.name.endswith(_SUFFIX) or not de.is_file():
                        continue
                    st = de.stat()
                    out.append((de.path, st.st_size, st.st_mtime))
        except OSError:
            pass
        return out

    def _refresh_usage_locked(self):
        entries = self._entries_locked()
        _BYTES.set(sum(e[1] for e in entries))
        _ENTRIES.set(len(entries))

    # -- error handling ----------------------------------------------------
    def _reject(self, path: str, kind: str) -> None:
        """Quarantine a bad artifact; never raises."""
        self.stats["errors"] += 1
        _ERRORS.labels(kind=kind).inc()
        qdir = os.path.join(self.directory, _QUARANTINE_DIR)
        try:
            os.makedirs(qdir, exist_ok=True)
            held = sorted(
                (de.path for de in os.scandir(qdir) if de.is_file()),
                key=lambda p: os.path.getmtime(p))
            for p in held[:max(0, len(held) - _QUARANTINE_CAP + 1)]:
                os.unlink(p)
            os.replace(path,
                       os.path.join(qdir, os.path.basename(path)))
        except OSError:
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- CacheInterface ----------------------------------------------------
    def get(self, key: str) -> Optional[bytes]:
        path = self._entry_path(key)
        with self._lock:
            try:
                with open(path, "rb") as f:
                    raw = f.read()
            except FileNotFoundError:
                self.stats["misses"] += 1
                _REQS.labels(tier="miss").inc()
                return None
            except OSError:
                self.stats["misses"] += 1
                _ERRORS.labels(kind="io").inc()
                self.stats["errors"] += 1
                _REQS.labels(tier="miss").inc()
                return None
            if len(raw) < _HEADER_LEN:
                self._reject(path, "truncated")
            elif not raw.startswith(_MAGIC):
                self._reject(path, "magic")
            elif raw[len(_MAGIC):len(_MAGIC) + _FP_LEN] != self.fp_digest:
                self._reject(path, "fingerprint")
            else:
                payload = raw[_HEADER_LEN:]
                want = raw[len(_MAGIC) + _FP_LEN:_HEADER_LEN]
                if hashlib.sha256(payload).digest() != want:
                    self._reject(path, "checksum")
                else:
                    self.stats["disk_hits"] += 1
                    _REQS.labels(tier="disk").inc()
                    try:
                        os.utime(path)  # LRU recency
                    except OSError:
                        pass
                    return payload
            self.stats["misses"] += 1
            _REQS.labels(tier="miss").inc()
            return None

    def put(self, key: str, value: bytes) -> None:
        path = self._entry_path(key)
        blob = (_MAGIC + self.fp_digest
                + hashlib.sha256(value).digest() + value)
        tmp = "%s.tmp.%d.%x" % (path, os.getpid(),
                                threading.get_ident() & 0xffff)
        with self._lock:
            try:
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)
            except OSError:
                self.stats["errors"] += 1
                _ERRORS.labels(kind="io").inc()
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return
            self.stats["puts"] += 1
            _COMPILES.inc()
            self._evict_locked()
            self._refresh_usage_locked()

    def _evict_locked(self) -> None:
        if self.max_bytes <= 0:
            return
        entries = self._entries_locked()
        total = sum(e[1] for e in entries)
        if total <= self.max_bytes:
            return
        for path, size, _mtime in sorted(entries, key=lambda e: e[2]):
            if total <= self.max_bytes:
                break
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= size
            self.stats["evictions"] += 1
            _EVICTIONS.inc()


# ---------------------------------------------------------------------------
# module state + jax call-path installation
# ---------------------------------------------------------------------------
class _State:
    def __init__(self) -> None:
        self.cache: Optional[DiskProgramCache] = None
        self.resolved = False          # env config read once
        self.mode: Optional[str] = None  # "native" | "config"
        self.root: Optional[str] = None
        self.info: Optional[Dict[str, Any]] = None
        self.memory_hits = 0
        self.atexit_registered = False


_state = _State()
_lock = threading.Lock()


def enabled() -> bool:
    return _state.cache is not None


def put_count() -> Optional[int]:
    """Fresh-compile (put) count so far, or None when disabled.  Cheap
    enough for per-first-call deltas: the op-jit wrapper compares it
    across a first invocation to label the trace span ``XLA::Compile``
    (a real compile happened) vs ``XLA::Restore`` (every program the
    call needed came off disk)."""
    c = _state.cache
    return c.stats["puts"] if c is not None else None


def note_memory_hit() -> None:
    """An in-process program-key lookup was served from a live jit cache
    (Executor._jitted / Operator._jit_cache / CachedOp._jitted).  Called
    from the compile sites on their hit path; gated by
    ``telemetry.enabled`` there, so steady state pays one attribute
    check."""
    _state.memory_hits += 1
    _REQS.labels(tier="memory").inc()


def ensure_enabled() -> bool:
    """Resolve the env config once and enable the cache if
    ``MXNET_PROGRAM_CACHE_DIR`` names a directory.  Called lazily from
    every whole-graph compile site on its miss path — i.e. right before
    jax is about to trace+compile, so touching the backend here is
    safe."""
    if _state.resolved:
        return _state.cache is not None
    with _lock:
        if _state.resolved:
            return _state.cache is not None
        root = os.environ.get(ENV_DIR)
        if not root or not get_env(ENV_GATE, True, bool):
            _state.resolved = True
            return False
    # enable() takes _lock itself and sets resolved
    return enable(root) is not None


def _install_into_jax(cache: DiskProgramCache, namespace: str) -> str:
    """Point jax's persistent compilation cache at ``cache``.

    Preferred ("native") mode replaces the module-level cache object in
    ``jax._src.compilation_cache`` so every ``compile_or_get_cached``
    lookup flows through our checksum/quarantine/LRU layer.  If those
    internals ever move, fall back to the public config knobs alone
    ("config" mode — jax's own LRUCache over the same namespace dir:
    still a working persistent cache, minus validation/telemetry).
    """
    import jax
    jax.config.update("jax_enable_compilation_cache", True)
    jax.config.update("jax_compilation_cache_dir", namespace)
    # persist everything: whole-step programs on CPU can compile in
    # <1s, and tiny glue programs (broadcasts, transfers) must load too
    # for the zero-compile contract to hold
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    try:
        from jax._src import compilation_cache as _cc
        with _cc._cache_initialized_mutex:
            _cc._cache = cache
            _cc._cache_initialized = True
            # re-evaluate the one-shot "is the cache used" verdict in
            # case compiles already happened before enable()
            _cc._cache_checked = False
            _cc._cache_used = False
        return "native"
    except Exception:
        return "config"


def _uninstall_from_jax() -> None:
    import jax
    try:
        from jax._src import compilation_cache as _cc
        with _cc._cache_initialized_mutex:
            _cc._cache = None
            _cc._cache_initialized = False
            _cc._cache_checked = False
            _cc._cache_used = False
    except Exception:
        pass
    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:
        pass


def enable(root: Optional[str] = None,
           max_bytes: Optional[int] = None) -> Optional[DiskProgramCache]:
    """Enable the persistent program cache under ``root`` (default
    ``MXNET_PROGRAM_CACHE_DIR``).  Idempotent: returns the live cache if
    already enabled.  Returns None when no directory is configured."""
    with _lock:
        if _state.cache is not None:
            _state.resolved = True
            return _state.cache
        root = root or os.environ.get(ENV_DIR)
        _state.resolved = True
        if not root:
            return None
        if max_bytes is None:
            max_bytes = get_env(ENV_MAX_BYTES, 4 * 1024 ** 3, int)
        info = fingerprint_info()
        digest = _digest_of(info)
        namespace = os.path.join(root, "fp-%s" % digest.hex())
        try:
            os.makedirs(namespace, exist_ok=True)
            manifest = os.path.join(namespace, "manifest.json")
            if not os.path.exists(manifest):
                tmp = manifest + ".tmp.%d" % os.getpid()
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump({"fingerprint": digest.hex(), "info": info,
                               "created": round(time.time(), 3)}, f,
                              indent=1, sort_keys=True)
                os.replace(tmp, manifest)
            cache = DiskProgramCache(namespace, digest, int(max_bytes))
        except OSError:
            # unusable directory: stay disabled rather than crash
            _ERRORS.labels(kind="io").inc()
            return None
        _state.mode = _install_into_jax(cache, namespace)
        _state.cache = cache
        _state.root = root
        _state.info = info
        if not _state.atexit_registered:
            _state.atexit_registered = True
            atexit.register(_log_summary)
    try:
        from . import runlog as _runlog
        _runlog.event("program_cache_start", dir=root,
                      namespace=namespace, fingerprint=digest.hex(),
                      mode=_state.mode, max_bytes=int(max_bytes),
                      info=info)
    except Exception:
        pass
    return _state.cache


def disable() -> None:
    """Detach from jax and drop the cache object (artifacts stay on
    disk).  Idempotent; also resets the env resolution so a later
    :func:`ensure_enabled` re-reads the environment (test isolation)."""
    with _lock:
        if _state.cache is None:
            _state.resolved = False
            _state.memory_hits = 0
            return
        _log_summary()
        _uninstall_from_jax()
        _state.cache = None
        _state.mode = None
        _state.root = None
        _state.info = None
        _state.resolved = False
        _state.memory_hits = 0


def stats() -> Dict[str, Any]:
    """JSON-able cache stats block (served on /statusz, logged by the
    runlog shutdown hook, embedded in bench results)."""
    c = _state.cache
    out: Dict[str, Any] = {
        "enabled": c is not None,
        "memory_hits": _state.memory_hits,
    }
    if c is None:
        return out
    entries = []
    try:
        with os.scandir(c.directory) as it:
            entries = [de.stat().st_size for de in it
                       if de.name.endswith(_SUFFIX) and de.is_file()]
    except OSError:
        pass
    out.update(c.stats)
    out.update({
        "dir": _state.root, "namespace": c.directory,
        "fingerprint": c.fingerprint_hex, "mode": _state.mode,
        "max_bytes": c.max_bytes,
        "bytes": sum(entries), "entries": len(entries),
    })
    return out


def _log_summary() -> None:
    """Shutdown hook: durable hit/miss/evict summary in the run ledger."""
    if _state.cache is None:
        return
    try:
        from . import runlog as _runlog
        _runlog.event("program_cache_summary", **stats())
    except Exception:
        pass
