"""Continuous training health monitor.

The perf story so far (bench rounds r02-r06) lives offline in ``bench.py``:
MFU, phase breakdowns and scaling numbers are bench artifacts, invisible to
a production run that silently degrades.  This module turns them into
runtime signals built from three pieces:

**Program cost accounting** — every cached step/forward program registers
itself here right before its first invocation.  We lower it (trace only —
on this jax the AOT ``.compile()`` does NOT share the executable cache
with the normal call path, so compiling here would double every program's
XLA compile) and read ``Lowered.cost_analysis()`` for the FLOP count plus
the in/out avals for the HBM footprint: ``program_flops{program}``,
``program_hbm_bytes{program,kind=args|output}``.  ``MXNET_HEALTH_DEEP=1``
opts into a real AOT compile per registered program for XLA's
``memory_analysis()`` temp-buffer figure (``kind=temp``) — explicitly
paying one extra compile each.  The donation audit is runtime truth
rather than a compiler report: after a donated program's first execution
the call site hands back the donated inputs (:func:`audit_donation`) and
any buffer jax did NOT invalidate means XLA dropped the alias
(``program_donation_leaks_total`` — the r04 donation chain silently
broke).

**Step-phase attribution** — :class:`StepMonitor` stitches a per-step
ledger from the existing hooks: ``io.py`` prefetch waits feed the *input*
phase, KVStore push/pull latencies feed *sync*, and deltas of
``op_jit_cache_misses_total`` / ``op_compile_seconds`` feed *compile*.
Each dispatch-to-dispatch window is classified input-bound / compute-bound
/ compile-bound / sync-bound (``step_health_verdict{cause}``) and a live
``step_mfu_pct`` gauge is computed as measured step rate x registered
program FLOPs / per-platform peak — replacing the two hand-counted FLOP
models ``bench.py`` used to carry.

**Anomaly + straggler detection** — a rolling EWMA plus a MAD band over
step time; a debounced trip bumps ``health_anomalies_total{cause}`` and
dumps the flight recorder (PR 3) so the evidence window around the bad
step survives.  In dist mode each worker piggybacks ``{rank, step_seconds}``
on the KVStore wire header (same pattern as the trace context) and the
server aggregates ``worker_step_seconds{rank}`` plus a straggler verdict.

Everything is gated on the module attribute :data:`enabled` (default OFF;
``MXNET_HEALTH=1`` or :func:`enable` — which implies telemetry — turns it
on), so the disabled path stays a single attribute check and executor
builds in the test suite never pay the AOT lowering cost.
"""
from __future__ import annotations

import collections
import os
import threading
import time

from . import telemetry as _telemetry
from .base import get_env

__all__ = ["enabled", "enable", "disable", "peak_tflops", "achieved_tflops",
           "mfu_fraction", "mfu_impossible", "register_program",
           "audit_donation", "programs", "program_flops_total", "monitor",
           "workers", "statusz", "healthz", "StepMonitor", "WorkerTable",
           "CAUSES"]

#: single-attribute gate read by every hook site; default off.
enabled: bool = False

# -- metrics ----------------------------------------------------------------

_PROG_FLOPS = _telemetry.gauge(
    "program_flops",
    "XLA cost_analysis flops of a registered compiled program",
    ("program",))
_PROG_HBM = _telemetry.gauge(
    "program_hbm_bytes",
    "XLA memory_analysis footprint of a registered program by kind",
    ("program", "kind"))
_PROG_DONATED = _telemetry.gauge(
    "program_donated_bytes",
    "donated input bytes actually invalidated by the first execution",
    ("program",))
_DONATION_LEAKS = _telemetry.counter(
    "program_donation_leaks_total",
    "donated programs whose inputs all survived execution (alias dropped)",
    ("program",))
_MFU = _telemetry.gauge(
    "step_mfu_pct",
    "live model-flops-utilization: program flops / (step time * peak)")
_STEP_EWMA = _telemetry.gauge(
    "step_seconds_ewma",
    "exponentially weighted moving average of the step interval")
_VERDICT = _telemetry.gauge(
    "step_health_verdict",
    "1 on the cause currently attributed to the step window, 0 elsewhere",
    ("cause",))
_ANOMALIES = _telemetry.counter(
    "health_anomalies_total",
    "debounced step-time anomaly trips by attributed cause",
    ("cause",))
_WORKER_STEP = _telemetry.gauge(
    "worker_step_seconds",
    "per-worker step time aggregated by the KVStore server",
    ("rank",))
_STRAGGLER = _telemetry.gauge(
    "worker_straggler_verdict",
    "1 when this rank's step time exceeds the straggler band",
    ("rank",))

#: ``oom_risk`` is set by memwatch's pre-flight (not by the step-window
#: classifier); listing it here lets on_step zero it once the risky
#: program's window passes.
CAUSES = ("compute_bound", "input_bound", "sync_bound", "compile_bound",
          "oom_risk")

# -- peak FLOPS model (shared with bench.py) --------------------------------

# Per-platform dense peaks in TFLOP/s.  The tpu column is the v5e-class
# figure bench.py has used since r02; cpu is a dev-box ballpark that keeps
# the live gauge finite without pretending the host is a chip.  Override
# with MXNET_HEALTH_PEAK_TFLOPS (or bench's BENCH_PEAK_TFLOPS).
_PEAK_TFLOPS = {
    "tpu": {"bfloat16": 197.0, "float16": 197.0, "float32": 99.0},
    "gpu": {"bfloat16": 312.0, "float16": 312.0, "float32": 19.5},
    "cpu": {"bfloat16": 0.25, "float16": 0.25, "float32": 0.25},
}


def peak_tflops(dtype="bfloat16", platform=None):
    """Per-platform peak in TFLOP/s for ``dtype`` (env-overridable).

    ``platform=None`` keeps bench.py's historical convention: quote MFU
    against the tpu peak even when measuring on another backend (so CPU
    container numbers stay comparable across rounds)."""
    for key in ("MXNET_HEALTH_PEAK_TFLOPS", "BENCH_PEAK_TFLOPS"):
        raw = os.environ.get(key)
        if raw:
            return float(raw)
    table = _PEAK_TFLOPS.get(platform or "tpu", _PEAK_TFLOPS["tpu"])
    return table.get(str(dtype), table["float32"])


def achieved_tflops(rate, flops_per_item):
    """items/s x flops/item in TFLOP/s."""
    return float(rate) * float(flops_per_item) / 1e12


def mfu_fraction(rate, flops_per_item, peak):
    """Achieved / peak as a fraction (bench multiplies by 100 to report)."""
    if peak <= 0:
        return 0.0
    return achieved_tflops(rate, flops_per_item) / float(peak)


def mfu_impossible(mfu, platform):
    """The bench sanity check: >120% MFU on a real chip means the FLOP
    model or the clock is wrong.  CPU runs are exempt (their peak is a
    convention, not a measurement)."""
    return platform != "cpu" and float(mfu) > 1.2


def _platform():
    try:
        import jax
        return jax.default_backend()
    except Exception:
        return "cpu"


# -- program cost accounting ------------------------------------------------

class ProgramCost(object):
    """Cost snapshot of one registered program.

    ``temp_bytes`` is None unless deep mode compiled the program;
    ``donated_bytes`` / ``donation_leak`` are filled in by
    :func:`audit_donation` after the first execution.  ``env`` is the
    registering site's snapshot of the env flags in the program's cache
    key ({key: value-at-build}), so post-mortem dumps can tie a cached
    program back to the formulation flags that built it."""

    __slots__ = ("name", "flops", "arg_bytes", "out_bytes", "temp_bytes",
                 "donated_bytes", "donation_requested", "donation_leak",
                 "env")

    def __init__(self, name, flops, arg_bytes, out_bytes, temp_bytes,
                 donation_requested, env=None):
        self.name = name
        self.flops = flops
        self.arg_bytes = arg_bytes
        self.out_bytes = out_bytes
        self.temp_bytes = temp_bytes
        self.donated_bytes = None
        self.donation_requested = donation_requested
        self.donation_leak = False
        self.env = dict(env or {})

    def as_dict(self):
        return {"flops": self.flops, "arg_bytes": self.arg_bytes,
                "out_bytes": self.out_bytes, "temp_bytes": self.temp_bytes,
                "donated_bytes": self.donated_bytes,
                "donation_requested": self.donation_requested,
                "donation_leak": self.donation_leak,
                "env": self.env}


_programs = {}
_programs_lock = threading.Lock()


def _leaf_bytes(leaf):
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    try:
        import numpy as np
        return n * np.dtype(dtype).itemsize
    except Exception:
        return 0


def _tree_bytes(tree):
    import jax
    return sum(_leaf_bytes(leaf) for leaf in jax.tree_util.tree_leaves(tree))


def register_program(name, fn, args, kwargs=None, donated=False, env=None):
    """Analyze a jitted callable right before its first invocation.

    Lowering only (trace, no XLA compile — on this jax an AOT
    ``.compile()`` does not share the normal call path's executable cache,
    so it would compile every program twice): FLOPs come from
    ``Lowered.cost_analysis()``, argument/output bytes from the avals.
    With ``MXNET_HEALTH_DEEP=1`` the program IS additionally AOT-compiled
    for ``memory_analysis()`` temp bytes — one extra XLA compile each,
    opt-in.  ``env`` (a {cache-key env var: value} snapshot from the
    registering site) is stored on the cost record for post-mortem dumps.
    Returns the :class:`ProgramCost` or None (disabled, non-jitted fn, or
    any analysis failure — health must never break the training step).
    """
    if not enabled or not hasattr(fn, "lower"):
        return None
    try:
        lowered = fn.lower(*args, **(kwargs or {}))
        cost = lowered.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        flops = float((cost or {}).get("flops", 0.0) or 0.0)
        arg_b = _tree_bytes((args, kwargs or {}))
        out_b = _tree_bytes(getattr(lowered, "out_info", None))
        tmp_b = None
        if get_env("MXNET_HEALTH_DEEP", False, bool):
            mem = lowered.compile().memory_analysis()
            tmp_b = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
    except Exception:
        return None
    pc = ProgramCost(name, flops, arg_b, out_b, tmp_b, donated, env=env)
    with _programs_lock:
        _programs[name] = pc
    try:
        from . import atlas as _atlas
        _atlas.analyze(name, lowered, cost_flops=flops)
    except Exception:
        pass
    try:
        from . import runlog as _runlog
        if _runlog.enabled():
            _runlog.note_topology()  # jax is initialized by now
            digest = None
            try:
                from . import atlas as _atlas
                snap = _atlas.snapshot(top_k=1).get(name)
                if snap:
                    digest = {"coverage_pct": snap.get("coverage_pct"),
                              "n_scopes": snap.get("n_scopes"),
                              "n_instructions": snap.get("n_instructions")}
            except Exception:
                pass
            _runlog.event("program_registered", program=name, flops=flops,
                          arg_bytes=arg_b, out_bytes=out_b, temp_bytes=tmp_b,
                          donated=bool(donated), env=env, atlas=digest)
    except Exception:
        pass
    _PROG_FLOPS.labels(program=name).set(flops)
    _PROG_HBM.labels(program=name, kind="args").set(arg_b)
    _PROG_HBM.labels(program=name, kind="output").set(out_b)
    if tmp_b is not None:
        _PROG_HBM.labels(program=name, kind="temp").set(tmp_b)
    try:
        # OOM pre-flight: every registration site gets the projection for
        # free; memwatch gates itself and must never break registration.
        from . import memwatch as _memwatch
        _memwatch.preflight(pc)
    except Exception:
        pass
    return pc


def audit_donation(name, donated):
    """Runtime donation audit, called by the owning site right AFTER the
    program's first execution with the inputs it donated: jax invalidates
    donated buffers the executable actually aliased, so any survivor
    means XLA silently dropped the alias and HBM use doubled.  Returns
    (freed_bytes, leaked_bytes) or None when disabled."""
    if not enabled:
        return None
    try:
        import jax
        freed = leaked = 0
        for leaf in jax.tree_util.tree_leaves(donated):
            if not hasattr(leaf, "is_deleted"):
                continue
            nbytes = _leaf_bytes(leaf)
            if leaf.is_deleted():
                freed += nbytes
            else:
                leaked += nbytes
    except Exception:
        return None
    leak = bool(freed == 0 and leaked > 0)
    with _programs_lock:
        pc = _programs.get(name)
        if pc is not None:
            pc.donated_bytes = freed
            pc.donation_leak = leak
    _PROG_DONATED.labels(program=name).set(freed)
    if leak:
        _DONATION_LEAKS.labels(program=name).inc()
    return freed, leaked


def programs():
    """Snapshot of every registered program's cost record."""
    with _programs_lock:
        return dict(_programs)


def program_flops_total(names):
    """Summed flops of the named programs (unknown names contribute 0).

    ``names`` may be a single program name or a tuple — split paths
    (eager fwdbwd + update program) sum their pieces."""
    if names is None:
        return 0.0
    if isinstance(names, str):
        names = (names,)
    with _programs_lock:
        return float(sum(_programs[n].flops for n in names
                         if n in _programs))


# -- compile activity (deltas of the PR 3 compile observability metrics) ----

def _compile_totals():
    """(total jit-cache misses, total compile seconds) across every op."""
    misses = 0.0
    fam = _telemetry.registry().get("op_jit_cache_misses_total")
    if fam is not None:
        misses = sum(v for _, v in fam.samples())
    secs = 0.0
    fam = _telemetry.registry().get("op_compile_seconds")
    if fam is not None:
        secs = sum(v["sum"] for _, v in fam.samples())
    return misses, secs


# -- step monitor -----------------------------------------------------------

class StepMonitor(object):
    """Per-step ledger: phase attribution, live MFU, anomaly trips.

    ``on_step(program)`` is called once per optimization step at the
    dispatch site; the elapsed time since the previous dispatch is the step
    window.  ``note_phase`` accumulates input/sync wall time contributed by
    the io/kvstore hooks inside that window.
    """

    #: EWMA smoothing factor over step intervals.
    ALPHA = 0.15
    #: a phase owns the verdict once it exceeds this share of the window.
    SHARE_THRESHOLD = 0.3
    #: anomaly needs at least this many samples of history.
    WARMUP = 8

    def __init__(self):
        self._lock = threading.Lock()
        self.dtype = None  # MFU dtype; resolved per-platform when unset
        self.reset()

    def reset(self):
        with getattr(self, "_lock", threading.Lock()):
            self._last_dispatch = None
            self._ewma = None
            self._window = collections.deque(maxlen=64)
            self._input_s = 0.0
            self._sync_s = 0.0
            self._misses_seen, self._compile_seen = _compile_totals()
            self._last_trip = 0.0
            self._ledger = collections.deque(maxlen=128)
            self._last_dt = None
            self._cause = None
            self._mfu = None

    # -- hooks -------------------------------------------------------------

    def note_phase(self, phase, seconds):
        """Attribute ``seconds`` of the current window to ``phase``
        (``"input"`` or ``"sync"``)."""
        if not enabled:
            return
        with self._lock:
            if phase == "input":
                self._input_s += float(seconds)
            elif phase == "sync":
                self._sync_s += float(seconds)

    def on_step(self, program=None):
        """Mark one step dispatched; closes the previous window."""
        if not enabled:
            return
        now = time.perf_counter()
        with self._lock:
            last, self._last_dispatch = self._last_dispatch, now
        if last is None:
            return  # first dispatch: no window to attribute yet
        self.observe_step(now - last, program=program, now=now)

    def observe_step(self, dt, program=None, now=None):
        """Account one closed step window of length ``dt`` seconds.

        Split out from :meth:`on_step` so tests can inject synthetic
        windows (e.g. a 10x slow step) without sleeping."""
        if not enabled or dt <= 0:
            return
        now = time.perf_counter() if now is None else now
        misses, compile_s = _compile_totals()
        with self._lock:
            input_s, self._input_s = self._input_s, 0.0
            sync_s, self._sync_s = self._sync_s, 0.0
            miss_d = misses - self._misses_seen
            compile_d = compile_s - self._compile_seen
            self._misses_seen, self._compile_seen = misses, compile_s
            prior_ewma = self._ewma
            window = tuple(self._window)

        shares = {
            "input": min(1.0, input_s / dt),
            "sync": min(1.0, sync_s / dt),
            "compile": min(1.0, compile_d / dt) if miss_d > 0 else 0.0,
        }
        cause = "compute_bound"
        top = max(shares, key=shares.get)
        if shares[top] > self.SHARE_THRESHOLD:
            cause = top + "_bound"

        flops = program_flops_total(program)
        mfu = None
        if flops > 0:
            plat = _platform()
            dtype = self.dtype or ("bfloat16" if plat == "tpu"
                                   else "float32")
            peak = peak_tflops(dtype, platform=plat)
            if peak > 0:
                mfu = 100.0 * flops / (dt * peak * 1e12)
                _MFU.set(mfu)

        tripped = False
        if prior_ewma is not None and len(window) >= self.WARMUP:
            med = _median(window)
            mad = _median([abs(x - med) for x in window])
            k = get_env("MXNET_HEALTH_ANOMALY_K", 6.0, float)
            band = prior_ewma + k * 1.4826 * max(mad, 1e-9)
            debounce = get_env("MXNET_HEALTH_ANOMALY_DEBOUNCE", 5.0, float)
            if dt > band and dt > 2.0 * prior_ewma:
                with self._lock:
                    ok = now - self._last_trip >= debounce
                    if ok:
                        self._last_trip = now
                if ok:
                    tripped = True
                    _ANOMALIES.labels(cause=cause).inc()
                    self._flight_dump(dt, prior_ewma, cause, shares)

        ewma = dt if prior_ewma is None else (
            (1.0 - self.ALPHA) * prior_ewma + self.ALPHA * dt)
        _STEP_EWMA.set(ewma)
        for c in CAUSES:
            _VERDICT.labels(cause=c).set(1.0 if c == cause else 0.0)

        entry = {"unix_time": time.time(), "step_seconds": dt,
                 "cause": cause, "shares": shares, "mfu_pct": mfu,
                 "programs": list(program) if isinstance(program, tuple)
                 else program, "anomaly": tripped,
                 "compile_misses": miss_d}
        with self._lock:
            prev_cause = self._cause
            self._ewma = ewma
            self._window.append(dt)
            self._last_dt = dt
            self._cause = cause
            self._mfu = mfu
            self._ledger.append(entry)
        if cause != prev_cause:
            # durable record of every verdict transition (not every step:
            # the ledger is an event log, not a metrics store)
            try:
                from . import runlog as _runlog
                _runlog.event("health_verdict", cause=cause,
                              prev_cause=prev_cause, step_seconds=dt,
                              shares=shares, mfu_pct=mfu,
                              ewma_seconds=ewma)
            except Exception:
                pass

    def _flight_dump(self, dt, ewma, cause, shares):
        """Record the anomaly into the flight ring and dump it; evidence
        capture must never raise into the step."""
        try:
            from . import tracing as _tracing
            from . import profiler as _profiler
            end_us = _profiler._now_us()
            _tracing.flight.record(
                "Health::Anomaly", "health",
                end_us - dt * 1e6, end_us,
                args={"step_seconds": dt, "ewma_seconds": ewma,
                      "cause": cause, "shares": shares})
            dump_path = _tracing.flight.dump(reason="health_anomaly")
            try:
                from . import runlog as _runlog
                _runlog.event("anomaly", step_seconds=dt,
                              ewma_seconds=ewma, cause=cause,
                              shares=shares, flight_dump=dump_path)
            except Exception:
                pass
        except Exception:
            pass

    def drop_window(self):
        """Discard the open window (e.g. after a disabled span) so the next
        dispatch starts a fresh interval instead of attributing the gap."""
        with self._lock:
            self._last_dispatch = None

    # -- readers -----------------------------------------------------------

    def last_step_seconds(self):
        with self._lock:
            return self._last_dt

    def snapshot(self):
        with self._lock:
            return {"ewma_seconds": self._ewma,
                    "last_step_seconds": self._last_dt,
                    "cause": self._cause,
                    "mfu_pct": self._mfu,
                    "samples": len(self._window),
                    "ledger": list(self._ledger)[-16:]}


def _median(values):
    vals = sorted(values)
    n = len(vals)
    if not n:
        return 0.0
    mid = n // 2
    if n % 2:
        return vals[mid]
    return 0.5 * (vals[mid - 1] + vals[mid])


# -- per-worker straggler table (server side) -------------------------------

class WorkerTable(object):
    """KVStore-server aggregate of per-worker step times.

    Workers piggyback ``{"r": rank, "st": step_seconds}`` on the wire
    header (the trace-context pattern); the server records the latest
    report per rank and flags ranks beyond the straggler band."""

    #: a rank is a straggler past this multiple of the median (>= 2 ranks).
    BAND = 1.75

    def __init__(self):
        self._lock = threading.Lock()
        self._workers = {}
        self._flags = {}  # rank -> bool, for transition-edge ledger events

    def update(self, rank, step_seconds):
        rank = str(rank)
        step_seconds = float(step_seconds)
        with self._lock:
            self._workers[rank] = (step_seconds, time.time())
            snap = {r: s for r, (s, _) in self._workers.items()}
        _WORKER_STEP.labels(rank=rank).set(step_seconds)
        if len(snap) >= 2:
            med = _median(list(snap.values()))
            transitions = []
            with self._lock:
                for r, s in snap.items():
                    flag = bool(med > 0 and s > self.BAND * med)
                    if self._flags.get(r, False) != flag:
                        transitions.append((r, flag, s))
                    self._flags[r] = flag
            for r, s in snap.items():
                _STRAGGLER.labels(rank=r).set(
                    1.0 if (med > 0 and s > self.BAND * med) else 0.0)
            if transitions:
                try:
                    from . import runlog as _runlog
                    for r, flag, s in transitions:
                        _runlog.event("straggler", worker_rank=r,
                                      straggler=flag, step_seconds=s,
                                      median_seconds=med)
                except Exception:
                    pass

    def snapshot(self):
        with self._lock:
            table = {r: {"step_seconds": s, "unix_time": t}
                     for r, (s, t) in self._workers.items()}
        if len(table) >= 2:
            med = _median([v["step_seconds"] for v in table.values()])
            for v in table.values():
                v["straggler"] = bool(
                    med > 0 and v["step_seconds"] > self.BAND * med)
        return table

    def clear(self):
        with self._lock:
            self._workers.clear()
            self._flags.clear()


#: process-wide singletons driven by the hook sites.
monitor = StepMonitor()
workers = WorkerTable()


# -- /statusz ---------------------------------------------------------------

def statusz():
    """JSON-able health snapshot served by telemetry/export.py."""
    plat = _platform()
    dtype = monitor.dtype or ("bfloat16" if plat == "tpu" else "float32")
    from . import program_cache as _program_cache
    return {
        "enabled": enabled,
        "platform": plat,
        "peak_tflops": peak_tflops(dtype, platform=plat),
        "peak_dtype": dtype,
        "programs": {n: pc.as_dict() for n, pc in programs().items()},
        "step": monitor.snapshot(),
        "workers": workers.snapshot(),
        "program_cache": _program_cache.stats(),
    }


def healthz():
    """Process-level liveness/degradation verdict for scrape consumers
    (served on ``/healthz`` and bundled into ``/allz``).  ``degraded``
    when the step window is attributed to oom_risk or an anomaly tripped
    within the last 60 s; a reachable process is otherwise ``ok`` even
    with the health hooks off (liveness and health are different
    questions)."""
    snap = monitor.snapshot()
    causes = []
    if snap["cause"] == "oom_risk":
        causes.append("oom_risk")
    now = time.time()
    for entry in reversed(snap["ledger"]):
        if entry.get("anomaly") and now - entry.get("unix_time", 0.0) <= 60.0:
            causes.append("recent_anomaly")
            break
    return {"status": "degraded" if causes else "ok", "enabled": enabled,
            "causes": causes, "cause": snap["cause"],
            "mfu_pct": snap["mfu_pct"],
            "ewma_seconds": snap["ewma_seconds"]}


# -- gates ------------------------------------------------------------------

def enable():
    """Turn the health hooks on (implies telemetry — the signals are
    exported through the registry)."""
    global enabled
    _telemetry.enable()
    enabled = True
    # re-baseline compile counters so pre-enable compilation isn't
    # attributed to the first monitored window
    monitor._misses_seen, monitor._compile_seen = _compile_totals()


def disable():
    global enabled
    enabled = False


def reset():
    """Test isolation: drop program records, monitor state, worker table."""
    with _programs_lock:
        _programs.clear()
    monitor.reset()
    workers.clear()


if get_env("MXNET_HEALTH", False, bool):
    enable()
