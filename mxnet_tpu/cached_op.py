"""CachedOp: compiled execution of a symbolic subgraph for imperative calls.

Reference analog: ``src/imperative/cached_op.{h,cc}`` (graph caching keyed on
shapes/types, dynamic vs static modes) invoked through
``MXCreateCachedOpEx/MXInvokeCachedOpEx``.

TPU-native design: the subgraph is compiled WHOLE by XLA — ``jax.jit`` over
the symbol's execution plan (see :class:`mxnet_tpu.executor._Plan`), cached per
(train-mode, differentiable-input-set); XLA's shape-keyed executable cache
replaces the reference's shape-keyed graph cache.  The backward pass is a
single fused forward+vjp XLA program (rematerialization: trades FLOPs for HBM,
the TPU analog of ``MXNET_BACKWARD_DO_MIRROR``), recorded on the autograd tape
like any other op.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError
from . import autograd as _autograd
from . import random as _random
from . import telemetry as _telemetry
from . import program_cache as _program_cache
from .executor import _Plan

__all__ = ["CachedOp"]


class CachedOp:
    """A compiled callable over a Symbol graph (parity: mx.nd.CachedOp)."""

    def __init__(self, sym, flags=()):
        self._sym = sym
        self._flags = dict(flags) if flags else {}
        self.input_names = sym.list_inputs()
        self.n_outputs = len(sym.list_outputs())
        self._plans: Dict[bool, _Plan] = {}
        self._jitted: Dict[Tuple, object] = {}

    def _plan(self, train: bool) -> _Plan:
        if train not in self._plans:
            self._plans[train] = _Plan(self._sym, train)
        return self._plans[train]

    def _keys(self, plan: _Plan):
        if plan.n_rng == 0:
            return jnp.zeros((0, 2), np.uint32)
        return jnp.stack([_random.next_key() for _ in range(plan.n_rng)])

    @staticmethod
    def _plan_env(plan: _Plan):
        # op env flags are baked into the whole-graph trace (same contract
        # as executor.Executor._plan_env_of): join them to the program key
        import os
        return tuple(os.environ.get(k) for k in plan.env_keys)

    def _fwd(self, train: bool):
        plan = self._plan(train)
        key = ("fwd", train) + self._plan_env(plan)
        if key not in self._jitted:
            _program_cache.ensure_enabled()
            arg_names, aux_names = plan.arg_names, plan.aux_names

            def fn(arg_list, aux_list, keys):
                outs, new_aux = plan.execute(
                    dict(zip(arg_names, arg_list)),
                    dict(zip(aux_names, aux_list)), keys)
                return outs, [new_aux[n] for n in aux_names]

            self._jitted[key] = jax.jit(fn)
        elif _telemetry.enabled:
            _program_cache.note_memory_hit()
        return self._jitted[key]

    def _bwd(self, train: bool, diff_idx: Tuple[int, ...]):
        """Fused recompute-forward + vjp program for the given diff inputs."""
        plan = self._plan(train)
        key = ("bwd", train, diff_idx) + self._plan_env(plan)
        if key not in self._jitted:
            _program_cache.ensure_enabled()
            arg_names, aux_names = plan.arg_names, plan.aux_names
            diff_names = [arg_names[i] for i in diff_idx]

            def fn(arg_list, aux_list, keys, ograds):
                base = dict(zip(arg_names, arg_list))

                def pure(*gvals):
                    av = dict(base)
                    av.update(dict(zip(diff_names, gvals)))
                    outs, _ = plan.execute(
                        av, dict(zip(aux_names, aux_list)), keys)
                    return outs

                outs, vjp = jax.vjp(pure, *[base[n] for n in diff_names])
                # head gradients may arrive in a different dtype than the
                # recorded outputs (e.g. an f32 loss on a bf16 net) — vjp
                # requires exact cotangent dtypes
                cots = [jnp.asarray(g, o.dtype)
                        for g, o in zip(ograds, outs)]
                return list(vjp(cots))

            self._jitted[key] = jax.jit(fn)
        elif _telemetry.enabled:
            _program_cache.note_memory_hit()
        return self._jitted[key]

    def __call__(self, *args):
        """Execute on NDArrays given in ``self.input_names`` order."""
        from .ndarray.ndarray import NDArray
        if len(args) != len(self.input_names):
            raise MXNetError(
                "CachedOp expects %d inputs (%s), got %d" % (
                    len(self.input_names), self.input_names, len(args)))
        train = _autograd.is_training()
        recording = _autograd.is_recording()
        plan = self._plan(train)
        by_name = dict(zip(self.input_names, args))
        arg_arrays = [by_name[n] for n in plan.arg_names]
        aux_arrays = [by_name[n] for n in plan.aux_names]
        arg_vals = [a._data for a in arg_arrays]
        aux_vals = [a._data for a in aux_arrays]
        keys = self._keys(plan)

        outs, new_aux = self._fwd(train)(arg_vals, aux_vals, keys)
        if train:
            for dst, v in zip(aux_arrays, new_aux):
                dst._data = v
        ctx = args[0].context if args else None
        out_arrays = [NDArray(o, ctx) for o in outs]

        if recording:
            diff_idx = tuple(
                i for i, a in enumerate(arg_arrays)
                if getattr(a, "_ag_entry", None) is not None
                or getattr(a, "_ag_leaf", False))
            if diff_idx:
                bwd = self._bwd(train, diff_idx)

                def vjp_fn(cots, _arg_vals=arg_vals, _aux_vals=aux_vals,
                           _keys=keys):
                    ogs = [c if c is not None else jnp.zeros(o.shape, o.dtype)
                           for c, o in zip(cots, outs)]
                    return bwd(_arg_vals, _aux_vals, _keys, ogs)

                _autograd.record_op(
                    "CachedOp", vjp_fn,
                    [arg_arrays[i] for i in diff_idx], out_arrays)
        return out_arrays if len(out_arrays) > 1 else out_arrays[0]
