"""Monitor: per-node tensor statistics during execution.

Reference analog: ``python/mxnet/monitor.py:33`` — installs an executor
monitor callback (``GraphExecutor::SetMonitorCallback``,
graph_executor.cc:123) invoked per node output in ``RunOps``; collects a
user stat function of every intermediate tensor between ``tic()`` and
``toc()``.
"""
from __future__ import annotations

import re
from math import sqrt

from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    """Collect per-node output statistics every ``interval`` batches
    (parity: monitor.py:33)."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False,
                 monitor_all=False):
        if stat_func is None:
            def stat_func(x):
                return x.abs().mean() if hasattr(x, "abs") else abs(x).mean()
        self.interval = interval
        self.stat_func = stat_func
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.monitor_all = monitor_all
        self.activated = False
        self.step = 0
        self.queue = []
        self.exes = []

    def install(self, exe):
        """Attach to an executor (reference install_executor)."""
        exe.set_monitor_callback(self._stat_helper, self.monitor_all)
        self.exes.append(exe)

    install_executor = install

    def _stat_helper(self, name, arr):
        if not self.activated or not self.re_pattern.match(name):
            return
        self.queue.append((self.step, name, self.stat_func(arr)))

    def tic(self):
        """Start collecting for this batch if the interval has elapsed."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Stop collecting; returns [(step, name, stat), ...]."""
        if not self.activated:
            return []
        self.activated = False
        res = []
        for n, k, v_ in self.queue:
            if isinstance(v_, NDArray):
                v_ = v_.asnumpy()
            res.append((n, k, v_))
        self.queue = []
        if self.sort:
            res.sort(key=lambda x: x[1])
        return res

    def toc_print(self):
        for n, k, v in self.toc():
            print("Batch: %7d %30s %s" % (n, k, v))
