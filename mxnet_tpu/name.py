"""Name manager (parity: python/mxnet/name.py NameManager/Prefix)."""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current_scope"]

_local = threading.local()


class NameManager:
    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name, hint):
        if name:
            return name
        i = self._counter.get(hint, 0)
        self._counter[hint] = i + 1
        return "%s%d" % (hint, i)

    def __enter__(self):
        self._old = getattr(_local, "scope", None)
        _local.scope = self
        return self

    def __exit__(self, *exc):
        _local.scope = self._old


class Prefix(NameManager):
    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return name if name else self._prefix + super().get(None, hint)


def current_scope():
    return getattr(_local, "scope", None)
