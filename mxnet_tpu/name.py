"""Name manager (parity: python/mxnet/name.py NameManager/Prefix)."""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current_scope"]

_local = threading.local()


class NameManager:
    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name, hint):
        if name:
            return name
        i = self._counter.get(hint, 0)
        self._counter[hint] = i + 1
        return "%s%d" % (hint, i)

    def __enter__(self):
        self._old = getattr(_local, "scope", None)
        _local.scope = self
        return self

    def __exit__(self, *exc):
        _local.scope = self._old


class Prefix(NameManager):
    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        # reference Prefix prepends even to explicit names
        # (python/mxnet/name.py Prefix.get)
        return self._prefix + (name if name else super().get(None, hint))


def current_scope():
    """Current NameManager, falling back to a per-thread default whose
    counters persist (parity: python/mxnet/name.py NameManager.current)."""
    scope = getattr(_local, "scope", None)
    if scope is None:
        scope = getattr(_local, "default", None)
        if scope is None:
            scope = NameManager()
            _local.default = scope
    return scope
