"""Network visualization (parity: ``python/mxnet/visualization.py``):
``print_summary`` textual table and ``plot_network`` graphviz rendering."""
from __future__ import annotations

import json

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a layer-by-layer summary table of a symbol
    (parity: visualization.py print_summary)."""
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    if shape is not None:
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shape)
        arg_dict = dict(zip(symbol.list_arguments(), arg_shapes))
    else:
        arg_dict = {}
    positions = [int(line_length * p) for p in positions]
    headers = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(fields):
        line = ""
        for f, pos in zip(fields, positions):
            line = (line + str(f))[:pos - 1].ljust(pos)
        print(line)

    print("_" * line_length)
    print_row(headers)
    print("=" * line_length)

    total_params = 0
    # walk the graph in topo order
    out_shape_of = {}
    if shape is not None:
        internals = symbol.get_internals()
        onames = internals.list_outputs()
        try:
            _, ishapes, _ = internals.infer_shape(**shape)
            out_shape_of = dict(zip(onames, ishapes))
        except MXNetError:
            pass
    for node in symbol._topo():
        if node.is_var:
            continue
        name = node.name
        op_name = node.op.name
        oshape = out_shape_of.get(name + "_output", "")
        params = 0
        prevs = []
        for pnode, _ in node.inputs:
            if pnode.is_var:
                if pnode.name in arg_dict and pnode.name != "data":
                    n = 1
                    for d in arg_dict[pnode.name]:
                        n *= d
                    params += n
            else:
                prevs.append(pnode.name)
        total_params += params
        print_row(["%s (%s)" % (name, op_name), oshape, params,
                   ",".join(prevs)])
    print("=" * line_length)
    print("Total params: %d" % total_params)
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz Digraph of the symbol (parity: visualization.py
    plot_network).  Requires the optional ``graphviz`` package."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError("plot_network requires the graphviz python "
                          "package") from e
    node_attrs = node_attrs or {}
    dot = Digraph(name=title, format=save_format)
    base_attrs = {"shape": "box", "fixedsize": "false", "style": "filled"}
    base_attrs.update(node_attrs)
    palette = {"Convolution": "#fb8072", "FullyConnected": "#fb8072",
               "Activation": "#ffffb3", "BatchNorm": "#bebada",
               "Pooling": "#80b1d3", "SoftmaxOutput": "#fccde5"}
    seen = set()
    for node in symbol._topo():
        name = node.name
        if node.is_var:
            if hide_weights and (name.endswith("_weight") or
                                 name.endswith("_bias") or
                                 name.endswith("_gamma") or
                                 name.endswith("_beta")):
                continue
            dot.node(name, name, {**base_attrs, "fillcolor": "#8dd3c7",
                                  "shape": "oval"})
        else:
            color = palette.get(node.op.name, "#b3de69")
            dot.node(name, "%s\n%s" % (name, node.op.name),
                     {**base_attrs, "fillcolor": color})
        seen.add(name)
        for pnode, _ in node.inputs:
            # parents precede their consumers in topo order, so every
            # drawn parent is already in `seen`; hidden weight vars are not
            if pnode.name in seen:
                dot.edge(pnode.name, name)
    return dot
