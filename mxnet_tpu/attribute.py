"""Attribute scope (parity: python/mxnet/attribute.py AttrScope): attaches
default attrs (e.g. ctx_group for coarse model parallelism, __lr_mult__) to
symbols created inside the scope."""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current_attrs"]

_local = threading.local()


class AttrScope:
    def __init__(self, **kwargs):
        self._attr = {k: str(v) for k, v in kwargs.items()}
        self._old = None

    def get(self, attr):
        out = dict(self._attr)
        if attr:
            out.update(attr)
        return out

    def __enter__(self):
        self._old = getattr(_local, "scope", None)
        if self._old is not None:
            merged = dict(self._old._attr)
            merged.update(self._attr)
            self._attr = merged
        _local.scope = self
        return self

    def __exit__(self, *exc):
        _local.scope = self._old


def current_attrs():
    scope = getattr(_local, "scope", None)
    return dict(scope._attr) if scope is not None else {}
