"""TensorBoard logging (parity: ``python/mxnet/contrib/tensorboard.py``).

The reference's ``LogMetricsCallback`` wraps the external ``tensorboard``
package's SummaryWriter.  Zero-dependency here: event files are written
directly — Event/Summary protos via the same hand-rolled protobuf codec
used for ONNX (:mod:`mxnet_tpu.contrib.onnx_proto`), framed in the
TFRecord format (length + masked CRC32C) that TensorBoard reads.  Scalars
and histograms are supported — the two summary kinds the reference
callback emits.
"""
from __future__ import annotations

import os
import struct
import time

import numpy as np

from .onnx_proto import Message

__all__ = ["SummaryWriter", "LogMetricsCallback"]


# ---------------------------------------------------------------------------
# CRC32C (Castagnoli), required by the TFRecord framing
# ---------------------------------------------------------------------------

def _make_crc_table():
    poly = 0x82F63B78
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_CRC_TABLE = _make_crc_table()


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# tensorflow Event/Summary proto subset (field numbers from
# tensorflow/core/util/event.proto and framework/summary.proto)
# ---------------------------------------------------------------------------

class HistogramProto(Message):
    pass


HistogramProto.FIELDS = {
    1: ("min", "double", False),
    2: ("max", "double", False),
    3: ("num", "double", False),
    4: ("sum", "double", False),
    5: ("sum_squares", "double", False),
    6: ("bucket_limit", "double", True),
    7: ("bucket", "double", True),
}


class SummaryValue(Message):
    pass


SummaryValue.FIELDS = {
    1: ("tag", "string", False),
    2: ("simple_value", "float", False),
    5: ("histo", HistogramProto, False),
}


class Summary(Message):
    pass


Summary.FIELDS = {
    1: ("value", SummaryValue, True),
}


class Event(Message):
    pass


Event.FIELDS = {
    1: ("wall_time", "double", False),
    2: ("step", "int", False),
    3: ("file_version", "string", False),
    5: ("summary", Summary, False),
}


class SummaryWriter:
    """Minimal event-file writer with the tensorboardX API subset the
    reference callback uses (add_scalar/add_histogram/flush/close)."""

    _seq = 0

    def __init__(self, logdir):
        os.makedirs(logdir, exist_ok=True)
        # pid + per-process counter uniquify concurrent writers in one
        # logdir (tensorboardX embeds hostname+pid for the same reason)
        SummaryWriter._seq += 1
        fname = "events.out.tfevents.%d.%d.%d.mxnet_tpu" % (
            int(time.time()), os.getpid(), SummaryWriter._seq)
        self._path = os.path.join(logdir, fname)
        self._f = open(self._path, "wb")
        self._write_event(Event(wall_time=time.time(),
                                file_version="brain.Event:2"))

    def _write_event(self, event: Event):
        payload = event.serialize()
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))

    def add_scalar(self, tag, value, global_step=0):
        self._write_event(Event(
            wall_time=time.time(), step=int(global_step),
            summary=Summary(value=[SummaryValue(
                tag=str(tag), simple_value=float(value))])))

    def add_histogram(self, tag, values, global_step=0, bins=30):
        arr = np.asarray(
            values.asnumpy() if hasattr(values, "asnumpy") else values,
            np.float64).ravel()
        counts, edges = np.histogram(arr, bins=bins)
        histo = HistogramProto(
            min=float(arr.min()), max=float(arr.max()),
            num=float(arr.size), sum=float(arr.sum()),
            sum_squares=float((arr * arr).sum()),
            bucket_limit=[float(e) for e in edges[1:]],
            bucket=[float(c) for c in counts])
        self._write_event(Event(
            wall_time=time.time(), step=int(global_step),
            summary=Summary(value=[SummaryValue(tag=str(tag),
                                                histo=histo)])))

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()


class LogMetricsCallback:
    """Batch-end callback streaming metric values to TensorBoard
    (parity: contrib.tensorboard.LogMetricsCallback)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self._writer = SummaryWriter(logging_dir)
        self._step = 0

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self._step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self._writer.add_scalar(name, value, self._step)
        self._writer.flush()
