"""Text utilities: vocabulary + token embeddings.

Reference analog: ``python/mxnet/contrib/text/`` (vocab.py Vocabulary,
embedding.py TokenEmbedding/CustomEmbedding, utils.py count_tokens_from_str)
— SURVEY.md §2.3 contrib.  Pre-trained downloads are out of scope (no
egress); ``CustomEmbedding`` loads any GloVe/word2vec-style text file.
"""
from __future__ import annotations

import collections
import re
from typing import Dict, List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["Vocabulary", "CustomEmbedding", "count_tokens_from_str",
           "get_pretrained_file_names"]


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Token frequency counter (reference utils.count_tokens_from_str)."""
    if to_lower:
        source_str = source_str.lower()
    tokens = [t for t in re.split(
        "[%s%s]" % (re.escape(token_delim), re.escape(seq_delim)),
        source_str) if t]
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    counter.update(tokens)
    return counter


class Vocabulary:
    """Indexed vocabulary (reference vocab.py Vocabulary): tokens ordered by
    descending frequency; index 0 is the unknown token; optional reserved
    tokens follow it."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise MXNetError("min_freq must be >= 1")
        reserved_tokens = list(reserved_tokens or [])
        if unknown_token in reserved_tokens:
            raise MXNetError("unknown token must not be reserved")
        if len(set(reserved_tokens)) != len(reserved_tokens):
            raise MXNetError("reserved tokens must be unique")
        self._unknown_token = unknown_token
        self._reserved_tokens = reserved_tokens or None
        self._idx_to_token = [unknown_token] + reserved_tokens
        self._token_to_idx = {t: i for i, t in enumerate(self._idx_to_token)}
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1], kv[0]))
            if most_freq_count is not None:
                pairs = pairs[:most_freq_count]
            for tok, freq in pairs:
                if freq < min_freq or tok in self._token_to_idx:
                    continue
                self._token_to_idx[tok] = len(self._idx_to_token)
                self._idx_to_token.append(tok)

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self) -> Dict[str, int]:
        return self._token_to_idx

    @property
    def idx_to_token(self) -> List[str]:
        return self._idx_to_token

    @property
    def unknown_token(self):
        return self._unknown_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        """Tokens -> indices; unknown tokens map to index 0
        (reference to_indices)."""
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idx = [self._token_to_idx.get(t, 0) for t in toks]
        return idx[0] if single else idx

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self._idx_to_token):
                raise MXNetError("token index %d out of range" % i)
        toks = [self._idx_to_token[i] for i in idxs]
        return toks[0] if single else toks


class CustomEmbedding:
    """Token embedding from a GloVe/word2vec-style text file
    (reference embedding.py CustomEmbedding): each line
    ``token v1 v2 ... vD``; unknown tokens get ``init_unknown_vec``."""

    def __init__(self, pretrained_file_path=None, elem_delim=" ",
                 encoding="utf8", vocabulary=None, init_unknown_vec=None,
                 vec_len=None, tokens_with_vecs=None):
        from .. import ndarray as nd
        vectors: Dict[str, np.ndarray] = {}
        if pretrained_file_path is not None:
            with open(pretrained_file_path, encoding=encoding) as f:
                for line in f:
                    parts = line.rstrip().split(elem_delim)
                    if len(parts) < 2:
                        continue
                    vec = np.asarray([float(x) for x in parts[1:]],
                                     np.float32)
                    if vec_len is None:
                        vec_len = len(vec)
                    elif len(vec) != vec_len:
                        raise MXNetError(
                            "inconsistent embedding dim at token %r"
                            % parts[0])
                    vectors[parts[0]] = vec
        if tokens_with_vecs:
            for tok, vec in tokens_with_vecs.items():
                vec = np.asarray(vec, np.float32)
                vec_len = vec_len or len(vec)
                vectors[tok] = vec
        if vec_len is None:
            raise MXNetError("no embedding vectors given")
        self.vec_len = vec_len
        if vocabulary is None:
            vocabulary = Vocabulary(
                collections.Counter({t: 1 for t in vectors}))
        self._vocab = vocabulary
        init = init_unknown_vec or (lambda shape: np.zeros(shape,
                                                           np.float32))
        table = np.stack([
            vectors.get(tok, np.asarray(init((vec_len,)), np.float32))
            for tok in vocabulary.idx_to_token])
        self._idx_to_vec = nd.array(table)

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    @property
    def token_to_idx(self):
        return self._vocab.token_to_idx

    @property
    def idx_to_token(self):
        return self._vocab.idx_to_token

    def __len__(self):
        return len(self._vocab)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        from .. import ndarray as nd
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idxs = []
        for t in toks:
            i = self.token_to_idx.get(t)
            if i is None and lower_case_backup:
                i = self.token_to_idx.get(t.lower())
            idxs.append(0 if i is None else i)
        vecs = nd.take(self._idx_to_vec, nd.array(idxs, dtype="int32"))
        return vecs[0] if single else vecs

    def update_token_vectors(self, tokens, new_vectors):
        from .. import ndarray as nd
        toks = [tokens] if isinstance(tokens, str) else tokens
        arr = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else np.asarray(new_vectors, np.float32)
        arr = arr.reshape(len(toks), self.vec_len)
        table = np.array(self._idx_to_vec.asnumpy())  # writable copy
        for t, v in zip(toks, arr):
            if t not in self.token_to_idx:
                raise MXNetError("token %r not in vocabulary" % t)
            table[self.token_to_idx[t]] = v
        self._idx_to_vec = nd.array(table)


def get_pretrained_file_names(embedding_name=None):
    """Reference API shape; pre-trained downloads need egress, so none are
    bundled — use CustomEmbedding with a local file."""
    return {} if embedding_name is None else []
