"""ONNX model import.

Reference analog: ``python/mxnet/contrib/onnx/`` (onnx2mx import_model /
import_to_gluon — SURVEY.md §2.3 contrib): converts an ONNX GraphProto into
a Symbol + parameter dict.

The converter itself (:func:`import_graph`) is pure and duck-typed over the
ONNX protobuf objects, so it needs only the ``onnx`` package for *loading*
files (:func:`import_model`); environments without onnx installed can still
convert in-memory graph objects (this is also how the unit tests exercise
every op converter without the package).
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..base import MXNetError

__all__ = ["import_model", "import_graph", "get_model_metadata"]


def _attrs_of(node) -> dict:
    """AttributeProto list -> python dict (ints/floats/strings/tuples)."""
    out = {}
    for a in node.attribute:
        name = a.name
        # AttributeProto.type enum: 1=FLOAT 2=INT 3=STRING 4=TENSOR
        # 6=FLOATS 7=INTS 8=STRINGS
        if getattr(a, "type", None) == 1 or _has(a, "f"):
            out[name] = float(a.f)
        if getattr(a, "type", None) == 2 or _has(a, "i"):
            out[name] = int(a.i)
        if getattr(a, "type", None) == 3 or _has(a, "s"):
            s = a.s
            out[name] = s.decode() if isinstance(s, bytes) else s
        if len(getattr(a, "ints", ())):
            out[name] = tuple(int(x) for x in a.ints)
        if len(getattr(a, "floats", ())):
            out[name] = tuple(float(x) for x in a.floats)
    return out


def _has(obj, field):
    try:
        return obj.HasField(field)
    except (AttributeError, ValueError):
        return getattr(obj, field, None) not in (None, 0, 0.0, b"", "")


def _tensor_to_np(t) -> np.ndarray:
    """TensorProto -> numpy (float/int tensors; raw or field data)."""
    shape = tuple(t.dims)
    raw = getattr(t, "raw_data", b"")
    # TensorProto.DataType: 1=FLOAT 6=INT32 7=INT64 11=DOUBLE
    dt = {1: np.float32, 6: np.int32, 7: np.int64,
          11: np.float64}.get(getattr(t, "data_type", 1), np.float32)
    if raw:
        arr = np.frombuffer(raw, dtype=dt)
    elif len(getattr(t, "float_data", ())):
        arr = np.asarray(list(t.float_data), np.float32)
    elif len(getattr(t, "int64_data", ())):
        arr = np.asarray(list(t.int64_data), np.int64)
    elif len(getattr(t, "int32_data", ())):
        arr = np.asarray(list(t.int32_data), np.int32)
    elif len(getattr(t, "double_data", ())):
        arr = np.asarray(list(t.double_data), np.float64)
    else:
        arr = np.zeros(shape, dt)
    return arr.reshape(shape) if shape else arr.reshape(())


def _pool_attrs(attrs):
    kernel = attrs.get("kernel_shape", (1, 1))
    stride = attrs.get("strides", (1,) * len(kernel))
    pads = attrs.get("pads", (0,) * 2 * len(kernel))
    begin, end = tuple(pads[:len(kernel)]), tuple(pads[len(kernel):])
    if end and begin != end:
        raise MXNetError("asymmetric ONNX pads %s are unsupported "
                         "(symmetric padding only)" % (pads,))
    return kernel, stride, begin


def import_graph(graph):
    """Convert an ONNX GraphProto (duck-typed) -> (sym, arg_params,
    aux_params)."""
    from .. import ndarray as nd
    from .. import symbol as S

    params: Dict[str, np.ndarray] = {}
    for init in graph.initializer:
        params[init.name] = _tensor_to_np(init)

    env: Dict[str, object] = {}
    for inp in graph.input:
        if inp.name not in params:
            env[inp.name] = S.var(inp.name)
    for name in params:
        env[name] = S.var(name)

    def conv(node):
        attrs = _attrs_of(node)
        kernel, stride, pad = _pool_attrs(attrs)
        wname = node.input[1]
        num_filter = params[wname].shape[0]
        args = [env[i] for i in node.input]
        return S.Convolution(*args, kernel=kernel, stride=stride, pad=pad,
                             num_filter=num_filter,
                             num_group=attrs.get("group", 1),
                             dilate=attrs.get("dilations",
                                              (1,) * len(kernel)),
                             no_bias=len(node.input) < 3,
                             name=node.name or node.output[0])

    def gemm(node):
        attrs = _attrs_of(node)
        if attrs.get("transA", 0):
            raise MXNetError("ONNX Gemm with transA=1 is unsupported")
        a, w = env[node.input[0]], env[node.input[1]]
        num_hidden = params[node.input[1]].shape[
            1 if attrs.get("transB", 0) == 0 else 0]
        if attrs.get("transB", 0) == 0:
            # our FullyConnected expects (out, in): pre-transpose the param
            params[node.input[1]] = params[node.input[1]].T
        # fold alpha/beta scaling into the (initializer) params
        alpha = attrs.get("alpha", 1.0)
        beta = attrs.get("beta", 1.0)
        if alpha != 1.0:
            params[node.input[1]] = params[node.input[1]] * np.float32(alpha)
        if beta != 1.0 and len(node.input) > 2:
            params[node.input[2]] = params[node.input[2]] * np.float32(beta)
        ins = [a, w] + ([env[node.input[2]]] if len(node.input) > 2 else [])
        return S.FullyConnected(*ins, num_hidden=num_hidden,
                                no_bias=len(node.input) < 3,
                                name=node.name or node.output[0])

    def pool(kind):
        def f(node):
            attrs = _attrs_of(node)
            kernel, stride, pad = _pool_attrs(attrs)
            return S.Pooling(env[node.input[0]], kernel=kernel,
                             stride=stride, pad=pad, pool_type=kind,
                             name=node.name or node.output[0])
        return f

    def global_pool(kind):
        def f(node):
            return S.Pooling(env[node.input[0]], global_pool=True,
                             kernel=(1, 1), pool_type=kind,
                             name=node.name or node.output[0])
        return f

    def batchnorm(node):
        attrs = _attrs_of(node)
        ins = [env[i] for i in node.input]
        return S.BatchNorm(*ins, eps=attrs.get("epsilon", 1e-5),
                           momentum=attrs.get("momentum", 0.9),
                           fix_gamma=False,
                           name=node.name or node.output[0])

    def reshape(node):
        shape = params.pop(node.input[1], None)
        if shape is None:
            raise MXNetError("ONNX Reshape with dynamic shape input is "
                             "unsupported")
        env.pop(node.input[1], None)
        return S.Reshape(env[node.input[0]],
                         shape=tuple(int(x) for x in shape))

    simple = {
        "Relu": lambda n: S.Activation(env[n.input[0]], act_type="relu"),
        "Sigmoid": lambda n: S.Activation(env[n.input[0]],
                                          act_type="sigmoid"),
        "Tanh": lambda n: S.Activation(env[n.input[0]], act_type="tanh"),
        # ONNX opset < 13 defines the default Softmax axis as 1
        "Softmax": lambda n: S.softmax(env[n.input[0]],
                                       axis=_attrs_of(n).get("axis", 1)),
        "Flatten": lambda n: S.Flatten(env[n.input[0]]),
        "Add": lambda n: env[n.input[0]] + env[n.input[1]],
        "Sub": lambda n: env[n.input[0]] - env[n.input[1]],
        "Mul": lambda n: env[n.input[0]] * env[n.input[1]],
        "MatMul": lambda n: S.dot(env[n.input[0]], env[n.input[1]]),
        "Identity": lambda n: env[n.input[0]],
        "Dropout": lambda n: S.Dropout(env[n.input[0]],
                                       p=_attrs_of(n).get("ratio", 0.5)),
        "Concat": lambda n: S.concat(*[env[i] for i in n.input],
                                     dim=_attrs_of(n).get("axis", 1)),
        "Conv": conv,
        "Gemm": gemm,
        "MaxPool": pool("max"),
        "AveragePool": pool("avg"),
        "GlobalMaxPool": global_pool("max"),
        "GlobalAveragePool": global_pool("avg"),
        "BatchNormalization": batchnorm,
        "Reshape": reshape,
    }

    for node in graph.node:
        fn = simple.get(node.op_type)
        if fn is None:
            raise MXNetError("unsupported ONNX op %r (supported: %s)"
                             % (node.op_type, sorted(simple)))
        out_sym = fn(node)
        avail = len(out_sym.list_outputs())
        for i, oname in enumerate(node.output):
            if i >= avail:
                # training-form extras (Dropout mask, BatchNorm saved
                # stats) have no symbol counterpart; consumers of output 0
                # are unaffected
                continue
            env[oname] = out_sym[i] if avail > 1 else out_sym

    out_names = [o.name for o in graph.output]
    outs = [env[n] for n in out_names]
    sym = outs[0] if len(outs) == 1 else S.Group(outs)

    arg_names = set(sym.list_arguments())
    aux_names = set(sym.list_auxiliary_states())
    arg_params = {k: nd.array(v) for k, v in params.items()
                  if k in arg_names}
    aux_params = {k: nd.array(v) for k, v in params.items()
                  if k in aux_names}
    return sym, arg_params, aux_params


def import_model(model_file):
    """Load an .onnx file (requires the ``onnx`` package) and convert
    (parity: contrib.onnx.import_model)."""
    try:
        import onnx
    except ImportError as e:
        raise ImportError(
            "import_model requires the 'onnx' package to parse .onnx "
            "files; in-memory graphs can be converted with import_graph"
        ) from e
    model = onnx.load(model_file)
    return import_graph(model.graph)


def get_model_metadata(model_file):
    """Input/output descriptions of an .onnx file."""
    try:
        import onnx
    except ImportError as e:
        raise ImportError("get_model_metadata requires 'onnx'") from e
    model = onnx.load(model_file)
    g = model.graph
    init = {i.name for i in g.initializer}

    def shape_of(vi):
        return tuple(d.dim_value for d in
                     vi.type.tensor_type.shape.dim)

    return {
        "input_tensor_data": [(i.name, shape_of(i)) for i in g.input
                              if i.name not in init],
        "output_tensor_data": [(o.name, shape_of(o)) for o in g.output],
    }
