"""ONNX model import/export.

Reference analog: ``python/mxnet/contrib/onnx/`` (onnx2mx import_model and
the ~100-entry converter table in ``onnx2mx/_op_translations.py`` —
SURVEY.md §2.3 contrib): converts an ONNX GraphProto into a Symbol +
parameter dict, and a Symbol + params back into an ONNX model.

Unlike the reference, no external ``onnx`` package is needed: ``.onnx``
files are (de)serialized with :mod:`mxnet_tpu.contrib.onnx_proto`, a
dependency-free protobuf wire codec.  The converter itself
(:func:`import_graph`) is duck-typed over the proto objects, so graphs
built with the real ``onnx`` package convert identically.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..base import MXNetError
from . import onnx_proto as P

__all__ = ["import_model", "import_graph", "get_model_metadata",
           "export_model", "export_graph"]

# TensorProto.DataType -> numpy
_ONNX_DT = {1: np.float32, 2: np.uint8, 3: np.int8, 6: np.int32,
            7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64}
_NP_DT = {np.dtype(v): k for k, v in _ONNX_DT.items()}


def _attrs_of(node) -> dict:
    """AttributeProto list -> python dict (ints/floats/strings/tuples)."""
    out = {}
    for a in node.attribute:
        name = a.name
        # AttributeProto.type enum: 1=FLOAT 2=INT 3=STRING 4=TENSOR
        # 6=FLOATS 7=INTS 8=STRINGS
        if getattr(a, "type", None) == 1 or _has(a, "f"):
            out[name] = float(a.f)
        if getattr(a, "type", None) == 2 or _has(a, "i"):
            out[name] = int(a.i)
        if getattr(a, "type", None) == 3 or _has(a, "s"):
            s = a.s
            out[name] = s.decode() if isinstance(s, bytes) else s
        if getattr(a, "type", None) == 4 or _has(a, "t"):
            if getattr(a, "t", None) is not None:
                out[name] = _tensor_to_np(a.t)
        if len(getattr(a, "ints", ())):
            out[name] = tuple(int(x) for x in a.ints)
        if len(getattr(a, "floats", ())):
            out[name] = tuple(float(x) for x in a.floats)
        if len(getattr(a, "strings", ())):
            out[name] = tuple(s.decode() if isinstance(s, bytes) else s
                              for s in a.strings)
    return out


def _has(obj, field):
    try:
        return obj.HasField(field)
    except (AttributeError, ValueError):
        return getattr(obj, field, None) not in (None, 0, 0.0, b"", "")


def _tensor_to_np(t) -> np.ndarray:
    """TensorProto -> numpy (float/int tensors; raw or field data)."""
    shape = tuple(t.dims)
    raw = getattr(t, "raw_data", b"")
    dt = _ONNX_DT.get(getattr(t, "data_type", 1), np.float32)
    if raw:
        arr = np.frombuffer(raw, dtype=dt)
    elif len(getattr(t, "float_data", ())):
        arr = np.asarray(list(t.float_data), np.float32)
    elif len(getattr(t, "int64_data", ())):
        arr = np.asarray(list(t.int64_data), np.int64)
    elif len(getattr(t, "int32_data", ())):
        arr = np.asarray(list(t.int32_data), np.int32)
    elif len(getattr(t, "double_data", ())):
        arr = np.asarray(list(t.double_data), np.float64)
    else:
        arr = np.zeros(shape, dt)
    return arr.reshape(shape) if shape else arr.reshape(())


def _pool_attrs(attrs):
    kernel = attrs.get("kernel_shape", (1, 1))
    stride = attrs.get("strides", (1,) * len(kernel))
    pads = attrs.get("pads", (0,) * 2 * len(kernel))
    begin, end = tuple(pads[:len(kernel)]), tuple(pads[len(kernel):])
    if end and begin != end:
        raise MXNetError("asymmetric ONNX pads %s are unsupported "
                         "(symmetric padding only)" % (pads,))
    return kernel, stride, begin


def import_graph(graph):
    """Convert an ONNX GraphProto (duck-typed) -> (sym, arg_params,
    aux_params)."""
    from .. import ndarray as nd
    from .. import symbol as S

    params: Dict[str, np.ndarray] = {}
    for init in graph.initializer:
        params[init.name] = _tensor_to_np(init)

    env: Dict[str, object] = {}
    declared: Dict[str, tuple] = {}   # static shapes from ValueInfos
    for vi in (list(graph.input) + list(graph.output) +
               list(getattr(graph, "value_info", ()) or ())):
        # duck-typed graphs may omit type info entirely
        tt = getattr(getattr(vi, "type", None), "tensor_type", None)
        shape = getattr(tt, "shape", None)
        if shape is None:
            continue
        dims = tuple(d.dim_value for d in shape.dim)
        if dims and all(d > 0 for d in dims):
            declared[vi.name] = dims
    for inp in graph.input:
        if inp.name not in params:
            env[inp.name] = S.var(inp.name,
                                  shape=declared.get(inp.name))
    for name in params:
        env[name] = S.var(name, shape=params[name].shape)

    def const_input(node, idx, what):
        """Fetch input idx which must be a graph constant (initializer)."""
        name = node.input[idx]
        if name not in params:
            raise MXNetError("ONNX %s with dynamic %s input is unsupported"
                             % (node.op_type, what))
        return params[name]

    def conv(node):
        attrs = _attrs_of(node)
        kernel, stride, pad = _pool_attrs(attrs)
        wname = node.input[1]
        num_filter = params[wname].shape[0]
        args = [env[i] for i in node.input]
        return S.Convolution(*args, kernel=kernel, stride=stride, pad=pad,
                             num_filter=num_filter,
                             num_group=attrs.get("group", 1),
                             dilate=attrs.get("dilations",
                                              (1,) * len(kernel)),
                             no_bias=len(node.input) < 3,
                             name=node.name or node.output[0])

    def conv_transpose(node):
        attrs = _attrs_of(node)
        kernel, stride, pad = _pool_attrs(attrs)
        group = attrs.get("group", 1)
        # ConvTranspose weight is (C_in, C_out/group, *kernel)
        num_filter = const_input(node, 1, "weight").shape[1] * group
        args = [env[i] for i in node.input]
        return S.Deconvolution(*args, kernel=kernel, stride=stride,
                               pad=pad, num_filter=num_filter,
                               num_group=group,
                               dilate=attrs.get("dilations",
                                                (1,) * len(kernel)),
                               adj=attrs.get("output_padding",
                                             (0,) * len(kernel)),
                               no_bias=len(node.input) < 3,
                               name=node.name or node.output[0])

    def gemm(node):
        attrs = _attrs_of(node)
        if attrs.get("transA", 0):
            raise MXNetError("ONNX Gemm with transA=1 is unsupported")
        a = env[node.input[0]]
        wname = node.input[1]
        w_shape = (params[wname].shape if wname in params
                   else None)
        trans_b = attrs.get("transB", 0)
        w = env[wname]
        if not trans_b:
            # our FullyConnected expects (out, in): transpose symbolically
            # (initializers stay untouched — they may be shared)
            w = S.transpose(w, axes=(1, 0))
        if w_shape is None:
            raise MXNetError("ONNX Gemm with dynamic weight unsupported")
        num_hidden = w_shape[1 if not trans_b else 0]
        alpha = attrs.get("alpha", 1.0)
        beta = attrs.get("beta", 1.0)
        if alpha != 1.0:
            w = w * float(alpha)
        ins = [a, w]
        if len(node.input) > 2:
            b = env[node.input[2]]
            if beta != 1.0:
                b = b * float(beta)
            ins.append(b)
        return S.FullyConnected(*ins, num_hidden=num_hidden,
                                no_bias=len(node.input) < 3,
                                name=node.name or node.output[0])

    def pool(kind):
        def f(node):
            attrs = _attrs_of(node)
            kernel, stride, pad = _pool_attrs(attrs)
            return S.Pooling(env[node.input[0]], kernel=kernel,
                             stride=stride, pad=pad, pool_type=kind,
                             name=node.name or node.output[0])
        return f

    def global_pool(kind):
        def f(node):
            return S.Pooling(env[node.input[0]], global_pool=True,
                             kernel=(1, 1), pool_type=kind,
                             name=node.name or node.output[0])
        return f

    def batchnorm(node):
        attrs = _attrs_of(node)
        ins = [env[i] for i in node.input]
        return S.BatchNorm(*ins, eps=attrs.get("epsilon", 1e-5),
                           momentum=attrs.get("momentum", 0.9),
                           fix_gamma=False,
                           name=node.name or node.output[0])

    def reshape(node):
        if len(node.input) > 1:
            shape = const_input(node, 1, "shape")
        else:  # opset 1 attr form
            shape = _attrs_of(node)["shape"]
        return S.Reshape(env[node.input[0]],
                         shape=tuple(int(x) for x in shape))

    def clip(node):
        attrs = _attrs_of(node)
        lo, hi = attrs.get("min"), attrs.get("max")
        if lo is None and len(node.input) > 1 and node.input[1]:
            lo = float(const_input(node, 1, "min"))
        if hi is None and len(node.input) > 2 and node.input[2]:
            hi = float(const_input(node, 2, "max"))
        return S.clip(env[node.input[0]],
                      a_min=-3.4e38 if lo is None else lo,
                      a_max=3.4e38 if hi is None else hi)

    def pad_op(node):
        attrs = _attrs_of(node)
        value = attrs.get("value", 0.0)
        if len(node.input) > 1:
            pads = tuple(int(x) for x in const_input(node, 1, "pads"))
            if len(node.input) > 2 and node.input[2]:
                value = float(np.asarray(
                    const_input(node, 2, "constant_value")).ravel()[0])
        else:
            pads = attrs.get("pads", attrs.get("paddings"))
        n = len(pads) // 2
        # ONNX: (b1..bn, e1..en) -> mxnet pad_width (b1,e1,b2,e2,...)
        pw = []
        for i in range(n):
            pw += [int(pads[i]), int(pads[i + n])]
        mode = {"constant": "constant", "edge": "edge",
                "reflect": "reflect"}[attrs.get("mode", "constant")]
        return S.Pad(env[node.input[0]], mode=mode, pad_width=tuple(pw),
                     constant_value=value)

    def slice_op(node):
        attrs = _attrs_of(node)
        if len(node.input) > 1:  # opset 10+: inputs
            starts = const_input(node, 1, "starts")
            ends = const_input(node, 2, "ends")
            axes = (const_input(node, 3, "axes")
                    if len(node.input) > 3 else range(len(starts)))
            steps = (const_input(node, 4, "steps")
                     if len(node.input) > 4 else [1] * len(starts))
        else:
            starts = attrs["starts"]
            ends = attrs["ends"]
            axes = attrs.get("axes", range(len(starts)))
            steps = [1] * len(starts)
        out = env[node.input[0]]
        for ax, b, e, st in zip(axes, starts, ends, steps):
            if int(st) != 1:
                raise MXNetError("ONNX Slice with step != 1 unsupported")
            e = int(e)
            out = S.slice_axis(out, axis=int(ax), begin=int(b),
                               end=None if e >= 2 ** 31 - 1 else e)
        return out

    def split(node):
        attrs = _attrs_of(node)
        axis = attrs.get("axis", 0)
        sizes = attrs.get("split")
        if sizes is None and len(node.input) > 1:  # opset 13+: input form
            sizes = tuple(int(x) for x in const_input(node, 1, "split"))
        if sizes is not None and len(set(sizes)) > 1:
            raise MXNetError("ONNX Split with unequal parts unsupported")
        return S.SliceChannel(env[node.input[0]],
                              num_outputs=len(node.output), axis=axis,
                              name=node.name or node.output[0])

    def constant(node):
        attrs = _attrs_of(node)
        value = attrs.get("value")
        if value is None:
            raise MXNetError("ONNX Constant without 'value' tensor")
        value = np.asarray(value)
        params[node.output[0]] = value
        return S.var(node.output[0], shape=value.shape)

    def axes_of(node, attrs, key="axes"):
        """axes from attribute (opset < 13) or constant input (13+)."""
        if key in attrs:
            return attrs[key]
        if len(node.input) > 1 and node.input[1]:
            return tuple(int(x) for x in const_input(node, 1, key))
        return None

    def unsqueeze(node):
        axes = axes_of(node, _attrs_of(node))
        if axes is None:
            raise MXNetError("ONNX Unsqueeze without axes")
        out = env[node.input[0]]
        for ax in sorted(axes):
            out = S.expand_dims(out, axis=int(ax))
        return out

    def squeeze(node):
        return S.squeeze(env[node.input[0]],
                         axis=axes_of(node, _attrs_of(node)))

    def reduce(op_name):
        def f(node):
            attrs = _attrs_of(node)
            return getattr(S, op_name)(
                env[node.input[0]], axis=axes_of(node, attrs),
                keepdims=bool(attrs.get("keepdims", 1)))
        return f

    def gather(node):
        axis = _attrs_of(node).get("axis", 0)
        return S.take(env[node.input[0]], env[node.input[1]], axis=axis)

    def upsample(node):
        attrs = _attrs_of(node)
        scales = attrs.get("scales")
        if scales is None and len(node.input) > 1:
            scales = const_input(node, 1, "scales")
        mode = attrs.get("mode", "nearest")
        sh, sw = float(scales[2]), float(scales[3])
        if sh != sw or sh != int(sh) or sh < 1:
            raise MXNetError("ONNX Upsample scales %s unsupported (need "
                             "equal integer H/W scales >= 1)"
                             % (tuple(scales),))
        return S.UpSampling(env[node.input[0]], scale=int(sh),
                            sample_type="nearest" if mode == "nearest"
                            else "bilinear",
                            num_filter=1)

    def cast(node):
        to = _attrs_of(node)["to"]
        return S.Cast(env[node.input[0]],
                      dtype=np.dtype(_ONNX_DT[int(to)]).name)

    def nary(binop):
        def f(node):
            out = env[node.input[0]]
            for i in node.input[1:]:
                out = binop(out, env[i])
            return out
        return f

    def leaky(act):
        def f(node):
            attrs = _attrs_of(node)
            kw = {}
            if act in ("leaky", "elu"):
                kw["slope"] = attrs.get("alpha",
                                        0.01 if act == "leaky" else 1.0)
            ins = [env[i] for i in node.input]
            return S.LeakyReLU(*ins, act_type=act, **kw)
        return f

    def hard_sigmoid(node):
        attrs = _attrs_of(node)
        alpha = attrs.get("alpha", 0.2)
        beta = attrs.get("beta", 0.5)
        return S.clip(env[node.input[0]] * alpha + beta, 0.0, 1.0)

    def image_scaler(node):
        attrs = _attrs_of(node)
        scale = attrs.get("scale", 1.0)
        bias = np.asarray(attrs.get("bias", (0.0,)), np.float32)
        bname = (node.name or node.output[0]) + "_bias"
        params[bname] = bias.reshape((1, -1, 1, 1))
        env[bname] = S.var(bname, shape=params[bname].shape)
        return S.broadcast_add(env[node.input[0]] * scale, env[bname])

    def mean_n(node):
        out = env[node.input[0]]
        for i in node.input[1:]:
            out = out + env[i]
        return out * (1.0 / len(node.input))

    def unary(op_name):
        return lambda n: getattr(S, op_name)(env[n.input[0]])

    def expand(node):
        """ONNX Expand = bidirectional numpy broadcast: adding symbolic
        zeros of the target shape handles rank expansion and 1-dims on
        either side (broadcast_to alone rejects both)."""
        shape = tuple(int(x) for x in const_input(node, 1, "shape"))
        return S.broadcast_add(env[node.input[0]], S.zeros(shape=shape))

    def one_hot(node):
        attrs = _attrs_of(node)
        axis = attrs.get("axis", -1)
        if axis != -1:
            raise MXNetError("ONNX OneHot with axis != -1 unsupported")
        depth = int(np.asarray(
            const_input(node, 1, "depth")).ravel()[0])
        kw = {}
        if len(node.input) > 2 and node.input[2]:
            off, on = np.asarray(
                const_input(node, 2, "values")).ravel()[:2]
            kw = {"on_value": float(on), "off_value": float(off)}
        return S.one_hot(env[node.input[0]], depth=depth, **kw)

    def reduce_logsumexp(node):
        """Numerically stable: m + log(sum(exp(x - m)))."""
        attrs = _attrs_of(node)
        axes = axes_of(node, attrs)
        keepdims = bool(attrs.get("keepdims", 1))
        x = env[node.input[0]]
        m = getattr(S, "max")(x, axis=axes, keepdims=True)
        s = getattr(S, "sum")(S.exp(S.broadcast_sub(x, m)), axis=axes,
                              keepdims=True)
        out = S.broadcast_add(m, S.log(s))
        if not keepdims:
            out = S.squeeze(out, axis=axes)
        return out

    def onnx_rnn(mode):
        """ONNX RNN/GRU/LSTM -> the fused RNN op (ops/rnn.py).

        Covers forward and bidirectional single-layer cells with constant
        weights; gate orders are remapped (ONNX LSTM iofc -> ifgo, GRU
        zrh -> rzn).  B (batch) must be statically known — from
        ``initial_h`` or the declared input ValueInfo — to synthesize
        zero initial states.
        """
        def f(node):
            attrs = _attrs_of(node)
            h = int(attrs["hidden_size"])
            direction = attrs.get("direction", "forward")
            if direction == "reverse":
                raise MXNetError("ONNX %s direction=reverse unsupported "
                                 "(forward/bidirectional only)" % mode)
            bidir = direction == "bidirectional"
            dirs = 2 if bidir else 1
            if mode == "GRU" and attrs.get("linear_before_reset", 0) == 0:
                raise MXNetError("ONNX GRU linear_before_reset=0 "
                                 "unsupported (cuDNN variant only)")
            W = const_input(node, 1, "W")       # (dirs, ng*h, in)
            R = const_input(node, 2, "R")       # (dirs, ng*h, h)
            ng = {"RNN": 1, "GRU": 3, "LSTM": 4}[mode]
            Bp = (const_input(node, 3, "B")
                  if len(node.input) > 3 and node.input[3]
                  else np.zeros((dirs, 2 * ng * h), np.float32))
            if len(node.input) > 4 and node.input[4]:
                raise MXNetError("ONNX %s with sequence_lens input "
                                 "unsupported (fixed-length only)" % mode)
            if mode == "LSTM" and len(node.input) > 7 and node.input[7]:
                raise MXNetError("ONNX LSTM with peephole weights (P) "
                                 "unsupported")

            def reorder(mat, axis):
                if mode == "LSTM":      # iofc -> ifgo (g = c)
                    order = [0, 2, 3, 1]
                elif mode == "GRU":     # zrh -> rzn
                    order = [1, 0, 2]
                else:
                    return mat
                parts = np.split(mat, ng, axis=axis)
                return np.concatenate([parts[i] for i in order],
                                      axis=axis)

            flat = []
            for d in range(dirs):
                flat.append(reorder(W[d], 0).ravel())
                flat.append(reorder(R[d], 0).ravel())
            for d in range(dirs):
                bW, bR = Bp[d][:ng * h], Bp[d][ng * h:]
                flat.append(reorder(bW, 0))
                flat.append(reorder(bR, 0))
            pname = (node.name or node.output[0]) + "_packed"
            params[pname] = np.concatenate(flat).astype(np.float32)
            env[pname] = S.var(pname, shape=params[pname].shape)

            # initial states: inputs 5 (h) / 6 (c), else zeros with the
            # statically-declared batch
            def state(idx, what):
                if len(node.input) > idx and node.input[idx]:
                    return env[node.input[idx]]
                xshape = declared.get(node.input[0])
                if xshape is None or len(xshape) != 3:
                    raise MXNetError(
                        "ONNX %s without %s needs a static input shape "
                        "to synthesize zero states" % (mode, what))
                sname = "%s_%s0" % (node.name or node.output[0], what)
                params[sname] = np.zeros((dirs, xshape[1], h), np.float32)
                env[sname] = S.var(sname, shape=params[sname].shape)
                return env[sname]

            ins = [env[node.input[0]], env[pname], state(5, "h")]
            mx_mode = {"RNN": "rnn_tanh", "GRU": "gru",
                       "LSTM": "lstm"}[mode]
            if mode == "RNN":
                acts = attrs.get("activations", ("Tanh",))
                act = acts[0] if isinstance(acts, (tuple, list)) else acts
                if isinstance(act, bytes):
                    act = act.decode()
                if act == "Relu":
                    mx_mode = "rnn_relu"
                elif act != "Tanh":
                    raise MXNetError("ONNX RNN activation %r unsupported"
                                     % (act,))
            if mode == "LSTM":
                ins.append(state(6, "c"))
            out = S.RNN(*ins, state_size=h, num_layers=1,
                        bidirectional=bidir, mode=mx_mode,
                        state_outputs=True,
                        name=node.name or node.output[0])
            # ONNX Y is (T, dirs, B, h); ours is (T, B, dirs*h)
            y = out[0]
            if bidir:
                y = S.transpose(S.Reshape(y, shape=(0, 0, 2, -1)),
                                axes=(0, 2, 1, 3))
            else:
                y = S.expand_dims(y, axis=1)
            env[node.output[0]] = y
            for i, oname in enumerate(node.output[1:], start=1):
                if oname:
                    env[oname] = out[i]
            return None  # outputs registered explicitly above
        return f

    simple = {
        # activations
        "Relu": lambda n: S.Activation(env[n.input[0]], act_type="relu"),
        "Sigmoid": lambda n: S.Activation(env[n.input[0]],
                                          act_type="sigmoid"),
        "Tanh": lambda n: S.Activation(env[n.input[0]], act_type="tanh"),
        "Softplus": lambda n: S.Activation(env[n.input[0]],
                                           act_type="softrelu"),
        "LeakyRelu": leaky("leaky"),
        "Elu": leaky("elu"),
        "PRelu": leaky("prelu"),
        "Selu": leaky("selu"),
        "HardSigmoid": hard_sigmoid,
        # ONNX opset < 13 defines the default Softmax axis as 1
        "Softmax": lambda n: S.softmax(env[n.input[0]],
                                       axis=_attrs_of(n).get("axis", 1)),
        "LogSoftmax": lambda n: S.log_softmax(
            env[n.input[0]], axis=_attrs_of(n).get("axis", 1)),
        # shape manipulation
        "Flatten": lambda n: S.Flatten(env[n.input[0]]),
        "Reshape": reshape,
        "Transpose": lambda n: S.transpose(
            env[n.input[0]], axes=_attrs_of(n).get("perm", ())),
        "Squeeze": squeeze,
        "Unsqueeze": unsqueeze,
        "Concat": lambda n: S.concat(*[env[i] for i in n.input],
                                     dim=_attrs_of(n).get("axis", 1)),
        "Split": split,
        "Slice": slice_op,
        "Pad": pad_op,
        "Tile": lambda n: S.tile(env[n.input[0]], reps=tuple(
            int(x) for x in const_input(n, 1, "repeats"))),
        "Identity": lambda n: env[n.input[0]],
        "Dropout": lambda n: S.Dropout(env[n.input[0]],
                                       p=_attrs_of(n).get("ratio", 0.5)),
        "Cast": cast,
        # arithmetic
        "Add": lambda n: S.broadcast_add(env[n.input[0]], env[n.input[1]]),
        "Sub": lambda n: S.broadcast_sub(env[n.input[0]], env[n.input[1]]),
        "Mul": lambda n: S.broadcast_mul(env[n.input[0]], env[n.input[1]]),
        "Div": lambda n: S.broadcast_div(env[n.input[0]], env[n.input[1]]),
        "Pow": lambda n: env[n.input[0]] ** env[n.input[1]],
        "MatMul": lambda n: S.dot(env[n.input[0]], env[n.input[1]]),
        "Sum": nary(lambda a, b: S.broadcast_add(a, b)),
        "Mean": mean_n,
        "Max": nary(lambda a, b: S.broadcast_maximum(a, b)),
        "Min": nary(lambda a, b: S.broadcast_minimum(a, b)),
        "Neg": unary("negative"),
        "Abs": unary("abs"),
        "Exp": unary("exp"),
        "Log": unary("log"),
        "Sqrt": unary("sqrt"),
        "Floor": unary("floor"),
        "Ceil": unary("ceil"),
        "Reciprocal": unary("reciprocal"),
        "Sign": unary("sign"),
        "Clip": clip,
        # reductions
        "ReduceMean": reduce("mean"),
        "ReduceSum": reduce("sum"),
        "ReduceMax": reduce("max"),
        "ReduceMin": reduce("min"),
        "ReduceProd": reduce("prod"),
        "ArgMax": lambda n: S.argmax(
            env[n.input[0]], axis=_attrs_of(n).get("axis", 0),
            keepdims=bool(_attrs_of(n).get("keepdims", 1))),
        # NN layers
        "Conv": conv,
        "ConvTranspose": conv_transpose,
        "Gemm": gemm,
        "MaxPool": pool("max"),
        "AveragePool": pool("avg"),
        "GlobalMaxPool": global_pool("max"),
        "GlobalAveragePool": global_pool("avg"),
        "BatchNormalization": batchnorm,
        "InstanceNormalization": lambda n: S.InstanceNorm(
            *[env[i] for i in n.input],
            eps=_attrs_of(n).get("epsilon", 1e-5)),
        "LRN": lambda n: S.LRN(
            env[n.input[0]], alpha=_attrs_of(n).get("alpha", 1e-4),
            beta=_attrs_of(n).get("beta", 0.75),
            knorm=_attrs_of(n).get("bias", 1.0),
            nsize=_attrs_of(n).get("size", 5)),
        "Gather": gather,
        "Upsample": upsample,
        "Constant": constant,
        "ImageScaler": image_scaler,
        # recurrent
        "RNN": onnx_rnn("RNN"),
        "GRU": onnx_rnn("GRU"),
        "LSTM": onnx_rnn("LSTM"),
        # comparison / logical (float outputs, mxnet convention)
        "Equal": lambda n: S.broadcast_equal(env[n.input[0]],
                                             env[n.input[1]]),
        "Greater": lambda n: S.broadcast_greater(env[n.input[0]],
                                                 env[n.input[1]]),
        "Less": lambda n: S.broadcast_lesser(env[n.input[0]],
                                             env[n.input[1]]),
        "And": lambda n: S.broadcast_logical_and(env[n.input[0]],
                                                 env[n.input[1]]),
        "Or": lambda n: S.broadcast_logical_or(env[n.input[0]],
                                               env[n.input[1]]),
        "Not": unary("logical_not"),
        "Where": lambda n: S.where(env[n.input[0]], env[n.input[1]],
                                   env[n.input[2]]),
        # more activations / elementwise
        "Softsign": unary("softsign"),
        "Erf": unary("erf"),
        "Expand": expand,
        "OneHot": one_hot,
        "DepthToSpace": lambda n: S.depth_to_space(
            env[n.input[0]], block_size=_attrs_of(n)["blocksize"]),
        "SpaceToDepth": lambda n: S.space_to_depth(
            env[n.input[0]], block_size=_attrs_of(n)["blocksize"]),
        "ArgMin": lambda n: S.argmin(
            env[n.input[0]], axis=_attrs_of(n).get("axis", 0),
            keepdims=bool(_attrs_of(n).get("keepdims", 1))),
        "ReduceL1": lambda n: S.norm(
            env[n.input[0]], ord=1, axis=axes_of(n, _attrs_of(n)),
            keepdims=bool(_attrs_of(n).get("keepdims", 1))),
        "ReduceL2": lambda n: S.norm(
            env[n.input[0]], ord=2, axis=axes_of(n, _attrs_of(n)),
            keepdims=bool(_attrs_of(n).get("keepdims", 1))),
        "ReduceLogSumExp": reduce_logsumexp,
        "ReduceSumSquare": lambda n: getattr(S, "sum")(
            S.square(env[n.input[0]]),
            axis=axes_of(n, _attrs_of(n)),
            keepdims=bool(_attrs_of(n).get("keepdims", 1))),
    }

    for node in graph.node:
        fn = simple.get(node.op_type)
        if fn is None:
            raise MXNetError("unsupported ONNX op %r (supported: %s)"
                             % (node.op_type, sorted(simple)))
        out_sym = fn(node)
        if out_sym is None:
            continue  # converter registered its outputs in env itself
        avail = len(out_sym.list_outputs())
        for i, oname in enumerate(node.output):
            if i >= avail:
                # training-form extras (Dropout mask, BatchNorm saved
                # stats) have no symbol counterpart; consumers of output 0
                # are unaffected
                continue
            env[oname] = out_sym[i] if avail > 1 else out_sym

    out_names = [o.name for o in graph.output]
    outs = [env[n] for n in out_names]
    sym = outs[0] if len(outs) == 1 else S.Group(outs)

    arg_names = set(sym.list_arguments())
    aux_names = set(sym.list_auxiliary_states())
    arg_params = {k: nd.array(v) for k, v in params.items()
                  if k in arg_names}
    aux_params = {k: nd.array(v) for k, v in params.items()
                  if k in aux_names}
    return sym, arg_params, aux_params


def import_model(model_file):
    """Load an .onnx file and convert -> (sym, arg_params, aux_params)
    (parity: contrib.onnx.import_model; parsing is self-contained)."""
    model = P.load(model_file)
    if model.graph is None:
        raise MXNetError("%s has no graph (not an ONNX ModelProto?)"
                         % (model_file,))
    return import_graph(model.graph)


def get_model_metadata(model_file):
    """Input/output descriptions of an .onnx file."""
    model = P.load(model_file)
    g = model.graph
    init = {i.name for i in g.initializer}

    def shape_of(vi):
        if vi.type is None or vi.type.tensor_type is None or \
                vi.type.tensor_type.shape is None:
            return ()
        return tuple(d.dim_value for d in vi.type.tensor_type.shape.dim)

    return {
        "input_tensor_data": [(i.name, shape_of(i)) for i in g.input
                              if i.name not in init],
        "output_tensor_data": [(o.name, shape_of(o)) for o in g.output],
    }


# ---------------------------------------------------------------------------
# export (Symbol + params -> ONNX)
# ---------------------------------------------------------------------------

def _np_to_tensor(name: str, arr: np.ndarray) -> P.TensorProto:
    arr = np.ascontiguousarray(arr)
    dt = _NP_DT.get(arr.dtype)
    if dt is None:
        arr = arr.astype(np.float32)
        dt = 1
    return P.TensorProto(name=name, dims=list(arr.shape), data_type=dt,
                         raw_data=arr.tobytes())


def _vi(name: str, shape, elem_type=1) -> P.ValueInfoProto:
    """ValueInfoProto; shape=None means unknown rank (no TensorShapeProto —
    an *empty* shape would declare a scalar in ONNX semantics)."""
    tt = P.TensorTypeProto(elem_type=elem_type)
    if shape is not None:
        tt.shape = P.TensorShapeProto(
            dim=[P.Dimension(dim_value=int(d)) for d in shape])
    return P.ValueInfoProto(name=name, type=P.TypeProto(tensor_type=tt))


def _attr(name, value):
    a = P.AttributeProto(name=name)
    if isinstance(value, bool):
        a.i, a.type = int(value), P.AttributeProto.INT
    elif isinstance(value, (int, np.integer)):
        a.i, a.type = int(value), P.AttributeProto.INT
    elif isinstance(value, (float, np.floating)):
        a.f, a.type = float(value), P.AttributeProto.FLOAT
    elif isinstance(value, str):
        a.s, a.type = value.encode(), P.AttributeProto.STRING
    elif isinstance(value, (tuple, list)):
        if value and isinstance(value[0], (float, np.floating)):
            a.floats, a.type = [float(v) for v in value], \
                P.AttributeProto.FLOATS
        else:
            a.ints, a.type = [int(v) for v in value], P.AttributeProto.INTS
    else:
        raise MXNetError("cannot export attribute %s=%r" % (name, value))
    return a


def export_graph(sym, params, input_shapes, graph_name="mxnet_tpu"):
    """Symbol + {name: array} + {input: shape} -> ONNX GraphProto.

    Covers the layer set of the model zoo (Conv/Deconv, FC, pooling incl.
    global, BatchNorm/InstanceNorm/LRN, activations, softmax, elementwise,
    concat/reshape/transpose/slice/split/pad/clip, reductions, dropout,
    embedding-gather, upsampling).  Multi-precision params are exported in
    their stored dtype.
    """
    params = {k: (v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v))
              for k, v in params.items()}
    nodes: List[P.NodeProto] = []
    initializers: List[P.TensorProto] = []
    graph_inputs: List[P.ValueInfoProto] = []
    names: Dict[int, List[str]] = {}   # id(_Node) -> output tensor names
    uniq = [0]

    def fresh(base):
        uniq[0] += 1
        return "%s_%d" % (base, uniq[0])

    def add_node(op_type, ins, outs, name, **attrs):
        nodes.append(P.NodeProto(
            op_type=op_type, input=list(ins), output=list(outs),
            name=name,
            attribute=[_attr(k, v) for k, v in attrs.items()
                       if v is not None]))

    def add_const(base, arr):
        name = fresh(base)
        initializers.append(_np_to_tensor(name, np.asarray(arr)))
        return name

    topo = sym._topo()
    for node in topo:
        if node.is_var:
            if node.name in params:
                initializers.append(_np_to_tensor(node.name,
                                                  params[node.name]))
            else:
                if node.name not in input_shapes:
                    raise MXNetError(
                        "export: missing shape for input %r" % node.name)
                graph_inputs.append(_vi(node.name,
                                        input_shapes[node.name]))
            names[id(node)] = [node.name]
            continue
        in_names = [names[id(p)][i] for p, i in node.inputs]
        attrs = node.parsed_attrs()
        op = node.op.name
        n_out = node.num_visible()
        outs = [node.name] if n_out == 1 else \
            ["%s_output%d" % (node.name, i) for i in range(n_out)]
        _export_one(op, attrs, in_names, outs, node, add_node, add_const,
                    params)
        names[id(node)] = outs

    out_vis = [_vi(n, None) for n in
               [names[id(node)][i] for node, i in sym._outputs]]
    return P.GraphProto(name=graph_name, node=nodes,
                        initializer=initializers,
                        input=graph_inputs, output=out_vis)


def _export_one(op, attrs, ins, outs, node, add_node, add_const, params):
    """Emit ONNX node(s) for one symbol node."""
    name = node.name

    def a(key, default=None):
        v = attrs.get(key, default)
        return v

    if op == "Convolution":
        kernel = a("kernel")
        add_node("Conv", ins, outs, name, kernel_shape=kernel,
                 strides=a("stride") or (1,) * len(kernel),
                 pads=tuple(a("pad") or (0,) * len(kernel)) * 2,
                 dilations=a("dilate") or (1,) * len(kernel),
                 group=a("num_group", 1))
    elif op == "Deconvolution":
        kernel = a("kernel")
        add_node("ConvTranspose", ins, outs, name, kernel_shape=kernel,
                 strides=a("stride") or (1,) * len(kernel),
                 pads=tuple(a("pad") or (0,) * len(kernel)) * 2,
                 dilations=a("dilate") or (1,) * len(kernel),
                 group=a("num_group", 1))
    elif op == "FullyConnected":
        if not a("flatten", True):
            # per-last-dim projection (N, ..., D) @ W.T: Gemm would flatten,
            # so emit Transpose(W) + MatMul (+ broadcast Add bias)
            wt = outs[0] + "_wT"
            add_node("Transpose", [ins[1]], [wt], name + "_wT",
                     perm=(1, 0))
            mm_out = outs if len(ins) < 3 else [outs[0] + "_mm"]
            add_node("MatMul", [ins[0], wt], mm_out, name + "_mm")
            if len(ins) > 2:
                add_node("Add", [mm_out[0], ins[2]], outs, name)
            return
        flat = outs[0] + "_flat"
        add_node("Flatten", ins[:1], [flat], name + "_flatten", axis=1)
        gemm_in = [flat, ins[1]]
        if len(ins) > 2:
            gemm_in.append(ins[2])
        else:
            gemm_in.append(add_const(name + "_zero_bias",
                                     np.zeros((a("num_hidden"),),
                                              np.float32)))
        add_node("Gemm", gemm_in, outs, name, transB=1)
    elif op == "Activation":
        act = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
               "softrelu": "Softplus", "softsign": "Softsign"}[a("act_type")]
        add_node(act, ins, outs, name)
    elif op in ("relu", "sigmoid", "tanh"):
        add_node(op.capitalize(), ins, outs, name)
    elif op == "LeakyReLU":
        act = a("act_type", "leaky")
        if act == "leaky":
            add_node("LeakyRelu", ins, outs, name, alpha=a("slope", 0.25))
        elif act == "elu":
            add_node("Elu", ins, outs, name, alpha=a("slope", 0.25))
        elif act == "prelu":
            add_node("PRelu", ins, outs, name)
        elif act == "selu":
            add_node("Selu", ins, outs, name)
        else:
            raise MXNetError("cannot export LeakyReLU act_type %r" % act)
    elif op == "Pooling":
        kind = a("pool_type", "max")
        if a("global_pool", False):
            add_node({"max": "GlobalMaxPool", "avg": "GlobalAveragePool"}
                     [kind], ins, outs, name)
        else:
            kernel = a("kernel")
            add_node({"max": "MaxPool", "avg": "AveragePool"}[kind],
                     ins, outs, name, kernel_shape=kernel,
                     strides=a("stride") or (1,) * len(kernel),
                     pads=tuple(a("pad") or (0,) * len(kernel)) * 2)
    elif op == "BatchNorm":
        if len(outs) > 1:
            raise MXNetError("cannot export BatchNorm with "
                             "output_mean_var=True (consumers of the "
                             "mean/var outputs have no ONNX equivalent)")
        bn_ins = list(ins)
        if a("fix_gamma", True):
            # our op computes with gamma forced to ones; serialize that,
            # not the stored (possibly nonuniform) gamma initializer
            if ins[1] not in params:
                raise MXNetError("cannot export BatchNorm with "
                                 "fix_gamma=True and non-constant gamma")
            bn_ins[1] = add_const(name + "_fixed_gamma",
                                  np.ones_like(params[ins[1]]))
        add_node("BatchNormalization", bn_ins, outs[:1], name,
                 epsilon=a("eps", 1e-3), momentum=a("momentum", 0.9))
    elif op == "InstanceNorm":
        add_node("InstanceNormalization", ins, outs, name,
                 epsilon=a("eps", 1e-3))
    elif op == "LRN":
        add_node("LRN", ins, outs, name, alpha=a("alpha", 1e-4),
                 beta=a("beta", 0.75), bias=a("knorm", 2.0),
                 size=a("nsize", 5))
    elif op == "Flatten":
        add_node("Flatten", ins, outs, name, axis=1)
    elif op == "Reshape":
        shape = add_const(name + "_shape",
                          np.asarray(a("shape"), np.int64))
        add_node("Reshape", [ins[0], shape], outs, name)
    elif op == "Dropout":
        if len(outs) > 1:
            raise MXNetError("cannot export Dropout with a consumed "
                             "mask output")
        add_node("Dropout", ins, outs, name, ratio=a("p", 0.5))
    elif op in ("softmax", "SoftmaxActivation"):
        add_node("Softmax", ins, outs, name, axis=a("axis", -1))
    elif op == "log_softmax":
        add_node("LogSoftmax", ins, outs, name, axis=a("axis", -1))
    elif op == "SoftmaxOutput":
        # inference form: softmax over axis 1; label input dropped
        add_node("Softmax", ins[:1], outs, name, axis=1)
    elif op in ("Concat", "concat"):
        add_node("Concat", ins, outs, name, axis=a("dim", 1))
    elif op in ("elemwise_add", "_plus", "broadcast_add"):
        add_node("Add", ins, outs, name)
    elif op in ("elemwise_sub", "_minus", "broadcast_sub"):
        add_node("Sub", ins, outs, name)
    elif op in ("elemwise_mul", "_mul", "broadcast_mul"):
        add_node("Mul", ins, outs, name)
    elif op in ("elemwise_div", "_div", "broadcast_div"):
        add_node("Div", ins, outs, name)
    elif op in ("broadcast_maximum",):
        add_node("Max", ins, outs, name)
    elif op in ("broadcast_minimum",):
        add_node("Min", ins, outs, name)
    elif op in ("add_n", "ElementWiseSum"):
        add_node("Sum", ins, outs, name)
    elif op == "dot":
        if a("transpose_a", False) or a("transpose_b", False):
            raise MXNetError("cannot export transposed dot")
        add_node("MatMul", ins, outs, name)
    elif op in ("_plus_scalar", "_minus_scalar", "_mul_scalar",
                "_div_scalar", "_power_scalar"):
        c = add_const(name + "_scalar",
                      np.asarray(a("scalar"), np.float32))
        onnx_op = {"_plus_scalar": "Add", "_minus_scalar": "Sub",
                   "_mul_scalar": "Mul", "_div_scalar": "Div",
                   "_power_scalar": "Pow"}[op]
        add_node(onnx_op, [ins[0], c], outs, name)
    elif op == "transpose":
        add_node("Transpose", ins, outs, name, perm=a("axes") or None)
    elif op == "expand_dims":
        add_node("Unsqueeze", ins, outs, name, axes=(a("axis"),))
    elif op == "squeeze":
        ax = a("axis")
        add_node("Squeeze", ins, outs, name,
                 axes=(ax,) if isinstance(ax, int) else ax)
    elif op == "clip":
        add_node("Clip", ins, outs, name, min=a("a_min"), max=a("a_max"))
    elif op == "Pad":
        pw = a("pad_width")
        n = len(pw) // 2
        pads = [int(pw[2 * i]) for i in range(n)] + \
               [int(pw[2 * i + 1]) for i in range(n)]
        add_node("Pad", ins, outs, name, mode=a("mode", "constant"),
                 pads=pads, value=a("constant_value", 0.0))
    elif op in ("sum", "mean", "max", "min", "prod"):
        onnx_op = {"sum": "ReduceSum", "mean": "ReduceMean",
                   "max": "ReduceMax", "min": "ReduceMin",
                   "prod": "ReduceProd"}[op]
        ax = a("axis")
        add_node(onnx_op, ins, outs, name,
                 axes=(ax,) if isinstance(ax, int) else (ax or None),
                 keepdims=int(bool(a("keepdims", False))))
    elif op == "slice_axis":
        add_node("Slice", ins, outs, name, axes=(a("axis"),),
                 starts=(a("begin"),),
                 ends=(2 ** 31 - 1 if a("end") is None else a("end"),))
    elif op in ("SliceChannel", "split"):
        add_node("Split", ins, outs, name, axis=a("axis", 1))
    elif op == "Cast":
        add_node("Cast", ins, outs, name,
                 to=_NP_DT[np.dtype(a("dtype"))])
    elif op == "Embedding":
        # ONNX Gather(weight, indices): weight is input[1] on our side
        add_node("Gather", [ins[1], ins[0]], outs, name, axis=0)
    elif op == "take":
        add_node("Gather", ins, outs, name, axis=a("axis", 0))
    elif op == "UpSampling":
        # opset 9: scales is a required input, not an attribute
        sc = add_const(name + "_scales",
                       np.asarray([1.0, 1.0, float(a("scale")),
                                   float(a("scale"))], np.float32))
        mode = {"nearest": "nearest",
                "bilinear": "linear"}[a("sample_type", "nearest")]
        add_node("Upsample", [ins[0], sc], outs, name, mode=mode)
    elif op in ("identity", "_copy", "BlockGrad", "stop_gradient"):
        add_node("Identity", ins, outs, name)
    elif op in ("negative", "abs", "exp", "log", "sqrt", "floor", "ceil",
                "reciprocal", "sign"):
        add_node({"negative": "Neg"}.get(op, op.capitalize()),
                 ins, outs, name)
    elif op == "argmax":
        add_node("ArgMax", ins, outs, name, axis=a("axis", 0),
                 keepdims=int(bool(a("keepdims", False))))
    else:
        raise MXNetError("cannot export op %r to ONNX" % op)


def export_model(sym, params, input_shapes, onnx_file=None,
                 graph_name="mxnet_tpu", opset=9):
    """Export Symbol + params to an ONNX model.

    ``input_shapes``: dict name->shape, or a single shape tuple when the
    symbol has exactly one data input.  Returns the serialized bytes; also
    writes ``onnx_file`` when given.  (Reference analog: the mx2onnx
    direction of contrib.onnx in later reference versions.)
    """
    if not isinstance(input_shapes, dict):
        args = set(sym.list_arguments()) - set(params)
        if len(args) != 1:
            raise MXNetError("pass input_shapes as a dict (inputs: %s)"
                             % sorted(args))
        input_shapes = {args.pop(): tuple(input_shapes)}
    graph = export_graph(sym, params, input_shapes, graph_name)
    model = P.ModelProto(
        ir_version=4, producer_name="mxnet_tpu",
        opset_import=[P.OperatorSetIdProto(domain="", version=opset)],
        graph=graph)
    data = model.serialize()
    if onnx_file:
        with open(onnx_file, "wb") as f:
            f.write(data)
    return data
