"""Dependency-free ONNX protobuf codec (wire format, schema subset).

The environment ships no ``onnx`` package, so :mod:`mxnet_tpu.contrib.onnx`
parses and writes ``.onnx`` files with this hand-rolled protobuf codec.  It
implements the protobuf wire format (varint / 64-bit / length-delimited /
32-bit fields, packed repeated scalars) plus descriptors for the subset of
the stable ONNX schema that model import/export needs: ModelProto,
GraphProto, NodeProto, AttributeProto, TensorProto, ValueInfoProto,
TypeProto(+Tensor), TensorShapeProto(+Dimension), OperatorSetIdProto,
StringStringEntryProto.  Field numbers follow onnx/onnx.proto (IR version 3+,
unchanged since).

Reference analog: the reference's ``contrib/onnx`` relies on the ``onnx``
package for (de)serialization (``python/mxnet/contrib/onnx/onnx2mx/
import_model.py``); here the codec is part of the framework so ONNX
interchange works in hermetic environments.
"""
from __future__ import annotations

import struct
from typing import Dict, Tuple

__all__ = [
    "ModelProto", "GraphProto", "NodeProto", "AttributeProto",
    "TensorProto", "ValueInfoProto", "TypeProto", "TensorTypeProto",
    "TensorShapeProto", "Dimension", "OperatorSetIdProto",
    "StringStringEntryProto", "load", "load_from_bytes", "save",
]

_WIRE_VARINT, _WIRE_I64, _WIRE_LEN, _WIRE_I32 = 0, 1, 2, 5


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def _write_varint(out: bytearray, value: int) -> None:
    if value < 0:
        value += 1 << 64
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _to_signed(value: int) -> int:
    return value - (1 << 64) if value >= (1 << 63) else value


# kind -> (wire type, packable)
_SCALAR_WIRE = {
    "int": (_WIRE_VARINT, True),
    "float": (_WIRE_I32, True),
    "double": (_WIRE_I64, True),
    "bytes": (_WIRE_LEN, False),
    "string": (_WIRE_LEN, False),
}


class Message:
    """Base class: FIELDS maps field number -> (name, kind, repeated).

    kind is 'int' | 'float' | 'double' | 'bytes' | 'string' or a Message
    subclass.  Presence is tracked for HasField(); repeated fields default to
    fresh lists, scalars to proto3 defaults, submessages to None.
    """

    FIELDS: Dict[int, tuple] = {}

    def __init__(self, **kwargs):
        self._present = set()
        for name, kind, repeated in self.FIELDS.values():
            if repeated:
                object.__setattr__(self, name, [])
            elif isinstance(kind, type):
                object.__setattr__(self, name, None)
            else:
                object.__setattr__(self, name, _DEFAULTS[kind])
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __setattr__(self, name, value):
        object.__setattr__(self, name, value)
        if not name.startswith("_"):
            self._present.add(name)

    def HasField(self, name):  # noqa: N802 (protobuf API parity)
        return name in self._present

    # ---- parsing --------------------------------------------------------
    @classmethod
    def parse(cls, data: bytes) -> "Message":
        msg = cls()
        pos, end = 0, len(data)
        while pos < end:
            key, pos = _read_varint(data, pos)
            field_num, wire = key >> 3, key & 7
            spec = cls.FIELDS.get(field_num)
            if spec is None:
                pos = _skip(data, pos, wire)
                continue
            name, kind, repeated = spec
            if isinstance(kind, type):
                if wire != _WIRE_LEN:
                    raise ValueError("submessage field with wire %d" % wire)
                ln, pos = _read_varint(data, pos)
                sub = kind.parse(data[pos:pos + ln])
                pos += ln
                if repeated:
                    getattr(msg, name).append(sub)
                else:
                    setattr(msg, name, sub)
                continue
            if wire == _WIRE_LEN and kind in ("int", "float", "double"):
                # packed repeated scalars
                ln, pos = _read_varint(data, pos)
                chunk_end = pos + ln
                vals = getattr(msg, name)
                while pos < chunk_end:
                    v, pos = _read_scalar(data, pos, kind)
                    vals.append(v)
                msg._present.add(name)
                continue
            v, pos = _read_scalar(data, pos, kind) if wire != _WIRE_LEN \
                else _read_len_delimited(data, pos, kind)
            if repeated:
                getattr(msg, name).append(v)
                msg._present.add(name)
            else:
                setattr(msg, name, v)
        return msg

    # ---- serialization --------------------------------------------------
    def serialize(self) -> bytes:
        out = bytearray()
        for field_num in sorted(self.FIELDS):
            name, kind, repeated = self.FIELDS[field_num]
            value = getattr(self, name)
            if isinstance(kind, type):
                subs = value if repeated else \
                    ([value] if value is not None else [])
                for sub in subs:
                    body = sub.serialize()
                    _write_varint(out, (field_num << 3) | _WIRE_LEN)
                    _write_varint(out, len(body))
                    out += body
                continue
            wire, packable = _SCALAR_WIRE[kind]
            if repeated:
                if not value:
                    continue
                if packable:
                    body = bytearray()
                    for v in value:
                        _write_scalar(body, v, kind)
                    _write_varint(out, (field_num << 3) | _WIRE_LEN)
                    _write_varint(out, len(body))
                    out += body
                else:
                    for v in value:
                        _write_field(out, field_num, v, kind, wire)
                continue
            if name not in self._present and not value:
                continue  # proto3: defaults are omitted
            _write_field(out, field_num, value, kind, wire)
        return bytes(out)

    def __repr__(self):
        items = ", ".join("%s=%r" % (n, getattr(self, n))
                          for n, _, _ in self.FIELDS.values()
                          if n in self._present)
        return "%s(%s)" % (type(self).__name__, items)


_DEFAULTS = {"int": 0, "float": 0.0, "double": 0.0, "bytes": b"",
             "string": ""}


def _skip(data: bytes, pos: int, wire: int) -> int:
    if wire == _WIRE_VARINT:
        _, pos = _read_varint(data, pos)
        return pos
    if wire == _WIRE_I64:
        return pos + 8
    if wire == _WIRE_LEN:
        ln, pos = _read_varint(data, pos)
        return pos + ln
    if wire == _WIRE_I32:
        return pos + 4
    raise ValueError("unsupported wire type %d" % wire)


def _read_scalar(data: bytes, pos: int, kind: str) -> Tuple[object, int]:
    if kind == "int":
        v, pos = _read_varint(data, pos)
        return _to_signed(v), pos
    if kind == "float":
        return struct.unpack_from("<f", data, pos)[0], pos + 4
    if kind == "double":
        return struct.unpack_from("<d", data, pos)[0], pos + 8
    raise ValueError("scalar kind %r with non-len wire" % kind)


def _read_len_delimited(data: bytes, pos: int, kind: str):
    ln, pos = _read_varint(data, pos)
    raw = data[pos:pos + ln]
    pos += ln
    if kind == "string":
        return raw.decode("utf-8", "surrogateescape"), pos
    if kind == "bytes":
        return raw, pos
    raise ValueError("unexpected len-delimited for kind %r" % kind)


def _write_scalar(out: bytearray, value, kind: str) -> None:
    if kind == "int":
        _write_varint(out, int(value))
    elif kind == "float":
        out += struct.pack("<f", float(value))
    elif kind == "double":
        out += struct.pack("<d", float(value))
    else:
        raise ValueError(kind)


def _write_field(out: bytearray, num: int, value, kind: str,
                 wire: int) -> None:
    _write_varint(out, (num << 3) | wire)
    if kind in ("int", "float", "double"):
        _write_scalar(out, value, kind)
    elif kind == "string":
        raw = value.encode("utf-8", "surrogateescape")
        _write_varint(out, len(raw))
        out += raw
    elif kind == "bytes":
        raw = bytes(value)
        _write_varint(out, len(raw))
        out += raw
    else:
        raise ValueError(kind)


# ---------------------------------------------------------------------------
# ONNX schema descriptors (field numbers from onnx/onnx.proto)
# ---------------------------------------------------------------------------

class StringStringEntryProto(Message):
    pass


StringStringEntryProto.FIELDS = {
    1: ("key", "string", False),
    2: ("value", "string", False),
}


class OperatorSetIdProto(Message):
    pass


OperatorSetIdProto.FIELDS = {
    1: ("domain", "string", False),
    2: ("version", "int", False),
}


class TensorProto(Message):
    # DataType enum values used by the converter
    FLOAT, UINT8, INT8, INT32, INT64, BOOL, FLOAT16, DOUBLE = \
        1, 2, 3, 6, 7, 9, 10, 11


TensorProto.FIELDS = {
    1: ("dims", "int", True),
    2: ("data_type", "int", False),
    4: ("float_data", "float", True),
    5: ("int32_data", "int", True),
    6: ("string_data", "bytes", True),
    7: ("int64_data", "int", True),
    8: ("name", "string", False),
    9: ("raw_data", "bytes", False),
    10: ("double_data", "double", True),
    11: ("uint64_data", "int", True),
    12: ("doc_string", "string", False),
}


class Dimension(Message):
    pass


Dimension.FIELDS = {
    1: ("dim_value", "int", False),
    2: ("dim_param", "string", False),
}


class TensorShapeProto(Message):
    pass


TensorShapeProto.FIELDS = {
    1: ("dim", Dimension, True),
}


class TensorTypeProto(Message):
    pass


TensorTypeProto.FIELDS = {
    1: ("elem_type", "int", False),
    2: ("shape", TensorShapeProto, False),
}


class TypeProto(Message):
    pass


TypeProto.FIELDS = {
    1: ("tensor_type", TensorTypeProto, False),
}


class ValueInfoProto(Message):
    pass


ValueInfoProto.FIELDS = {
    1: ("name", "string", False),
    2: ("type", TypeProto, False),
    3: ("doc_string", "string", False),
}


class GraphProto(Message):
    pass


class AttributeProto(Message):
    # AttributeType enum
    FLOAT, INT, STRING, TENSOR, GRAPH = 1, 2, 3, 4, 5
    FLOATS, INTS, STRINGS, TENSORS, GRAPHS = 6, 7, 8, 9, 10


AttributeProto.FIELDS = {
    1: ("name", "string", False),
    2: ("f", "float", False),
    3: ("i", "int", False),
    4: ("s", "bytes", False),
    5: ("t", TensorProto, False),
    # 6: subgraph attr (control flow) — parsed generically if ever present
    7: ("floats", "float", True),
    8: ("ints", "int", True),
    9: ("strings", "bytes", True),
    10: ("tensors", TensorProto, True),
    13: ("doc_string", "string", False),
    20: ("type", "int", False),
}


class NodeProto(Message):
    pass


NodeProto.FIELDS = {
    1: ("input", "string", True),
    2: ("output", "string", True),
    3: ("name", "string", False),
    4: ("op_type", "string", False),
    5: ("attribute", AttributeProto, True),
    6: ("doc_string", "string", False),
    7: ("domain", "string", False),
}


GraphProto.FIELDS = {
    1: ("node", NodeProto, True),
    2: ("name", "string", False),
    5: ("initializer", TensorProto, True),
    10: ("doc_string", "string", False),
    11: ("input", ValueInfoProto, True),
    12: ("output", ValueInfoProto, True),
    13: ("value_info", ValueInfoProto, True),
}


class ModelProto(Message):
    pass


ModelProto.FIELDS = {
    1: ("ir_version", "int", False),
    2: ("producer_name", "string", False),
    3: ("producer_version", "string", False),
    4: ("domain", "string", False),
    5: ("model_version", "int", False),
    6: ("doc_string", "string", False),
    7: ("graph", GraphProto, False),
    8: ("opset_import", OperatorSetIdProto, True),
    14: ("metadata_props", StringStringEntryProto, True),
}


# ---------------------------------------------------------------------------
# file API (onnx.load / onnx.save parity)
# ---------------------------------------------------------------------------

def load_from_bytes(data: bytes) -> ModelProto:
    return ModelProto.parse(data)


def load(path) -> ModelProto:
    with open(path, "rb") as f:
        return load_from_bytes(f.read())


def save(model: ModelProto, path) -> None:
    with open(path, "wb") as f:
        f.write(model.serialize())
