"""Contrib package (parity: python/mxnet/contrib/): quantization,
text utilities, ONNX import, experimental APIs."""
from . import quantization  # noqa: F401
