"""Contrib package (parity: python/mxnet/contrib/): quantization,
text utilities, ONNX import, experimental APIs."""
from . import quantization  # noqa: F401
from . import text          # noqa: F401
from . import onnx          # noqa: F401
from . import onnx_proto    # noqa: F401
from . import tensorboard   # noqa: F401
