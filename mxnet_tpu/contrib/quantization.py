"""INT8 model quantization: graph pass + calibration.

Reference analog: ``python/mxnet/contrib/quantization.py`` (quantize_model,
calib modes none/naive/entropy) driving the C++ graph pass
``src/operator/quantization/quantize_graph_pass.cc``.

Pipeline (same as reference):
1. rewrite the symbol graph: supported ops (Convolution, FullyConnected,
   Pooling, Flatten) become ``_contrib_quantized_*`` nodes fed by
   ``_contrib_quantize`` (activations, on-the-fly min/max) and offline-
   quantized weight/bias vars; each int32 accumulator goes through
   ``_contrib_requantize`` (+calibrated ranges) and lazily through
   ``_contrib_dequantize`` for fp32 consumers;
2. quantize parameters offline (int8 + min/max vars);
3. calibrate: run the fp32 graph on sample data collecting per-layer output
   ranges — ``naive`` records min/max, ``entropy`` minimizes KL divergence
   between the fp32 histogram and its int8 projection (the reference's
   _get_optimal_threshold).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError

def _symbol_of(node, idx=0):
    from ..symbol.symbol import Symbol
    return Symbol([(node, idx)])


def quantize_graph(sym, excluded_sym_names: Sequence[str] = (),
                   th_dict: Optional[Dict[str, Tuple[float, float]]] = None,
                   quantized_dtype: str = "int8"):
    """Rewrite ``sym`` into its int8 form.  Returns (qsym, offline_params)
    where offline_params maps original param name -> role for
    :func:`quantize_params`."""
    from .. import symbol as S
    from ..symbol.symbol import _create
    if quantized_dtype != "int8":
        raise MXNetError("only int8 quantization is supported "
                         "(TPU MXU int8 path)")
    th_dict = th_dict or {}
    excluded = set(excluded_sym_names)

    fp32: Dict[Tuple[int, int], object] = {}   # (node id, out idx) -> Symbol
    qmemo: Dict[Tuple[int, int], Tuple] = {}   # -> (q, min, max) Symbols
    offline: List[str] = []

    def fp32_of(entry):
        node, idx = entry
        return fp32[(id(node), idx)]

    def quantized_of(entry):
        """int8 view of an entry: reuse producer's, else insert quantize."""
        node, idx = entry
        key = (id(node), idx)
        if key in qmemo:
            return qmemo[key]
        data = fp32_of(entry)
        mn = S.min(data)
        mx = S.max(data)
        q = S.contrib.quantize(data, mn, mx, out_type="int8")
        qmemo[key] = (q[0], q[1], q[2])
        return qmemo[key]

    topo = sym._topo()
    for node in topo:
        if node.is_var:
            fp32[(id(node), 0)] = _symbol_of(node)
            continue
        op_name = node.op.name
        ins = node.inputs
        if op_name == "Convolution" and node.name not in excluded or \
                op_name == "FullyConnected" and node.name not in excluded:
            no_bias = str(node.attrs.get("no_bias", "False")).lower() in \
                ("1", "true")
            qd, dmin, dmax = quantized_of(ins[0])
            wnode = ins[1][0]
            if not wnode.is_var:
                raise MXNetError("quantization: %s weight must be a "
                                 "variable" % node.name)
            qw = S.var(wnode.name + "_quantize")
            wmin = S.var(wnode.name + "_min")
            wmax = S.var(wnode.name + "_max")
            offline.append(wnode.name)
            inputs = [qd, qw]
            tail = [dmin, dmax, wmin, wmax]
            if not no_bias:
                bnode = ins[2][0]
                qb = S.var(bnode.name + "_quantize")
                bmin = S.var(bnode.name + "_min")
                bmax = S.var(bnode.name + "_max")
                offline.append(bnode.name)
                inputs.append(qb)
                tail += [bmin, bmax]
            qop = "_contrib_quantized_conv" if op_name == "Convolution" \
                else "_contrib_quantized_fully_connected"
            attrs = {k: v for k, v in node.attrs.items()
                     if not k.startswith("__")}
            node_q = _create(qop, inputs + tail, attrs,
                             name=node.name + "_quantize")
            rq_attrs = {}
            if node.name in th_dict:
                mn_c, mx_c = th_dict[node.name]
                rq_attrs = {"min_calib_range": float(mn_c),
                            "max_calib_range": float(mx_c)}
            rq = _create("_contrib_requantize",
                         [node_q[0], node_q[1], node_q[2]], rq_attrs,
                         name=node.name + "_requantize")
            qmemo[(id(node), 0)] = (rq[0], rq[1], rq[2])
            fp32[(id(node), 0)] = S.contrib.dequantize(rq[0], rq[1], rq[2])
            continue
        pool_ok = op_name != "Pooling" or (
            str(node.attrs.get("pool_type", "max")) in ("max", "avg") and
            str(node.attrs.get("pooling_convention", "valid")) in
            ("valid", "full"))
        if op_name in ("Pooling", "Flatten", "flatten") and pool_ok and \
                node.name not in excluded and \
                (id(ins[0][0]), ins[0][1]) in qmemo:
            # stay int8 when the producer is already quantized
            q, mn, mx = qmemo[(id(ins[0][0]), ins[0][1])]
            attrs = {k: v for k, v in node.attrs.items()
                     if not k.startswith("__")}
            qop = "_contrib_quantized_pooling" if op_name == "Pooling" \
                else "_contrib_quantized_flatten"
            node_q = _create(qop, [q, mn, mx], attrs,
                             name=node.name + "_quantize")
            qmemo[(id(node), 0)] = (node_q[0], node_q[1], node_q[2])
            fp32[(id(node), 0)] = S.contrib.dequantize(
                node_q[0], node_q[1], node_q[2])
            continue
        # default: rebuild the fp32 node on rewritten inputs
        in_syms = [fp32_of(e) for e in ins]
        attrs = {k: v for k, v in node.attrs.items()
                 if not k.startswith("__")}
        new_node = _create(op_name, in_syms, attrs, name=node.name)
        # multi-output nodes (e.g. BatchNorm: out + hidden mean/var) may
        # expose fewer VISIBLE outputs on the rebuilt symbol than
        # node.num_outputs(); map what exists — consumers only reference
        # visible entries in inference graphs
        n_vis = len(new_node._outputs)
        if n_vis > 1:
            for i in range(n_vis):
                fp32[(id(node), i)] = new_node[i]
        else:
            fp32[(id(node), 0)] = new_node

    outs = [fp32_of(e) for e in sym._outputs]
    qsym = outs[0] if len(outs) == 1 else S.Group(outs)
    return qsym, offline


def _to_np(v):
    return np.asarray(v.asnumpy() if hasattr(v, "asnumpy") else v,
                      np.float32)


def fold_batchnorm(sym, arg_params, aux_params):
    """Inference-time BatchNorm folding (the graph-level half of the
    reference's quantize pass; MKLDNN does the same fold inside
    ``src/operator/subgraph/mkldnn/mkldnn_conv.cc``): every BatchNorm whose
    sole producer is a Convolution with variable weights is absorbed into
    that conv's weight/bias::

        W' = W * gamma/sqrt(var+eps)        b' = beta - mean*gamma/sqrt(..)
                                                 (+ b * gamma/sqrt(..))

    Returns ``(folded_sym, folded_args, remaining_auxs)`` with param VALUES
    rewritten; unfoldable BatchNorms are kept as-is."""
    from .. import symbol as S
    from ..symbol.symbol import _create

    new_args = dict(arg_params)
    topo = sym._topo()
    n_cons: Dict[Tuple[int, int], int] = {}
    for node in topo:
        if node.is_var:
            continue
        for e in node.inputs:
            n_cons[(id(e[0]), e[1])] = n_cons.get((id(e[0]), e[1]), 0) + 1

    fp32: Dict[Tuple[int, int], object] = {}
    for node in topo:
        if node.is_var:
            fp32[(id(node), 0)] = _symbol_of(node)
            continue
        ins = node.inputs
        if node.op.name == "BatchNorm" and not ins[0][0].is_var:
            prod = ins[0][0]
            if (prod.op.name == "Convolution"
                    and n_cons.get((id(prod), 0)) == 1
                    and prod.inputs[1][0].is_var):
                # parsed_attrs applies the op's REGISTERED defaults
                # (eps=1e-3, fix_gamma=True) — hand-rolled defaults here
                # silently mis-folded default-attr BatchNorms
                battrs = node.parsed_attrs()
                eps = float(battrs["eps"])
                g = _to_np(arg_params[ins[1][0].name])
                if battrs["fix_gamma"]:
                    g = np.ones_like(g)
                beta = _to_np(arg_params[ins[2][0].name])
                mu = _to_np(aux_params[ins[3][0].name])
                var = _to_np(aux_params[ins[4][0].name])
                sc = g / np.sqrt(var + eps)

                wname = prod.inputs[1][0].name
                W = _to_np(new_args[wname])
                new_args[wname] = W * sc.reshape((-1,) + (1,) * (W.ndim - 1))
                no_bias = prod.parsed_attrs()["no_bias"]
                if no_bias:
                    bias_name = prod.name + "_folded_bias"
                    bias = beta - mu * sc
                else:
                    bias_name = prod.inputs[2][0].name
                    bias = beta + (_to_np(new_args[bias_name]) - mu) * sc
                new_args[bias_name] = bias

                attrs = {k: v for k, v in prod.attrs.items()
                         if not k.startswith("__")}
                attrs["no_bias"] = "False"
                conv_in = fp32[(id(prod.inputs[0][0]), prod.inputs[0][1])]
                fp32[(id(node), 0)] = _create(
                    "Convolution", [conv_in, S.var(wname), S.var(bias_name)],
                    attrs, name=prod.name)
                continue
        in_syms = [fp32[(id(e[0]), e[1])] for e in ins]
        attrs = {k: v for k, v in node.attrs.items()
                 if not k.startswith("__")}
        new_node = _create(node.op.name, in_syms, attrs, name=node.name)
        n_vis = len(new_node._outputs)
        if n_vis > 1:
            for i in range(n_vis):
                fp32[(id(node), i)] = new_node[i]
        else:
            fp32[(id(node), 0)] = new_node

    outs = [fp32[(id(n), i)] for n, i in sym._outputs]
    fsym = outs[0] if len(outs) == 1 else S.Group(outs)
    keep_args = set(fsym.list_arguments())
    keep_aux = set(fsym.list_auxiliary_states())
    return (fsym,
            {k: v for k, v in new_args.items() if k in keep_args},
            {k: v for k, v in aux_params.items() if k in keep_aux})


def quantize_graph_fused(sym, arg_params, th_dict,
                         excluded_sym_names: Sequence[str] = ()):
    """Static-scale fused int8 rewrite (run AFTER :func:`fold_batchnorm`,
    with ``th_dict`` covering conv/FC/add outputs and the ``data`` var).

    TPU-native redesign of the reference's MKLDNN int8 subgraph pass: each
    supported node becomes ONE ``_sg_int8_*`` op whose requantize(+ReLU)
    epilogue is a static multiply/round/clip XLA fuses into the conv, and
    residual adds stay int8 (``_sg_int8_elemwise_add``).  No per-layer
    min/max reductions, no f32 round-trips between quantized ops — the
    glue that made the unfused path 0.80x bf16.  Unsupported consumers get
    a ``_contrib_dequantize_v2`` splice; unsupported producers fall back
    to fp32.  Returns ``(qsym, qargs)`` with qargs holding s8 weights, s32
    biases, and the untouched fp32 params."""
    from .. import symbol as S
    from ..symbol.symbol import _create

    excluded = set(excluded_sym_names)
    topo = sym._topo()
    consumers: Dict[Tuple[int, int], list] = {}
    for node in topo:
        if node.is_var:
            continue
        for e in node.inputs:
            consumers.setdefault((id(e[0]), e[1]), []).append(node)

    def sole_relu_consumer(node):
        cons = consumers.get((id(node), 0), [])
        if len(cons) == 1 and cons[0].op.name == "Activation" \
                and cons[0].parsed_attrs()["act_type"] == "relu" \
                and cons[0].name not in excluded:
            return cons[0]
        return None

    _Q_CONSUMERS = ("Convolution", "FullyConnected", "elemwise_add",
                    "broadcast_add", "_plus", "Pooling", "Flatten",
                    "flatten", "Activation")

    def wants_float(node):
        """True when every consumer stays fp32 (or the node is a graph
        output): emit f32 straight from the s32 accumulator instead of
        s8 + dequantize (skips one rounding, e.g. on logits)."""
        cons = consumers.get((id(node), 0), [])
        return not cons or all(c.op.name not in _Q_CONSUMERS
                               for c in cons)

    fp32: Dict[Tuple[int, int], object] = {}
    qmemo: Dict[Tuple[int, int], Tuple[object, float]] = {}
    fused_relu: Dict[int, Tuple[object, float]] = {}   # relu node id -> q
    qargs: Dict[str, object] = {}

    def fp32_of(entry):
        key = (id(entry[0]), entry[1])
        if key not in fp32 and key in qmemo:
            q, t = qmemo[key]
            fp32[key] = S.contrib.dequantize_v2(q, threshold=float(t))
        return fp32[key]

    def q_of(entry):
        """(s8 symbol, threshold) of an entry, quantizing the fp32 input
        with its calibrated static range when needed."""
        key = (id(entry[0]), entry[1])
        if key in qmemo:
            return qmemo[key]
        name = entry[0].name
        if name in th_dict:
            t = max(abs(th_dict[name][0]), abs(th_dict[name][1]))
            qs = S.contrib.quantize_v2(fp32_of(entry),
                                       min_calib_range=-t,
                                       max_calib_range=t)
            qmemo[key] = (qs[0], t)
            return qmemo[key]
        return None

    def quant_weight(wnode):
        W = _to_np(arg_params[wnode.name])
        t_w = max(float(np.max(np.abs(W))), 1e-30)
        qargs[wnode.name + "_quantize"] = np.clip(
            np.round(W * (127.0 / t_w)), -127, 127).astype(np.int8)
        return S.var(wnode.name + "_quantize"), t_w

    for node in topo:
        if node.is_var:
            fp32[(id(node), 0)] = _symbol_of(node)
            continue
        if id(node) in fused_relu:          # already emitted with producer
            qmemo[(id(node), 0)] = fused_relu[id(node)]
            continue
        op_name, ins = node.op.name, node.inputs

        pattrs = None if node.is_var else node.parsed_attrs()
        if op_name in ("Convolution", "FullyConnected") \
                and node.name not in excluded and node.name in th_dict \
                and ins[1][0].is_var \
                and (op_name != "Convolution"
                     or len(pattrs["kernel"]) == 2) \
                and q_of(ins[0]) is not None:
            # (1-D/3-D convs fall through to fp32: _sg_int8_conv lowers
            # with 2-D NCHW dimension numbers)
            qd, t_in = q_of(ins[0])
            qw, t_w = quant_weight(ins[1][0])
            inputs = [qd, qw]
            no_bias = pattrs["no_bias"]
            if not no_bias:
                b = _to_np(arg_params[ins[2][0].name])
                bname = ins[2][0].name + "_q32"
                qargs[bname] = np.round(
                    b * (127.0 / t_in) * (127.0 / t_w)).astype(np.int64) \
                    .clip(-2**31 + 1, 2**31 - 1).astype(np.int32)
                inputs.append(S.var(bname))
            relu = sole_relu_consumer(node)
            t_out = max(abs(th_dict[node.name][0]),
                        abs(th_dict[node.name][1]))
            attrs = {k: v for k, v in node.attrs.items()
                     if not k.startswith("__")}
            attrs["fuse_relu"] = bool(relu)
            qop = "_sg_int8_conv" if op_name == "Convolution" \
                else "_sg_int8_fully_connected"
            if relu is None and wants_float(node):
                attrs["scale_out"] = t_in * t_w / (127.0 * 127.0)
                attrs["dequant_out"] = True
                fp32[(id(node), 0)] = _create(
                    qop, inputs, attrs, name=node.name + "_int8")
                continue
            attrs["scale_out"] = t_in * t_w / (127.0 * t_out)
            out = _create(qop, inputs, attrs, name=node.name + "_int8")
            qmemo[(id(node), 0)] = (out, t_out)
            if relu is not None:
                fused_relu[id(relu)] = (out, t_out)
            continue

        if op_name in ("elemwise_add", "broadcast_add", "_plus") \
                and node.name not in excluded and node.name in th_dict:
            qa, qb = q_of(ins[0]), q_of(ins[1])
            if qa is not None and qb is not None:
                (sa, ta), (sb, tb) = qa, qb
                relu = sole_relu_consumer(node)
                t_out = max(abs(th_dict[node.name][0]),
                            abs(th_dict[node.name][1]))
                out = _create("_sg_int8_elemwise_add", [sa, sb],
                              {"scale_a": ta / t_out, "scale_b": tb / t_out,
                               "fuse_relu": bool(relu)},
                              name=node.name + "_int8")
                qmemo[(id(node), 0)] = (out, t_out)
                if relu is not None:
                    fused_relu[id(relu)] = (out, t_out)
                continue

        if op_name == "Pooling" and node.name not in excluded \
                and pattrs["pool_type"] == "max" \
                and not pattrs["global_pool"] \
                and (id(ins[0][0]), ins[0][1]) in qmemo:
            q, t = qmemo[(id(ins[0][0]), ins[0][1])]
            attrs = {k: v for k, v in node.attrs.items()
                     if not k.startswith("__")}
            out = _create("_sg_int8_pooling", [q], attrs,
                          name=node.name + "_int8")
            qmemo[(id(node), 0)] = (out, t)
            continue

        if op_name == "Pooling" and node.name not in excluded \
                and pattrs["pool_type"] == "avg" \
                and pattrs["global_pool"] \
                and (id(ins[0][0]), ins[0][1]) in qmemo:
            # s8 head (round 5): the mean preserves the threshold, so the
            # chain stays quantized into the final FC (which then runs
            # s8xs8->s32 with a dequantized f32 output)
            q, t = qmemo[(id(ins[0][0]), ins[0][1])]
            out = _create("_sg_int8_global_avg_pool", [q], {},
                          name=node.name + "_int8")
            qmemo[(id(node), 0)] = (out, t)
            continue

        if op_name in ("Flatten", "flatten", "Activation") \
                and (id(ins[0][0]), ins[0][1]) in qmemo:
            q, t = qmemo[(id(ins[0][0]), ins[0][1])]
            if op_name == "Activation" \
                    and pattrs["act_type"] == "relu":
                # unfused standalone relu on s8: clip at zero, free
                out = _create("_sg_int8_elemwise_add", [q, q],
                              {"scale_a": 1.0, "scale_b": 0.0,
                               "fuse_relu": True},
                              name=node.name + "_int8")
                qmemo[(id(node), 0)] = (out, t)
                continue
            if op_name in ("Flatten", "flatten"):
                out = S.Flatten(q)
                qmemo[(id(node), 0)] = (out, t)
                continue

        # fp32 fallback: rebuild on dequantized inputs
        in_syms = [fp32_of(e) for e in ins]
        attrs = {k: v for k, v in node.attrs.items()
                 if not k.startswith("__")}
        new_node = _create(op_name, in_syms, attrs, name=node.name)
        n_vis = len(new_node._outputs)
        if n_vis > 1:
            for i in range(n_vis):
                fp32[(id(node), i)] = new_node[i]
        else:
            fp32[(id(node), 0)] = new_node

    outs = [fp32_of(e) for e in sym._outputs]
    qsym = outs[0] if len(outs) == 1 else S.Group(outs)
    for name in qsym.list_arguments():
        if name not in qargs and name in arg_params:
            qargs[name] = _to_np(arg_params[name])
    return qsym, qargs


def quantize_params(qsym, params):
    """Offline int8 parameter quantization (reference _quantize_params):
    for every ``X_quantize`` argument of ``qsym``, quantize ``params[X]``."""
    from .. import nd
    qargs = {}
    arg_names = set(qsym.list_arguments())
    for name in arg_names:
        if name.endswith("_quantize"):
            base = name[:-len("_quantize")]
            val = params[base]
            # route through the same op as activation quantization so the
            # scale/round/clip convention has a single definition
            q, mn, mx = nd.contrib.quantize(val, val.min(), val.max(),
                                            out_type="int8")
            qargs[name] = q
            qargs[base + "_min"] = mn
            qargs[base + "_max"] = mx
        elif name in params:
            qargs[name] = params[name]
    return qargs


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------
def _collect_layer_outputs(sym, arg_params, aux_params, ctx, calib_data,
                           collect_names, num_calib_examples=None,
                           data_names=("data",), label_names=("softmax_label",)):
    """Run the fp32 graph over calib batches, returning {name: [np arrays]}
    for each collected node output (reference _LayerOutputCollector)."""
    from .. import symbol as S
    from .. import nd
    name_to_node = {}
    for node in sym._topo():
        if not node.is_var:
            name_to_node[node.name] = node
    out_syms = [_symbol_of(name_to_node[n]) for n in collect_names]
    group = S.Group(out_syms)
    collected = {n: [] for n in collect_names}
    seen = 0
    calib_data.reset()
    ex = None
    bound_shapes = None
    for batch in calib_data:
        shapes = tuple(tuple(a.shape) for a in batch.data)
        if ex is None or shapes != bound_shapes:
            # bind once per batch SHAPE (normally once total): a fresh
            # Executor per batch would re-trace and re-compile the whole
            # fp32 graph every iteration; a ragged final batch rebinds
            # instead of silently broadcasting into the old buffers
            args = dict(arg_params)
            for dn, arr in zip(data_names, batch.data):
                args[dn] = arr
            ex = group.bind(ctx, args, aux_states=dict(aux_params),
                            grad_req="null")
            bound_shapes = shapes
        else:
            for dn, arr in zip(data_names, batch.data):
                ex.arg_dict[dn][:] = arr
        outs = ex.forward(is_train=False)
        for n, o in zip(collect_names, outs):
            collected[n].append(o.asnumpy())
        seen += batch.data[0].shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    return collected


def _get_optimal_threshold(arr, num_bins=2001, num_quantized_bins=255):
    """KL-divergence-optimal |threshold| (reference _get_optimal_threshold).

    Builds a histogram of the fp32 values and picks the symmetric clip
    threshold whose int8 projection minimizes KL(p || q).
    """
    a = np.abs(np.concatenate([x.ravel() for x in arr]))
    amax = float(a.max()) if a.size else 1e-8
    if amax < 1e-8:
        return 1e-8
    hist, edges = np.histogram(a, bins=num_bins, range=(0, amax))
    best_kl, best_t = np.inf, amax
    # candidate thresholds sweep the upper half of the histogram
    for i in range(num_quantized_bins // 2, num_bins + 1,
                   max(1, num_bins // 64)):
        t = edges[i] if i < len(edges) else amax
        p = hist[:i].astype(np.float64).copy()
        p[-1] += hist[i:].sum()                     # clip outliers into edge
        if p.sum() == 0:
            continue
        # project p onto num_quantized_bins then expand back
        factor = i / num_quantized_bins
        idx = (np.arange(i) / factor).astype(np.int64).clip(
            0, num_quantized_bins - 1)
        q_small = np.bincount(idx, weights=p, minlength=num_quantized_bins)
        counts = np.bincount(idx, minlength=num_quantized_bins)
        q = np.where(counts[idx] > 0, q_small[idx] / counts[idx], 0)
        pn = p / p.sum()
        qn = q / q.sum() if q.sum() > 0 else q
        mask = pn > 0
        kl = float(np.sum(pn[mask] * np.log(
            pn[mask] / np.maximum(qn[mask], 1e-12))))
        if kl < best_kl:
            best_kl, best_t = kl, float(t)
    return max(best_t, 1e-8)


def _calibrate(sym, arg_params, aux_params, ctx, calib_data, collect,
               calib_mode, num_calib_examples, data_names, label_names,
               logger=None):
    outputs = _collect_layer_outputs(
        sym, arg_params, aux_params, ctx, calib_data, collect,
        num_calib_examples, data_names, label_names)
    th_dict = {}
    for name, arrs in outputs.items():
        if calib_mode == "naive":
            t = max(abs(float(np.min([a.min() for a in arrs]))),
                    abs(float(np.max([a.max() for a in arrs]))))
        elif calib_mode == "entropy":
            t = _get_optimal_threshold(arrs)
        else:
            raise MXNetError("unknown calib_mode %r" % calib_mode)
        th_dict[name] = (-t, t)
        if logger:
            logger.info("calibrated %s: threshold=%f", name, t)
    return th_dict


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=None, calib_mode="entropy",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", fuse=False, logger=None):
    """Quantize a model (parity: python/mxnet/contrib/quantization.py
    quantize_model).  Returns (qsym, qarg_params, aux_params).

    ``fuse=True`` selects the TPU-native static-scale pipeline (the role
    of the reference's MKLDNN int8 subgraph backend): BatchNorms are
    folded into convs, calibration covers conv/FC/residual-add outputs
    plus the data input, and the graph is rewritten with the fused
    ``_sg_int8_*`` ops — requantize+ReLU epilogues fused into each conv,
    int8 residual adds, no dynamic range reductions.  Requires
    ``calib_mode`` != none (static scales need calibration)."""
    from .. import context as _ctx_mod
    ctx = ctx or _ctx_mod.current_context()
    excluded = excluded_sym_names or []

    if fuse:
        if not calib_mode or calib_mode == "none" or calib_data is None:
            raise MXNetError("fuse=True needs calib_mode naive/entropy "
                             "and calib_data (static scales)")
        from .. import nd
        fsym, fargs, fauxs = fold_batchnorm(sym, arg_params, aux_params)
        fargs = {k: (v if hasattr(v, "_data") else nd.array(v))
                 for k, v in fargs.items()}
        fauxs = {k: (v if hasattr(v, "_data") else nd.array(v))
                 for k, v in fauxs.items()}
        collect = [n.name for n in fsym._topo()
                   if not n.is_var and n.op.name in
                   ("Convolution", "FullyConnected", "elemwise_add",
                    "broadcast_add", "_plus")
                   and n.name not in excluded]
        th_dict = _calibrate(fsym, fargs, fauxs, ctx, calib_data, collect,
                             calib_mode, num_calib_examples, data_names,
                             label_names, logger)
        # the data input's own range (naive min/max over the calib set)
        calib_data.reset()
        dmax, seen = 0.0, 0
        for batch in calib_data:
            for arr in batch.data:
                dmax = max(dmax, float(np.max(np.abs(
                    arr.asnumpy() if hasattr(arr, "asnumpy") else arr))))
            seen += batch.data[0].shape[0]
            if num_calib_examples is not None and \
                    seen >= num_calib_examples:
                break
        for dn in data_names:
            th_dict[dn] = (-max(dmax, 1e-8), max(dmax, 1e-8))
        qsym, qargs = quantize_graph_fused(fsym, fargs, th_dict, excluded)
        qarg_params = {k: (v if hasattr(v, "asnumpy") else nd.array(v))
                       for k, v in qargs.items()}
        return qsym, qarg_params, dict(fauxs)

    th_dict = {}
    if calib_mode and calib_mode != "none":
        if calib_data is None:
            raise MXNetError("calib_data is required for calib_mode=%r"
                             % calib_mode)
        collect = [n.name for n in sym._topo()
                   if not n.is_var and n.op.name in
                   ("Convolution", "FullyConnected")
                   and n.name not in excluded]
        th_dict = _calibrate(sym, arg_params, aux_params, ctx, calib_data,
                             collect, calib_mode, num_calib_examples,
                             data_names, label_names, logger)

    qsym, _ = quantize_graph(sym, excluded, th_dict, quantized_dtype)
    qarg_params = quantize_params(qsym, arg_params)
    return qsym, qarg_params, aux_params
