"""INT8 model quantization: graph pass + calibration.

Reference analog: ``python/mxnet/contrib/quantization.py`` (quantize_model,
calib modes none/naive/entropy) driving the C++ graph pass
``src/operator/quantization/quantize_graph_pass.cc``.

Pipeline (same as reference):
1. rewrite the symbol graph: supported ops (Convolution, FullyConnected,
   Pooling, Flatten) become ``_contrib_quantized_*`` nodes fed by
   ``_contrib_quantize`` (activations, on-the-fly min/max) and offline-
   quantized weight/bias vars; each int32 accumulator goes through
   ``_contrib_requantize`` (+calibrated ranges) and lazily through
   ``_contrib_dequantize`` for fp32 consumers;
2. quantize parameters offline (int8 + min/max vars);
3. calibrate: run the fp32 graph on sample data collecting per-layer output
   ranges — ``naive`` records min/max, ``entropy`` minimizes KL divergence
   between the fp32 histogram and its int8 projection (the reference's
   _get_optimal_threshold).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError

def _symbol_of(node, idx=0):
    from ..symbol.symbol import Symbol
    return Symbol([(node, idx)])


def quantize_graph(sym, excluded_sym_names: Sequence[str] = (),
                   th_dict: Optional[Dict[str, Tuple[float, float]]] = None,
                   quantized_dtype: str = "int8"):
    """Rewrite ``sym`` into its int8 form.  Returns (qsym, offline_params)
    where offline_params maps original param name -> role for
    :func:`quantize_params`."""
    from .. import symbol as S
    from ..symbol.symbol import _create
    if quantized_dtype != "int8":
        raise MXNetError("only int8 quantization is supported "
                         "(TPU MXU int8 path)")
    th_dict = th_dict or {}
    excluded = set(excluded_sym_names)

    fp32: Dict[Tuple[int, int], object] = {}   # (node id, out idx) -> Symbol
    qmemo: Dict[Tuple[int, int], Tuple] = {}   # -> (q, min, max) Symbols
    offline: List[str] = []

    def fp32_of(entry):
        node, idx = entry
        return fp32[(id(node), idx)]

    def quantized_of(entry):
        """int8 view of an entry: reuse producer's, else insert quantize."""
        node, idx = entry
        key = (id(node), idx)
        if key in qmemo:
            return qmemo[key]
        data = fp32_of(entry)
        mn = S.min(data)
        mx = S.max(data)
        q = S.contrib.quantize(data, mn, mx, out_type="int8")
        qmemo[key] = (q[0], q[1], q[2])
        return qmemo[key]

    topo = sym._topo()
    for node in topo:
        if node.is_var:
            fp32[(id(node), 0)] = _symbol_of(node)
            continue
        op_name = node.op.name
        ins = node.inputs
        if op_name == "Convolution" and node.name not in excluded or \
                op_name == "FullyConnected" and node.name not in excluded:
            no_bias = str(node.attrs.get("no_bias", "False")).lower() in \
                ("1", "true")
            qd, dmin, dmax = quantized_of(ins[0])
            wnode = ins[1][0]
            if not wnode.is_var:
                raise MXNetError("quantization: %s weight must be a "
                                 "variable" % node.name)
            qw = S.var(wnode.name + "_quantize")
            wmin = S.var(wnode.name + "_min")
            wmax = S.var(wnode.name + "_max")
            offline.append(wnode.name)
            inputs = [qd, qw]
            tail = [dmin, dmax, wmin, wmax]
            if not no_bias:
                bnode = ins[2][0]
                qb = S.var(bnode.name + "_quantize")
                bmin = S.var(bnode.name + "_min")
                bmax = S.var(bnode.name + "_max")
                offline.append(bnode.name)
                inputs.append(qb)
                tail += [bmin, bmax]
            qop = "_contrib_quantized_conv" if op_name == "Convolution" \
                else "_contrib_quantized_fully_connected"
            attrs = {k: v for k, v in node.attrs.items()
                     if not k.startswith("__")}
            node_q = _create(qop, inputs + tail, attrs,
                             name=node.name + "_quantize")
            rq_attrs = {}
            if node.name in th_dict:
                mn_c, mx_c = th_dict[node.name]
                rq_attrs = {"min_calib_range": float(mn_c),
                            "max_calib_range": float(mx_c)}
            rq = _create("_contrib_requantize",
                         [node_q[0], node_q[1], node_q[2]], rq_attrs,
                         name=node.name + "_requantize")
            qmemo[(id(node), 0)] = (rq[0], rq[1], rq[2])
            fp32[(id(node), 0)] = S.contrib.dequantize(rq[0], rq[1], rq[2])
            continue
        pool_ok = op_name != "Pooling" or (
            str(node.attrs.get("pool_type", "max")) in ("max", "avg") and
            str(node.attrs.get("pooling_convention", "valid")) in
            ("valid", "full"))
        if op_name in ("Pooling", "Flatten", "flatten") and pool_ok and \
                node.name not in excluded and \
                (id(ins[0][0]), ins[0][1]) in qmemo:
            # stay int8 when the producer is already quantized
            q, mn, mx = qmemo[(id(ins[0][0]), ins[0][1])]
            attrs = {k: v for k, v in node.attrs.items()
                     if not k.startswith("__")}
            qop = "_contrib_quantized_pooling" if op_name == "Pooling" \
                else "_contrib_quantized_flatten"
            node_q = _create(qop, [q, mn, mx], attrs,
                             name=node.name + "_quantize")
            qmemo[(id(node), 0)] = (node_q[0], node_q[1], node_q[2])
            fp32[(id(node), 0)] = S.contrib.dequantize(
                node_q[0], node_q[1], node_q[2])
            continue
        # default: rebuild the fp32 node on rewritten inputs
        in_syms = [fp32_of(e) for e in ins]
        attrs = {k: v for k, v in node.attrs.items()
                 if not k.startswith("__")}
        new_node = _create(op_name, in_syms, attrs, name=node.name)
        # multi-output nodes (e.g. BatchNorm: out + hidden mean/var) may
        # expose fewer VISIBLE outputs on the rebuilt symbol than
        # node.num_outputs(); map what exists — consumers only reference
        # visible entries in inference graphs
        n_vis = len(new_node._outputs)
        if n_vis > 1:
            for i in range(n_vis):
                fp32[(id(node), i)] = new_node[i]
        else:
            fp32[(id(node), 0)] = new_node

    outs = [fp32_of(e) for e in sym._outputs]
    qsym = outs[0] if len(outs) == 1 else S.Group(outs)
    return qsym, offline


def quantize_params(qsym, params):
    """Offline int8 parameter quantization (reference _quantize_params):
    for every ``X_quantize`` argument of ``qsym``, quantize ``params[X]``."""
    from .. import nd
    qargs = {}
    arg_names = set(qsym.list_arguments())
    for name in arg_names:
        if name.endswith("_quantize"):
            base = name[:-len("_quantize")]
            val = params[base]
            # route through the same op as activation quantization so the
            # scale/round/clip convention has a single definition
            q, mn, mx = nd.contrib.quantize(val, val.min(), val.max(),
                                            out_type="int8")
            qargs[name] = q
            qargs[base + "_min"] = mn
            qargs[base + "_max"] = mx
        elif name in params:
            qargs[name] = params[name]
    return qargs


# ---------------------------------------------------------------------------
# calibration
# ---------------------------------------------------------------------------
def _collect_layer_outputs(sym, arg_params, aux_params, ctx, calib_data,
                           collect_names, num_calib_examples=None,
                           data_names=("data",), label_names=("softmax_label",)):
    """Run the fp32 graph over calib batches, returning {name: [np arrays]}
    for each collected node output (reference _LayerOutputCollector)."""
    from .. import symbol as S
    from .. import nd
    name_to_node = {}
    for node in sym._topo():
        if not node.is_var:
            name_to_node[node.name] = node
    out_syms = [_symbol_of(name_to_node[n]) for n in collect_names]
    group = S.Group(out_syms)
    collected = {n: [] for n in collect_names}
    seen = 0
    calib_data.reset()
    ex = None
    bound_shapes = None
    for batch in calib_data:
        shapes = tuple(tuple(a.shape) for a in batch.data)
        if ex is None or shapes != bound_shapes:
            # bind once per batch SHAPE (normally once total): a fresh
            # Executor per batch would re-trace and re-compile the whole
            # fp32 graph every iteration; a ragged final batch rebinds
            # instead of silently broadcasting into the old buffers
            args = dict(arg_params)
            for dn, arr in zip(data_names, batch.data):
                args[dn] = arr
            ex = group.bind(ctx, args, aux_states=dict(aux_params),
                            grad_req="null")
            bound_shapes = shapes
        else:
            for dn, arr in zip(data_names, batch.data):
                ex.arg_dict[dn][:] = arr
        outs = ex.forward(is_train=False)
        for n, o in zip(collect_names, outs):
            collected[n].append(o.asnumpy())
        seen += batch.data[0].shape[0]
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    return collected


def _get_optimal_threshold(arr, num_bins=2001, num_quantized_bins=255):
    """KL-divergence-optimal |threshold| (reference _get_optimal_threshold).

    Builds a histogram of the fp32 values and picks the symmetric clip
    threshold whose int8 projection minimizes KL(p || q).
    """
    a = np.abs(np.concatenate([x.ravel() for x in arr]))
    amax = float(a.max()) if a.size else 1e-8
    if amax < 1e-8:
        return 1e-8
    hist, edges = np.histogram(a, bins=num_bins, range=(0, amax))
    best_kl, best_t = np.inf, amax
    # candidate thresholds sweep the upper half of the histogram
    for i in range(num_quantized_bins // 2, num_bins + 1,
                   max(1, num_bins // 64)):
        t = edges[i] if i < len(edges) else amax
        p = hist[:i].astype(np.float64).copy()
        p[-1] += hist[i:].sum()                     # clip outliers into edge
        if p.sum() == 0:
            continue
        # project p onto num_quantized_bins then expand back
        factor = i / num_quantized_bins
        idx = (np.arange(i) / factor).astype(np.int64).clip(
            0, num_quantized_bins - 1)
        q_small = np.bincount(idx, weights=p, minlength=num_quantized_bins)
        counts = np.bincount(idx, minlength=num_quantized_bins)
        q = np.where(counts[idx] > 0, q_small[idx] / counts[idx], 0)
        pn = p / p.sum()
        qn = q / q.sum() if q.sum() > 0 else q
        mask = pn > 0
        kl = float(np.sum(pn[mask] * np.log(
            pn[mask] / np.maximum(qn[mask], 1e-12))))
        if kl < best_kl:
            best_kl, best_t = kl, float(t)
    return max(best_t, 1e-8)


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   label_names=("softmax_label",), ctx=None,
                   excluded_sym_names=None, calib_mode="entropy",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", logger=None):
    """Quantize a model (parity: python/mxnet/contrib/quantization.py
    quantize_model).  Returns (qsym, qarg_params, aux_params)."""
    from .. import context as _ctx_mod
    ctx = ctx or _ctx_mod.current_context()
    excluded = excluded_sym_names or []

    th_dict = {}
    if calib_mode and calib_mode != "none":
        if calib_data is None:
            raise MXNetError("calib_data is required for calib_mode=%r"
                             % calib_mode)
        collect = [n.name for n in sym._topo()
                   if not n.is_var and n.op.name in
                   ("Convolution", "FullyConnected")
                   and n.name not in excluded]
        outputs = _collect_layer_outputs(
            sym, arg_params, aux_params, ctx, calib_data, collect,
            num_calib_examples, data_names, label_names)
        for name, arrs in outputs.items():
            if calib_mode == "naive":
                t = max(abs(float(np.min([a.min() for a in arrs]))),
                        abs(float(np.max([a.max() for a in arrs]))))
            elif calib_mode == "entropy":
                t = _get_optimal_threshold(arrs)
            else:
                raise MXNetError("unknown calib_mode %r" % calib_mode)
            th_dict[name] = (-t, t)
            if logger:
                logger.info("calibrated %s: threshold=%f", name, t)

    qsym, _ = quantize_graph(sym, excluded, th_dict, quantized_dtype)
    qarg_params = quantize_params(qsym, arg_params)
    return qsym, qarg_params, aux_params
