"""Executor: a bound symbolic graph, compiled whole by XLA.

Reference analog: ``include/mxnet/executor.h`` + ``src/executor/
graph_executor.cc`` (GraphExecutor::Init/Forward/Backward, SURVEY.md N6).

TPU-native design: binding builds ONE pure function over the graph and
``jax.jit``s it — XLA takes over everything GraphExecutor did by hand:
memory planning (PlanMemory pass → XLA buffer assignment), op fusion (bulk
exec segments → XLA fusion), layout, and stream scheduling.  The backward
graph is ``jax.vjp`` of that function (the nnvm Gradient pass analog); the
fused ``forward_backward`` entry used by Module.fit compiles forward+backward
into a single XLA program so training steps are one device launch.
Monitor callbacks (GraphExecutor::SetMonitorCallback, graph_executor.cc:123)
run through an un-jitted eager replay of the same plan.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError, AttrDict
from .context import Context
from . import atlas as _atlas
from . import random as _random
from . import telemetry as _telemetry
from . import health as _health
from . import memwatch as _memwatch
from . import program_cache as _program_cache

__all__ = ["Executor"]

# wall-time histograms fed through profiler.span so the Chrome trace and
# the metrics registry share one measurement per call.  These measure the
# python DISPATCH of the (async) jitted program — on the fused/mesh paths
# the device executes long after the span closes — hence the _dispatch_
# names; device-side attribution lives in atlas.py / health.py
_FWD_TIME = _telemetry.histogram(
    "executor_forward_dispatch_seconds",
    "Executor.forward dispatch wall time (async: excludes device execution)")
_BWD_TIME = _telemetry.histogram(
    "executor_backward_dispatch_seconds",
    "Executor.backward dispatch wall time (async: excludes device execution)")
_FWDBWD_TIME = _telemetry.histogram(
    "executor_forward_backward_dispatch_seconds",
    "Fused Executor.forward_backward dispatch wall time (async: excludes "
    "device execution)")

# whole-graph program observability: the executor's jitted forward is one
# XLA program per (mode, input-shape signature), so its cache lookups join
# the SAME compile metrics ops/registry.py feeds for per-op entries — a
# serving bucket set that stays within its declared programs shows exactly
# len(buckets) misses here and nothing but hits afterwards.
_PROG_HITS = _telemetry.counter(
    "op_jit_cache_hits_total",
    "Operator jit-cache lookups served by an existing entry", ("op",))
_PROG_MISSES = _telemetry.counter(
    "op_jit_cache_misses_total",
    "Operator jit-cache lookups that built a new entry", ("op",))


class _Plan:
    """Precomputed execution plan for a symbol graph."""

    def __init__(self, symbol, train: bool):
        from .symbol.symbol import _Node  # noqa: F401

        self.topo = symbol._topo()
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.out_entries = [(id(n), i) for n, i in symbol._outputs]
        aux_ids = {}
        for node in self.topo:
            if node.is_var and node.name in self.aux_names:
                aux_ids[id(node)] = node.name
        self.steps = []
        self.n_rng = 0
        for node in self.topo:
            if node.is_var:
                continue
            attrs = node.parsed_attrs()
            if node.op.train_aware:
                attrs = AttrDict({**attrs, "__train__": train})
            if node.op.nin == -1 and "num_args" in node.op.params:
                attrs = AttrDict({**attrs, "num_args": len(node.inputs)})
            rng_slot = None
            if node.op.needs_rng:
                rng_slot = self.n_rng
                self.n_rng += 1
            # aux writeback: map op output index -> aux name
            wb = {}
            if train:
                for oi, ii in node.op.get_aux_writeback(attrs).items():
                    if ii < len(node.inputs):
                        src = node.inputs[ii][0]
                        if id(src) in aux_ids:
                            wb[oi] = aux_ids[id(src)]
            self.steps.append((node, attrs, rng_slot, wb))
        # trace-time formulation flags of every op in the graph: whole-graph
        # programs call node.op.fn directly (bypassing the per-op cache in
        # ops/registry.py compiled()), so the values of these flags are baked
        # into the traced program and must join the PROGRAM's cache key
        env_union = set()
        for node, _a, _r, _w in self.steps:
            env_union.update(node.op.env_keys)
        self.env_keys = tuple(sorted(env_union))

    def execute(self, arg_vals: Dict[str, Any], aux_vals: Dict[str, Any],
                keys, monitor=None, placements=None):
        """Run the plan on jax values (traceable under jit).

        ``placements`` maps node ids to jax devices (coarse model parallel,
        the AssignContext pass of graph_executor.cc:315): inputs of a placed
        node are device_put there first — the reference's
        ``kCrossDeviceCopy`` nodes become explicit transfers.  Only valid in
        eager execution (one XLA program runs on one device).
        """
        import jax as _jax

        env: Dict[Tuple[int, int], Any] = {}
        for node in self.topo:
            if node.is_var:
                if node.name in arg_vals:
                    env[(id(node), 0)] = arg_vals[node.name]
                elif node.name in aux_vals:
                    env[(id(node), 0)] = aux_vals[node.name]
                else:
                    raise MXNetError("unbound variable %r" % node.name)
        new_aux = dict(aux_vals)
        for node, attrs, rng_slot, wb in self.steps:
            ins = [env[(id(p), i)] for p, i in node.inputs]
            if placements and id(node) in placements:
                # device_put is traceable (works on vjp tracers) and a
                # no-op for values already on the target device
                dev = placements[id(node)]
                ins = [_jax.device_put(x, dev) for x in ins]
            if rng_slot is not None:
                ins = [keys[rng_slot]] + ins
            # atlas scope: the node's identity survives into the lowered
            # module's debug locations (and through vjp as jvp/transpose
            # wrappers), so fused-program instructions attribute per layer
            with _jax.named_scope(
                    _atlas.scope_name(node.op.name, node.name)):
                res = node.op.fn(attrs, *ins)
            outs = res if isinstance(res, tuple) else (res,)
            for i, o in enumerate(outs):
                env[(id(node), i)] = o
            for oi, aux_name in wb.items():
                new_aux[aux_name] = outs[oi]
            if monitor is not None:
                if getattr(monitor, "monitor_all", False):
                    for i, (p, pi) in enumerate(node.inputs):
                        monitor("%s_input%d" % (node.name, i),
                                env[(id(p), pi)])
                for i in range(node.num_visible()):
                    monitor(node.name + "_output", outs[i])
        outputs = [env[e] for e in self.out_entries]
        return outputs, new_aux

    # -- coarse model parallel: segment bulking ---------------------------
    def build_segments(self, placements, default_device):
        """Partition the step list into contiguous same-device segments
        (the reference's engine bulking, graph_executor.cc:1455): each
        segment compiles into ONE jitted XLA program on its device, so a
        2-group model dispatches 2 programs per pass instead of one per
        op.  Unplaced nodes inherit the running segment's device
        (AssignContext propagation, graph_executor.cc:315)."""
        segments = []
        cur_dev, cur_steps = None, []
        for step in self.steps:
            node = step[0]
            dev = placements.get(id(node),
                                 cur_dev if cur_dev is not None
                                 else default_device)
            if cur_steps and dev is not cur_dev:
                segments.append([cur_dev, cur_steps])
                cur_steps = []
            cur_dev = dev
            cur_steps.append(step)
        if cur_steps:
            segments.append([cur_dev, cur_steps])

        out_set = set(self.out_entries)
        built = []
        for si, (dev, steps) in enumerate(segments):
            local = {id(node) for (node, _, _, _) in steps}
            ins, seen = [], set()
            for (node, _, _, _) in steps:
                for p, i in node.inputs:
                    e = (id(p), i)
                    if id(p) not in local and e not in seen:
                        seen.add(e)
                        ins.append(e)
            # exports: exactly the demanded entries whose producer is local
            consumers_after = set()
            for sj in range(si + 1, len(segments)):
                for (node, _, _, _) in segments[sj][1]:
                    for p, i in node.inputs:
                        consumers_after.add((id(p), i))
            outs = sorted(
                {e for e in (consumers_after | out_set) if e[0] in local},
                key=lambda e: e[1])
            built.append(_Segment(dev, steps, ins, outs))
        return built

    def execute_bulked(self, arg_vals, aux_vals, keys, segments):
        """execute() with per-segment jit (coarse model parallel)."""
        import jax as _jax

        env = {}
        for node in self.topo:
            if node.is_var:
                if node.name in arg_vals:
                    env[(id(node), 0)] = arg_vals[node.name]
                elif node.name in aux_vals:
                    env[(id(node), 0)] = aux_vals[node.name]
                else:
                    raise MXNetError("unbound variable %r" % node.name)
        new_aux = dict(aux_vals)
        for seg in segments:
            ins = [_jax.device_put(env[e], seg.device)
                   for e in seg.in_entries]
            outs, aux_updates = seg.fn(ins, keys)
            for e, v in zip(seg.out_entries, outs):
                env[e] = v
            new_aux.update(aux_updates)
        outputs = [env[e] for e in self.out_entries]
        return outputs, new_aux


class _Segment:
    """One bulked same-device slice of a plan, compiled as one program."""

    def __init__(self, device, steps, in_entries, out_entries):
        import jax as _jax

        self.device = device
        self.steps = steps
        self.in_entries = list(in_entries)
        self.out_entries = list(out_entries)
        in_entries = self.in_entries
        out_entries = self.out_entries

        def fn(ins, keys):
            env = dict(zip(in_entries, ins))
            aux_updates = {}
            for (node, attrs, rng_slot, wb) in steps:
                vals = [env[(id(p), i)] for p, i in node.inputs]
                if rng_slot is not None:
                    vals = [keys[rng_slot]] + vals
                with _jax.named_scope(
                        _atlas.scope_name(node.op.name, node.name)):
                    res = node.op.fn(attrs, *vals)
                outs = res if isinstance(res, tuple) else (res,)
                for i, o in enumerate(outs):
                    env[(id(node), i)] = o
                for oi, aux_name in wb.items():
                    aux_updates[aux_name] = outs[oi]
            return [env[e] for e in out_entries], aux_updates

        self.fn = _jax.jit(fn)


def build_update_program(update_fns, donate_params=True):
    """One donated XLA program applying every parameter's optimizer update.

    ``gvals[i]`` is the list of per-replica gradients for param ``i``;
    replicas are summed in-trace (the local-kvstore reduce), so the whole
    update phase — reduce + N optimizer kernels — is a single device
    launch.  ``donate_params=False`` keeps the weight inputs alive for
    callers whose autograd tape may still reference them (gluon Trainer);
    opt-state is always donated (it never escapes the updater).
    """
    update_fns = tuple(update_fns)

    def fn(pvals, svals, gvals, lrs, wds, ts, rescale):
        new_p, new_s = [], []
        for i, upd in enumerate(update_fns):
            with jax.named_scope(_atlas.GRAD_SYNC):
                g = gvals[i][0]
                for extra in gvals[i][1:]:
                    g = g + extra
            with jax.named_scope(_atlas.optimizer_scope(upd)):
                w, s = upd(pvals[i], g, svals[i], lrs[i], wds[i], rescale,
                           ts[i])
            new_p.append(w)
            new_s.append(s)
        return new_p, new_s

    return jax.jit(fn, donate_argnums=(0, 1) if donate_params else (1,))


class Executor:
    """A bound executor (parity: mxnet.executor.Executor)."""

    # env flags that select a different fused-step program; they join the
    # program cache key so a toggle takes effect without a rebind (same
    # contract as ops/registry.py env_keys).  MXNET_TPU_BF16 decides array
    # dtypes at BIND time, but it also selects per-slot mp update_fns
    # closure-captured by the step program — a mid-process flip must
    # recompile, not reuse.  The attention gates are consulted at trace
    # time wherever a step contains attention — the MultiHeadAttention op
    # (whose own env_keys join the plan union) or the functional
    # parallel/ring_attention forms composed into a custom stage, which
    # the plan's op-level union cannot see — so they are declared here
    # too: a flip re-specializes every cached step program.
    STEP_ENV_KEYS = ("MXNET_TPU_FUSED_STEP", "MXNET_TPU_MESH_STEP",
                     "MXNET_TPU_BF16", "MXNET_TPU_FLASH_ATTENTION",
                     "MXNET_TPU_PALLAS_ATTN")

    def __init__(self, symbol, ctx: Context, args: Dict[str, Any],
                 args_grad: Dict[str, Any], grad_req: Dict[str, str],
                 aux_states: Dict[str, Any], group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx
        self._group2ctx = dict(group2ctx or {})
        self.arg_dict = args
        self.grad_dict = args_grad
        self.aux_dict = aux_states
        self._grad_req = grad_req
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_names = symbol.list_outputs()
        missing = [n for n in self.arg_names if n not in self.arg_dict]
        if missing:
            raise MXNetError("bind: missing arguments %s" % missing)
        self._plans: Dict[bool, _Plan] = {}
        self._jitted: Dict[Any, Any] = {}
        self.outputs_nd: List[Any] = []
        self._last_keys = None
        self._monitor = None
        # mesh-sharded callers (serving mesh Predictor) set _mesh_sig —
        # (mesh shape, sharding specs) — so forward programs specialised
        # for one layout are never reused for another (PR 6 / GL001
        # contract: everything that selects a program joins its cache
        # key).  _program_prefix namespaces health.register_program names
        # (e.g. "serving:<model>:b<bucket>:") so N models/buckets get N
        # distinct /programz entries instead of overwriting "forward".
        self._mesh_sig = None
        self._program_prefix = ""
        self._grad_args = [n for n in self.arg_names
                           if grad_req.get(n, "null") != "null"]

    # -- helpers ----------------------------------------------------------
    @property
    def symbol(self):
        return self._symbol

    def _plan(self, train: bool) -> _Plan:
        if train not in self._plans:
            self._plans[train] = _Plan(self._symbol, train)
        return self._plans[train]

    def _placements(self, plan: _Plan):
        """node-id -> jax.Device from ctx_group attrs + the bind-time
        group2ctx map (AssignContext, graph_executor.cc:315,:1176)."""
        if not self._group2ctx:
            return None
        out = {}
        for node in plan.topo:
            if node.is_var:
                continue
            group = node.attrs.get("ctx_group")
            if group is not None and group in self._group2ctx:
                out[id(node)] = self._group2ctx[group].jax_device
        return out or None

    def _keys(self, plan: _Plan):
        if plan.n_rng == 0:
            return jnp.zeros((0, 2), np.uint32)
        ks = [_random.next_key() for _ in range(plan.n_rng)]
        return jnp.stack(ks)

    def _segments(self, plan, placements):
        """Cached bulked segments for a placed plan (engine bulking)."""
        key = ("segs", id(plan)) + self._plan_env_of(plan)
        if key not in self._jitted:
            _program_cache.ensure_enabled()
            self._jitted[key] = plan.build_segments(
                placements, self._ctx.jax_device)
        return self._jitted[key]

    def _mesh_key(self):
        """Cache-key suffix for the bound mesh layout (empty off-mesh so
        existing single-device keys are unchanged)."""
        return (self._mesh_sig,) if self._mesh_sig is not None else ()

    def _dtype_sig(self):
        """Bound-argument dtype signature.  Joins forward program cache
        keys next to mesh_sig: dtypes are fixed per binding (every
        adoption path casts to the bound dtype), but serving hot-swap
        re-points ``_arg_params`` and a bf16-weights binding must never
        share a program slot with an fp32 one."""
        return tuple(np.dtype(self.arg_dict[n].dtype).name
                     for n in self.arg_names)

    def _fwd_key(self, train: bool):
        return ("fwd", bool(train)) + self._plan_env(train) \
            + self._mesh_key() + self._dtype_sig()

    def _fwd_fn(self, train: bool):
        key = self._fwd_key(train)
        if key not in self._jitted:
            _program_cache.ensure_enabled()
            plan = self._plan(train)
            arg_names, aux_names = plan.arg_names, plan.aux_names
            placements = self._placements(plan)

            if placements:
                # coarse model parallel: one XLA program per same-device
                # SEGMENT (reference bulking, graph_executor.cc:1455) —
                # transfers only at group boundaries, not per op
                segments = self._segments(plan, placements)

                def fn(arg_list, aux_list, keys):
                    outs, new_aux = plan.execute_bulked(
                        dict(zip(arg_names, arg_list)),
                        dict(zip(aux_names, aux_list)), keys, segments)
                    return outs, [new_aux[n] for n in aux_names]

                self._jitted[key] = fn
            else:
                def fn(arg_list, aux_list, keys):
                    outs, new_aux = plan.execute(
                        dict(zip(arg_names, arg_list)),
                        dict(zip(aux_names, aux_list)), keys)
                    return outs, [new_aux[n] for n in aux_names]

                self._jitted[key] = jax.jit(fn)
        elif _telemetry.enabled:
            _program_cache.note_memory_hit()
        return self._jitted[key]

    def _fwdbwd_key(self):
        return ("fwdbwd",) + self._plan_env(True) + self._mesh_key() \
            + self._dtype_sig()

    def _fwd_bwd_fn(self):
        """Single compiled program: forward + vjp-backward (+aux update)."""
        key = self._fwdbwd_key()
        if key not in self._jitted:
            _program_cache.ensure_enabled()
            plan = self._plan(True)
            arg_names, aux_names = plan.arg_names, plan.aux_names
            grad_args = self._grad_args
            placements = self._placements(plan)

            segments = (self._segments(plan, placements)
                        if placements else None)

            def fn(arg_list, aux_list, keys, ograds):
                base = dict(zip(arg_names, arg_list))

                def pure(gvals):
                    av = dict(base)
                    av.update(dict(zip(grad_args, gvals)))
                    if segments is not None:
                        outs, new_aux = plan.execute_bulked(
                            av, dict(zip(aux_names, aux_list)), keys,
                            segments)
                    else:
                        outs, new_aux = plan.execute(
                            av, dict(zip(aux_names, aux_list)), keys)
                    return outs, [new_aux[n] for n in aux_names]

                gvals = [base[n] for n in grad_args]
                (outs, new_aux), vjp = jax.vjp(
                    lambda *g: pure(list(g)), *gvals)
                cots = (list(ograds),
                        [jnp.zeros_like(a) for a in new_aux])
                grads = vjp(cots)
                return outs, new_aux, list(grads)

            self._jitted[key] = fn if placements else jax.jit(fn)
        elif _telemetry.enabled:
            _program_cache.note_memory_hit()
        return self._jitted[key]

    def _step_env(self):
        import os
        return tuple(os.environ.get(k) for k in self.STEP_ENV_KEYS)

    @staticmethod
    def _plan_env_of(plan: "_Plan"):
        """Current values of the plan's op env flags (``_Plan.env_keys``);
        joins every whole-graph program cache key so toggling e.g.
        MXNET_TPU_PALLAS_CONV after the first forward rebuilds the program
        instead of serving one with the old formulation baked in."""
        import os
        return tuple(os.environ.get(k) for k in plan.env_keys)

    def _plan_env(self, train: bool = True):
        return self._plan_env_of(self._plan(train))

    def _program_env(self, plan: Optional["_Plan"] = None):
        """{env key: current value} snapshot of everything in a program's
        cache key — recorded with health registrations so flight-recorder
        dumps can tie a crash back to the formulation flags that built the
        live programs."""
        keys = self.STEP_ENV_KEYS + (plan.env_keys if plan is not None
                                     else ())
        import os
        return {k: os.environ.get(k) for k in keys}

    def _step_key(self, mesh_sig=None):
        """Cache key of the fused whole-step program — also the first_run
        probe used by fused_step drivers, so key shape changes stay in ONE
        place."""
        return ("step",) + ((mesh_sig,) if mesh_sig is not None else ()) \
            + self._step_env() + self._plan_env(True)

    def _update_key(self):
        """Cache key of the update-only program (optimizer update_fns only —
        no graph ops, so no plan env component)."""
        return ("update",) + self._step_env()

    def step_program(self, pnames, update_fns, mesh_sig=None,
                     param_shardings=None):
        """Whole-step program: forward + vjp-backward + optimizer update in
        ONE ``jax.jit`` with params and opt-state donated — weights update
        in place on device, zero per-param python dispatch.

        ``pnames`` are the trainable args (vjp is taken w.r.t. exactly
        these); ``update_fns[i]`` is the param's bound
        ``Optimizer.fused_update``.  Both are closure-captured at first
        build, so callers must drop cached ``("step", ...)`` entries when
        the optimizer binding changes (fused_step.ModuleFusedStep does).
        Per-slot lr/wd/t and rescale_grad arrive as traced scalars: one
        compiled program serves every step.

        ``mesh_sig`` (mesh shape + input sharding signature) joins the
        cache key for the GSPMD variant: the traced body is identical —
        partitioning comes entirely from the input shardings — but a mesh
        or rule change must not reuse a program specialised for the old
        layout.  ``param_shardings`` (aligned with ``pnames``) pins each
        updated param and its opt-state to the INPUT's sharding: without
        the constraint GSPMD may pick a different output layout (e.g.
        shard a small bias), which would silently break the take/give
        donation chain on the next step.
        """
        key = self._step_key(mesh_sig)
        fn = self._jitted.get(key)
        if fn is not None:
            if _telemetry.enabled:
                _program_cache.note_memory_hit()
            return fn
        _program_cache.ensure_enabled()
        plan = self._plan(True)
        arg_names, aux_names = plan.arg_names, plan.aux_names
        pnames = tuple(pnames)
        update_fns = tuple(update_fns)
        pset = set(pnames)
        other_names = [n for n in arg_names if n not in pset]

        def fn(pvals, svals, others, auxs, keys, ograds, lrs, wds, ts,
               rescale):
            base = dict(zip(other_names, others))

            def pure(gvals):
                av = dict(base)
                av.update(zip(pnames, gvals))
                outs, new_aux = plan.execute(
                    av, dict(zip(aux_names, auxs)), keys)
                return outs, [new_aux[n] for n in aux_names]

            (outs, new_aux), vjp = jax.vjp(lambda *g: pure(list(g)), *pvals)
            grads = vjp((list(ograds), [jnp.zeros_like(a) for a in new_aux]))
            new_p, new_s = [], []
            for i, upd in enumerate(update_fns):
                with jax.named_scope(_atlas.optimizer_scope(upd)):
                    w, s = upd(pvals[i], grads[i], svals[i],
                               lrs[i], wds[i], rescale, ts[i])
                if param_shardings is not None:
                    sh = param_shardings[i]
                    w = jax.lax.with_sharding_constraint(w, sh)
                    s = jax.tree_util.tree_map(
                        lambda a: jax.lax.with_sharding_constraint(a, sh), s)
                new_p.append(w)
                new_s.append(s)
            return new_p, new_s, outs, new_aux

        fn = jax.jit(fn, donate_argnums=(0, 1))
        self._jitted[key] = fn
        return fn

    def update_program(self, update_fns):
        """Cached donated update-only program (multi-device local path:
        fwdbwd stays per-device, the update fuses into one launch)."""
        key = self._update_key()
        fn = self._jitted.get(key)
        if fn is None:
            _program_cache.ensure_enabled()
            fn = build_update_program(update_fns)
            self._jitted[key] = fn
        elif _telemetry.enabled:
            _program_cache.note_memory_hit()
        return fn

    def _gather(self):
        args = [self.arg_dict[n]._data for n in self.arg_names]
        auxs = [self.aux_dict[n]._data for n in self.aux_names]
        return args, auxs

    def _ograds_for(self, shapes):
        """Ones head-gradients for a {arg_name: shape} dict (cached
        shape+dtype inference).  The mesh step passes full-batch shapes
        here; the bound per-device shapes come from ``_default_ograds``.
        Output dtypes come from abstract evaluation of the plan under the
        bound argument dtypes — ``jax.vjp`` requires cotangent dtype ==
        output dtype, and bf16 bindings produce bf16 heads (fp32 for heads
        that reduce in fp32, e.g. SoftmaxOutput on low-precision input)."""
        shape_key = tuple(tuple(shapes[n]) for n in self.arg_names)
        key = ("oshapes", shape_key, self._dtype_sig())
        cached = self._jitted.get(key)
        if cached is None:
            _, oshapes, _ = self._symbol.infer_shape(**shapes)
            plan = self._plan(True)
            avals = {n: jax.ShapeDtypeStruct(tuple(shapes[n]),
                                             np.dtype(self.arg_dict[n].dtype))
                     for n in self.arg_names}
            aux_avals = {n: jax.ShapeDtypeStruct(
                self.aux_dict[n].shape, np.dtype(self.aux_dict[n].dtype))
                for n in self.aux_names}
            kstruct = jax.ShapeDtypeStruct((plan.n_rng, 2), np.uint32)
            outs = jax.eval_shape(
                lambda a, x, k: plan.execute(a, x, k)[0],
                avals, aux_avals, kstruct)
            cached = [(s, o.dtype) for s, o in zip(oshapes, outs)]
            self._jitted[key] = cached
        return [jnp.ones(s, dt) for s, dt in cached]

    def _default_ograds(self):
        """Ones head-gradients with shapes from (cached) shape inference."""
        return self._ograds_for(
            {n: self.arg_dict[n].shape for n in self.arg_names})

    def _wrap_outputs(self, outs):
        from .ndarray.ndarray import NDArray
        self.outputs_nd = [NDArray(o, self._ctx) for o in outs]
        if _memwatch.enabled:
            _memwatch.tag("activations", outs)
        return self.outputs_nd

    def _writeback_aux(self, new_aux):
        for n, v in zip(self.aux_names, new_aux):
            self.aux_dict[n]._data = v

    # -- public API -------------------------------------------------------
    def forward(self, is_train: bool = False, **kwargs):
        from .ndarray.ndarray import NDArray
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown input %r" % k)
            dst = self.arg_dict[k]
            if isinstance(v, NDArray):
                # adopt pre-placed producer batches as-is (PrefetchingIter
                # device double buffering): no re-put, no same-dtype astype
                src = v._data
                dst._data = src if src.dtype == dst.dtype \
                    else src.astype(dst.dtype)
            else:
                dst._data = jnp.asarray(v, dst.dtype)
            if _memwatch.enabled:
                # adopted input batches are io-owned on the ledger (the
                # device-resident staging side of the data pipeline)
                _memwatch.tag("io", dst._data)
        from . import profiler as _profiler
        plan = self._plan(bool(is_train))
        keys = self._keys(plan)
        self._last_keys = keys
        # first_run marks the trace+compile invocation of this (mode,
        # shape-set) so recompiles stand out from steady-state iterations
        plan_env = self._plan_env_of(plan)
        first_run = self._fwd_key(is_train) not in self._jitted
        if _telemetry.enabled:
            # count per input-shape signature, not per _fwd_fn build: the
            # jitted fn silently recompiles on a new shape, and THAT is
            # the event a shape-bucketing layer must see (an env-flag
            # toggle recompiles too — plan_env keeps the counter truthful,
            # and a mesh-layout change is a recompile the same way)
            skey = ("fwdsig", bool(is_train),
                    tuple(self.arg_dict[n].shape
                          for n in self.arg_names)) + plan_env \
                + self._mesh_key() + self._dtype_sig()
            if skey in self._jitted:
                _PROG_HITS.labels(op="Executor::Forward").inc()
            else:
                self._jitted[skey] = True
                _PROG_MISSES.labels(op="Executor::Forward").inc()
        # dispatch-only span: the jitted call returns before the device
        # finishes (async dispatch), so this is NOT an execution timing
        with _profiler.span("Executor::ForwardDispatch", "executor",
                            histogram=_FWD_TIME,
                            args={"first_run": first_run}):
            if self._monitor is not None:
                args, auxs = self._gather()
                outs, new_aux = plan.execute(
                    dict(zip(self.arg_names, args)),
                    dict(zip(self.aux_names, auxs)), keys,
                    monitor=self._monitor)
                new_aux = [new_aux[n] for n in self.aux_names]
            else:
                fwd = self._fwd_fn(bool(is_train))
                args, auxs = self._gather()
                if first_run and _health.enabled:
                    # lowering-only analysis: the call below still owns
                    # the one and only compilation
                    _health.register_program(
                        self._program_prefix + "forward", fwd,
                        (args, auxs, keys), env=self._program_env(plan))
                try:
                    outs, new_aux = fwd(args, auxs, keys)
                except Exception as e:
                    if _memwatch.enabled and _memwatch.is_oom(e):
                        _memwatch.on_oom(
                            e, site="executor",
                            program=self._program_prefix + "forward")
                    raise
        if is_train:
            self._writeback_aux(new_aux)
        return self._wrap_outputs(outs)

    def backward(self, out_grads=None, is_train=True):
        """Gradients w.r.t. args with grad_req != null.  Recomputes the
        forward inside one fused XLA program (rematerialization — the TPU
        analog of MXNET_BACKWARD_DO_MIRROR, trading FLOPs for HBM)."""
        from .ndarray.ndarray import NDArray
        plan = self._plan(True)
        if out_grads is None:
            ogs = [jnp.ones(self.outputs_nd[i].shape,
                            self.outputs_nd[i].dtype)
                   for i in range(len(plan.out_entries))]
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            ogs = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                   for g in out_grads]
        keys = self._last_keys if self._last_keys is not None \
            else self._keys(plan)
        args, auxs = self._gather()
        from . import profiler as _profiler
        first_run = self._fwdbwd_key() not in self._jitted
        with _profiler.span("Executor::BackwardDispatch", "executor",
                            histogram=_BWD_TIME,
                            args={"first_run": first_run}):
            fb = self._fwd_bwd_fn()
            if first_run and _health.enabled:
                _health.register_program(
                    self._program_prefix + "fwdbwd", fb,
                    (args, auxs, keys, ogs), env=self._program_env(plan))
            try:
                outs, new_aux, grads = fb(args, auxs, keys, ogs)
            except Exception as e:
                if _memwatch.enabled and _memwatch.is_oom(e):
                    _memwatch.on_oom(e, site="executor",
                                     program=self._program_prefix + "fwdbwd")
                raise
            self._apply_grads(grads)
        return

    def forward_backward(self, out_grads=None, **kwargs):
        """Fused train step: one XLA program for fwd+bwd (+aux update)."""
        from .ndarray.ndarray import NDArray
        for k, v in kwargs.items():
            if k in self.arg_dict:
                dst = self.arg_dict[k]
                if isinstance(v, NDArray):
                    src = v._data
                    dst._data = src if src.dtype == dst.dtype \
                        else src.astype(dst.dtype)
                else:
                    dst._data = jnp.asarray(v, dst.dtype)
                if _memwatch.enabled:
                    _memwatch.tag("io", dst._data)
        plan = self._plan(True)
        keys = self._keys(plan)
        self._last_keys = keys
        args, auxs = self._gather()
        if out_grads is None:
            ogs = self._default_ograds()
        else:
            ogs = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                   for g in out_grads]
        from . import profiler as _profiler
        first_run = self._fwdbwd_key() not in self._jitted
        with _profiler.span("Executor::ForwardBackwardDispatch", "executor",
                            histogram=_FWDBWD_TIME,
                            args={"first_run": first_run}):
            fb = self._fwd_bwd_fn()
            if first_run and _health.enabled:
                _health.register_program(
                    self._program_prefix + "fwdbwd", fb,
                    (args, auxs, keys, ogs), env=self._program_env(plan))
            try:
                outs, new_aux, grads = fb(args, auxs, keys, ogs)
            except Exception as e:
                if _memwatch.enabled and _memwatch.is_oom(e):
                    _memwatch.on_oom(e, site="executor",
                                     program=self._program_prefix + "fwdbwd")
                raise
            self._writeback_aux(new_aux)
            self._apply_grads(grads)
        return self._wrap_outputs(outs)

    def _apply_grads(self, grads):
        for n, g in zip(self._grad_args, grads):
            if n not in self.grad_dict:
                continue
            dst = self.grad_dict[n]
            if self._grad_req.get(n) == "add":
                dst._data = dst._data + g.astype(dst.dtype)
            else:
                dst._data = g.astype(dst.dtype)
            if _memwatch.enabled:
                # gradient buffers persist across steps; ledger them with
                # the step-transient products so the leak sentinel stays
                # quiet about them
                _memwatch.tag("activations", dst._data)

    @property
    def outputs(self):
        return self.outputs_nd

    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self.arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict.get(n) for n in self.arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self.aux_names]

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in (arg_params or {}).items():
            if k in self.arg_dict:
                self.arg_dict[k][:] = v
            elif not allow_extra_params:
                raise MXNetError("unknown parameter %r" % k)
        for k, v in (aux_params or {}).items():
            if k in self.aux_dict:
                self.aux_dict[k][:] = v
            elif not allow_extra_params:
                raise MXNetError("unknown aux state %r" % k)

    def set_monitor_callback(self, callback, monitor_all=False):
        """Install a per-node-output callback (runs the un-jitted plan);
        ``monitor_all`` additionally reports every node INPUT
        (reference SetMonitorCallback monitor_all semantics)."""
        if callback is None:
            self._monitor = None
            return

        def mon(name, arr):
            from .ndarray.ndarray import NDArray
            callback(name, NDArray(arr, self._ctx))

        mon.monitor_all = bool(monitor_all)
        self._monitor = mon

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Re-bind with new shapes (compile cache keyed on shapes by jit)."""
        from . import ndarray as nd
        new_shapes, _, new_aux_shapes = self._symbol.infer_shape(**kwargs)
        args = {}
        for n, s in zip(self.arg_names, new_shapes):
            cur = self.arg_dict[n]
            args[n] = cur if cur.shape == s else nd.zeros(s, ctx=self._ctx,
                                                          dtype=cur.dtype)
        auxs = {}
        for n, s in zip(self.aux_names, new_aux_shapes):
            cur = self.aux_dict[n]
            auxs[n] = cur if cur.shape == s else nd.zeros(s, ctx=self._ctx,
                                                          dtype=cur.dtype)
        grads = {n: nd.zeros(a.shape, ctx=self._ctx, dtype=a.dtype)
                 for n, a in args.items() if n in self.grad_dict}
        return Executor(self._symbol, self._ctx, args, grads,
                        self._grad_req, auxs)
