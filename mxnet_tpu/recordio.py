"""RecordIO file format (parity: python/mxnet/recordio.py + dmlc-core
RecordIO).  Binary-compatible with the reference format so .rec datasets
interchange: records framed by magic 0xced7230a + length word, 4-byte
aligned; IRHeader (flag, label, id, id2) prefix for image records."""
from __future__ import annotations

import ctypes  # noqa: F401  (kept for API-shape parity)
import numbers
import os
import struct
import threading
from collections import namedtuple

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_LFLAG_BITS = 29
_CFLAG_MASK = ((1 << (32 - _LFLAG_BITS)) - 1) << _LFLAG_BITS
_LEN_MASK = (1 << _LFLAG_BITS) - 1


class MXRecordIO:
    """Sequential RecordIO reader/writer (ref recordio.py:MXRecordIO).

    Backed by the native C++ reader/writer (src/recordio.cc via ctypes, the
    dmlc-core RecordIO analog) when the native library is available; the
    pure-Python code below is the byte-identical fallback.
    """

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.writable = None
        self._nlib = None
        self._nhandle = None
        self.open()

    def open(self):
        from . import _native
        self._nlib = _native.lib()
        if self.flag == "w":
            self.writable = True
        elif self.flag == "r":
            self.writable = False
        else:
            raise MXNetError("invalid flag %r" % self.flag)
        if self._nlib is not None:
            create = (self._nlib.MXNativeRecordIOWriterCreate if self.writable
                      else self._nlib.MXNativeRecordIOReaderCreate)
            self._nhandle = create(str(self.uri).encode())
            if not self._nhandle:
                raise MXNetError(
                    self._nlib.MXNativeRecordIOGetLastError().decode())
        else:
            self.handle = open(self.uri, "wb" if self.writable else "rb")
        self.pid = os.getpid()

    def close(self):
        if self._nhandle:
            if self.writable:
                self._nlib.MXNativeRecordIOWriterClose(self._nhandle)
            else:
                self._nlib.MXNativeRecordIOReaderClose(self._nhandle)
            self._nhandle = None
        if self.handle is not None:
            self.handle.close()
            self.handle = None

    def __del__(self):
        self.close()

    def __getstate__(self):
        d = dict(self.__dict__)
        d["handle"] = None
        d["_nlib"] = None       # ctypes objects are not picklable;
        d["_nhandle"] = None    # __setstate__ reopens
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        if self._nhandle:
            if self.writable:
                return int(self._nlib.MXNativeRecordIOWriterTell(
                    self._nhandle))
            return int(self._nlib.MXNativeRecordIOReaderTell(self._nhandle))
        return self.handle.tell()

    def _seek(self, pos):
        assert not self.writable
        if self._nhandle:
            self._nlib.MXNativeRecordIOReaderSeek(self._nhandle, int(pos))
        else:
            self.handle.seek(pos)

    def write(self, buf: bytes):
        assert self.writable
        if self._nhandle:
            if self._nlib.MXNativeRecordIOWriterWrite(self._nhandle, buf,
                                                      len(buf)) != 0:
                raise MXNetError(
                    self._nlib.MXNativeRecordIOGetLastError().decode())
            return
        n = len(buf)
        self.handle.write(struct.pack("<II", _MAGIC, n & _LEN_MASK))
        self.handle.write(buf)
        pad = (4 - (n & 3)) & 3
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        if self._nhandle:
            buf = ctypes.c_void_p()
            size = ctypes.c_uint64()
            rc = self._nlib.MXNativeRecordIOReaderRead(
                self._nhandle, ctypes.byref(buf), ctypes.byref(size))
            if rc == 1:
                return None
            if rc != 0:
                raise MXNetError(
                    self._nlib.MXNativeRecordIOGetLastError().decode())
            return ctypes.string_at(buf, size.value)
        head = self.handle.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        if magic != _MAGIC:
            raise MXNetError("invalid RecordIO magic in %s" % self.uri)
        n = lrec & _LEN_MASK
        data = self.handle.read(n)
        pad = (4 - (n & 3)) & 3
        if pad:
            self.handle.read(pad)
        return data


class MXIndexedRecordIO(MXRecordIO):
    """Random-access RecordIO with a .idx sidecar (ref MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        self._read_lock = threading.RLock()
        super().__init__(uri, flag)

    def __getstate__(self):
        d = super().__getstate__()
        d["_read_lock"] = None  # locks don't pickle; __setstate__ rebuilds
        return d

    def __setstate__(self, d):
        super().__setstate__(d)
        self._read_lock = threading.RLock()

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.writable:
            self.fidx = open(self.idx_path, "w")
        else:
            self.fidx = None
            if os.path.exists(self.idx_path):
                with open(self.idx_path) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        if len(parts) >= 2:
                            key = self.key_type(parts[0])
                            self.idx[key] = int(parts[1])
                            self.keys.append(key)

    def close(self):
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None
        super().close()

    def seek(self, idx):
        self._seek(self.idx[idx])

    def read_idx(self, idx):
        # seek+read must be ONE atomic unit: the pipelined ImageRecordIter
        # reader thread shares this handle with user-thread random access,
        # and an interleaved seek lands the read on the wrong record
        with self._read_lock:
            self.seek(idx)
            return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack (header, payload) into one record (ref recordio.pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        head = struct.pack(_IR_FORMAT, header.flag, header.label,
                           header.id, header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        head = struct.pack(_IR_FORMAT, len(label), 0.0, header.id, header.id2)
        head += label.tobytes()
    return head + s


def unpack(s: bytes):
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        s = s[header.flag * 4:]
        header = header._replace(label=label, flag=0)
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode image + pack (requires cv2 or PIL; gated)."""
    buf = _encode_img(img, quality, img_fmt)
    return pack(header, buf)


def unpack_img(s, iscolor=-1):
    header, img_bytes = unpack(s)
    return header, _decode_img(img_bytes, iscolor)


def _encode_img(img, quality, img_fmt):
    try:
        import cv2
        ext = img_fmt if img_fmt.startswith(".") else "." + img_fmt
        params = [int(cv2.IMWRITE_JPEG_QUALITY), quality] \
            if "jp" in ext else []
        ok, buf = cv2.imencode(ext, img, params)
        if not ok:
            raise MXNetError("image encode failed")
        return buf.tobytes()
    except ImportError:
        import io as _io
        from PIL import Image
        im = Image.fromarray(img[..., ::-1] if img.ndim == 3 else img)
        bio = _io.BytesIO()
        im.save(bio, format="JPEG", quality=quality)
        return bio.getvalue()


def _decode_img(img_bytes, iscolor=-1):
    try:
        import cv2
        arr = np.frombuffer(img_bytes, dtype=np.uint8)
        return cv2.imdecode(arr, iscolor)
    except ImportError:
        import io as _io
        from PIL import Image
        im = Image.open(_io.BytesIO(img_bytes))
        a = np.asarray(im)
        return a[..., ::-1] if a.ndim == 3 else a
