"""Bucketing data iterator for variable-length sequences.

Reference analog: ``python/mxnet/rnn/io.py:84`` (BucketSentenceIter): each
sentence is padded to the smallest bucket that fits it; every batch is
drawn from ONE bucket, and ``provide_data`` advertises the default-bucket
shape so BucketingModule can bind the largest executor first.  On TPU a
bucket is one static-shape XLA compilation — this iterator is what keeps
the number of distinct compiled shapes small.
"""
from __future__ import annotations

import random
from typing import List, Optional, Sequence

import numpy as np

from ..io import DataBatch, DataDesc, DataIter

__all__ = ["BucketSentenceIter"]


class BucketSentenceIter(DataIter):
    """Bucketed iterator over tokenized sentences.

    Parameters
    ----------
    sentences : list of list of int
    batch_size : int
    buckets : list of int, optional
        Bucket sizes (sorted); defaults to the sizes with enough data.
    invalid_label : int
        Padding/label id for positions past the sentence end.
    data_name / label_name : str
    label : list of list of int, optional
        Per-position labels; defaults to next-token (shift by one).
    """

    def __init__(self, sentences: Sequence[Sequence[int]], batch_size: int,
                 buckets: Optional[List[int]] = None, invalid_label: int = -1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT", label=None):
        super().__init__(batch_size)
        if buckets is None:
            lens = np.array([len(s) for s in sentences])
            buckets = sorted({int(b) for b in np.unique(lens)
                              if (lens == b).sum() >= batch_size})
            if not buckets:
                buckets = [int(lens.max())]
        if layout not in ("NT", "TN"):
            raise ValueError("layout must be 'NT' (batch-major) or 'TN' "
                             "(time-major), got %r" % (layout,))
        buckets = sorted(buckets)
        self.data_name = data_name
        self.label_name = label_name
        self.buckets = buckets
        self.invalid_label = invalid_label
        self.default_bucket_key = max(buckets)
        self.dtype = dtype
        self.layout = layout

        self._bucket_data = [[] for _ in buckets]
        self._bucket_label = [[] for _ in buckets]
        ndiscard = 0
        for i, sent in enumerate(sentences):
            bkt = next((b for b in buckets if b >= len(sent)), None)
            if bkt is None:
                ndiscard += 1
                continue
            buf = np.full((bkt,), invalid_label, dtype)
            buf[:len(sent)] = sent
            lab = np.full((bkt,), invalid_label, dtype)
            if label is not None:
                lab[:len(label[i])] = label[i][:bkt]
            elif len(sent) > 1:   # empty/1-token sentences have no targets
                lab[:len(sent) - 1] = sent[1:]
            idx = buckets.index(bkt)
            self._bucket_data[idx].append(buf)
            self._bucket_label[idx].append(lab)
        if ndiscard:
            import logging
            logging.warning("discarded %d sentences longer than the "
                            "largest bucket", ndiscard)
        self._bucket_data = [np.asarray(b).astype(dtype) if b else
                             np.zeros((0, k), dtype)
                             for b, k in zip(self._bucket_data, buckets)]
        self._bucket_label = [np.asarray(b).astype(dtype) if b else
                              np.zeros((0, k), dtype)
                              for b, k in zip(self._bucket_label, buckets)]
        self._plan = []       # (bucket_idx, start) per batch
        self.reset()

    def _shape(self, bucket):
        return ((self.batch_size, bucket) if self.layout == "NT"
                else (bucket, self.batch_size))

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         self._shape(self.default_bucket_key),
                         self.dtype)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         self._shape(self.default_bucket_key),
                         self.dtype)]

    def reset(self):
        # reshuffle sentences WITHIN each bucket too (reference reset():
        # batch composition must differ between epochs, not just order)
        for i in range(len(self._bucket_data)):
            if len(self._bucket_data[i]):
                perm = np.random.permutation(len(self._bucket_data[i]))
                self._bucket_data[i] = self._bucket_data[i][perm]
                self._bucket_label[i] = self._bucket_label[i][perm]
        self._plan = []
        for i, data in enumerate(self._bucket_data):
            for start in range(0, len(data) - self.batch_size + 1,
                               self.batch_size):
                self._plan.append((i, start))
        random.shuffle(self._plan)
        self._cursor = 0

    def next(self) -> DataBatch:
        if self._cursor >= len(self._plan):
            raise StopIteration
        i, start = self._plan[self._cursor]
        self._cursor += 1
        from .. import ndarray as nd
        d = self._bucket_data[i][start:start + self.batch_size]
        l = self._bucket_label[i][start:start + self.batch_size]
        if self.layout == "TN":
            d, l = d.T, l.T
        bkt = self.buckets[i]
        return DataBatch(
            data=[nd.array(d)], label=[nd.array(l)], pad=0,
            bucket_key=bkt,
            provide_data=[DataDesc(self.data_name, self._shape(bkt),
                                   self.dtype)],
            provide_label=[DataDesc(self.label_name, self._shape(bkt),
                                    self.dtype)])
