"""Legacy RNN package: bucketing IO (parity: ``python/mxnet/rnn/``).

The modern RNN API lives in ``gluon.rnn``; this package carries the
symbolic-era pieces that the BucketingModule workflow needs — chiefly
:class:`BucketSentenceIter` (``python/mxnet/rnn/io.py:84``), the
variable-length sequence iterator that assigns each sentence to its
length bucket.
"""
from .io import BucketSentenceIter

__all__ = ["BucketSentenceIter"]
