"""Gluon Trainer: imperative data-parallel optimization.

Reference analog: ``python/mxnet/gluon/trainer.py`` (``Trainer:27``, kvstore
init ``:153``, ``step:217``, ``_allreduce_grads:267-275``, ``_update:310``).

TPU-native notes: on a single host the cross-device gradient reduce rides
XLA (KVStore ``device`` = add-chain the compiler lowers to ICI all-reduce on
a pod mesh); the fused-optimizer update kernels are the ``optimizer_op.cc``
analogs in :mod:`mxnet_tpu.ops.optimizer_ops`, executed one XLA program per
parameter.
"""
from __future__ import annotations

import time

from ..base import MXNetError
from .. import optimizer as opt
from .. import kvstore as kvs
from .. import telemetry as _telemetry
from .. import fused_step as _fused
from .. import health as _health
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]

_STEPS = _telemetry.counter(
    "trainer_steps_total", "Optimization steps taken by gluon.Trainer")
_SYNC_LAT = _telemetry.histogram(
    "trainer_grad_sync_seconds",
    "Gradient push/pull (allreduce) latency per Trainer step")


class Trainer:
    """Applies an Optimizer on a set of Parameters."""

    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, got %s."
                % type(params))
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    "got list of %s." % type(param))
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._contexts = self._check_contexts()
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._kvstore_name = kvstore
        self._fused_update = None
        self._mesh_update = None

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx()
            if contexts is not None and contexts != ctx:
                raise ValueError(
                    "All Parameters must be initialized on the same set of "
                    "contexts, but Parameter %s is initialized on %s while "
                    "previous Parameters are initialized on %s." % (
                        param.name, str(ctx), str(contexts)))
            contexts = ctx
        return contexts

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params and list(optimizer_params) != ["rescale_grad"]:
                raise ValueError(
                    "optimizer_params must be None if optimizer is an "
                    "instance of Optimizer instead of str")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(
                optimizer, param_dict=param_dict, **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)
                          for _ in self._contexts]

    def _init_kvstore(self):
        config = self._kvstore_name
        if config is None or (isinstance(config, str) and config == "None"):
            kvstore = None
            update_on_kvstore = False
        elif isinstance(config, kvs.KVStore):
            kvstore = config
            update_on_kvstore = self._update_on_kvstore
        else:
            arg_arrays = {}
            kvstore, update_on_kvstore = _create_kvstore(
                config, len(self._contexts), arg_arrays)
            if self._update_on_kvstore is not None:
                update_on_kvstore = self._update_on_kvstore
        if kvstore:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            if update_on_kvstore is None:
                update_on_kvstore = "dist" in kvstore.type
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
            for i, param in enumerate(self._params):
                param_arrays = param.list_data()
                kvstore.init(i, param_arrays[0])
                if update_on_kvstore:
                    kvstore.pull(i, param_arrays, priority=-i)
        else:
            update_on_kvstore = False
        self._kvstore = kvstore
        self._update_on_kvstore = bool(update_on_kvstore)
        self._kv_initialized = True

    @property
    def learning_rate(self):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning(
                "Optimizer has to be defined before its learning rate can "
                "be accessed.")
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning(
                "Optimizer has to be defined before its learning rate is "
                "mutated.")
        self._optimizer.lr = lr

    def step(self, batch_size, ignore_stale_grad=False):
        """Make one optimization step: allreduce grads then update.

        On local multi-device with MXNET_TPU_MESH_STEP (default ON) the
        two phases fuse into ONE GSPMD program over a ``dp`` mesh — raw
        per-device gradients are adopted zero-copy as batch shards and XLA
        inserts the all-reduce — so the host-side kvstore push/pull never
        runs; the KVStore remains the cross-host transport only."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        if self._mesh_update is None:
            self._mesh_update = _fused.TrainerMeshUpdate(self)
        mu = self._mesh_update
        if mu.eligible():
            tel = _telemetry.enabled
            t0 = time.perf_counter() if tel else 0.0
            if mu.step():
                if tel:
                    _fused.STEP_DISPATCH.labels(path="mesh_fused").inc()
                    _fused.STEP_TIME.observe(time.perf_counter() - t0)
                    _STEPS.inc()
                if _health.enabled:
                    _health.monitor.on_step("trainer_mesh_update")
                return
        self._allreduce_grads()
        self._update(ignore_stale_grad)
        if _telemetry.enabled:
            _STEPS.inc()
        if _health.enabled:
            _health.monitor.on_step("trainer_update")

    def fit_epoch(self, data_iter, step_fn, block_fn=None, depth=None):
        """Drive one epoch with dispatch and blocking tails overlapped
        (train_loop.run_epoch): ``step_fn(batch)`` runs fwd/bwd +
        ``self.step`` and returns an async handle (e.g. the loss);
        ``block_fn(handle, i)`` — optional — is the hard-blocking tail
        (loss D2H, logging), deferred ``depth`` steps behind dispatch so
        the device pipeline stays full.  Returns batches consumed.

        When ``MXNET_CKPT_DIR``/``MXNET_CKPT_EVERY_N_STEPS`` are set the
        step is wrapped with donation-safe async checkpointing: on the
        first call the latest committed checkpoint (if any) is restored,
        and thereafter every due step snapshots params + optimizer state
        to host memory before the next step can donate the buffers.  A
        SIGTERM (preemption notice) triggers a final synchronous
        checkpoint followed by a clean ``SystemExit(0)``."""
        from ..train_loop import run_epoch
        from .. import chaos as _chaos
        from .. import checkpoint as _ckpt
        if not hasattr(self, "_ft_ckpt"):
            self._ft_ckpt = _ckpt.TrainCheckpointer.from_env()
            self._global_step = 0
            if self._ft_ckpt is not None:
                _ckpt.install_preempt_handler()
                latest = self._ft_ckpt.latest()
                if latest is not None:
                    tree, meta, blobs = self._ft_ckpt.load(latest)
                    self._ft_restore(tree, meta, blobs)
                    self._global_step = int(meta.get("global_step", 0))
        ckpt = self._ft_ckpt
        if ckpt is None and not _chaos.active():
            return run_epoch(data_iter, step_fn, block_fn=block_fn,
                             depth=depth)

        def _step(batch):
            out = step_fn(batch)
            self._global_step += 1
            gstep = self._global_step
            _chaos.step(gstep)
            if ckpt is not None:
                if _ckpt.preempted():
                    ckpt.save_sync(gstep, *self._ft_snapshot(gstep))
                    ckpt.close()
                    raise SystemExit(0)
                if ckpt.due(gstep):
                    ckpt.maybe_save(gstep, *self._ft_snapshot(gstep))
            return out

        return run_epoch(data_iter, _step, block_fn=block_fn, depth=depth)

    # ---- fault-tolerant training state ----------------------------------
    def _ft_snapshot(self, gstep):
        """Host-side copy of params + optimizer state for the async
        checkpointer.  Safe against donation: TrainerMeshUpdate scatters
        updated shards back to per-device arrays after every step, and
        ``asnumpy`` below forces the D2H copy before the next dispatch."""
        tree = {}
        for i, param in enumerate(self._params):
            tree["param/%d/%s" % (i, param.name)] = \
                param.list_data()[0].asnumpy()
        meta = {"global_step": int(gstep)}
        blobs = {}
        if not self._update_on_kvstore and getattr(self, "_updaters", None):
            blobs["opt_states.bin"] = self._updaters[0].get_states(
                dump_optimizer=False)
            # per-slot update counts are not part of get_states; without
            # them an Adam resume restarts bias correction at t=0
            meta["index_update_count"] = {
                str(k): int(v)
                for k, v in self._optimizer._index_update_count.items()}
            meta["num_update"] = int(self._optimizer.num_update)
        return tree, meta, blobs

    def _ft_restore(self, tree, meta, blobs):
        from .. import ndarray as _nd
        for i, param in enumerate(self._params):
            key = "param/%d/%s" % (i, param.name)
            if key not in tree:
                raise MXNetError(
                    "checkpoint is missing parameter %r" % key)
            cur = param.list_data()[0]
            restored = tree[key]
            if tuple(restored.shape) != tuple(cur.shape):
                raise MXNetError(
                    "checkpoint shape mismatch for %r: saved %s, model %s"
                    % (key, tuple(restored.shape), tuple(cur.shape)))
            param.set_data(_nd.array(restored, dtype=restored.dtype))
        states = (blobs or {}).get("opt_states.bin")
        if states is not None and getattr(self, "_updaters", None):
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._updaters[0].optimizer
            self._optimizer = self._updaters[0].optimizer
            counts = meta.get("index_update_count") or {}
            self._optimizer._index_update_count = {
                (int(k) if str(k).lstrip("-").isdigit() else k): int(v)
                for k, v in counts.items()}
            if "num_update" in meta:
                self._optimizer.num_update = int(meta["num_update"])

    def allreduce_grads(self):
        """Reduce gradients over devices only (then call update())."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise AssertionError(
                "allreduce_grads() when parameters are updated on kvstore "
                "is not supported. Try setting `update_on_kvstore` to False "
                "when creating trainer.")
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        tel = _telemetry.enabled
        t0 = time.perf_counter() if tel else 0.0
        # batched push/pull over every live param: one call lets the
        # dist_async wire layer coalesce per-key traffic into buckets
        live = [i for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if live:
            grads = [self._params[i].list_grad() for i in live]
            self._kvstore.push(live, grads)
            if not self._update_on_kvstore:
                self._kvstore.pull(live, out=grads)
        if tel:
            _SYNC_LAT.observe(time.perf_counter() - t0)
            if _health.enabled:
                _health.monitor.note_phase(
                    "sync", time.perf_counter() - t0)

    def update(self, batch_size, ignore_stale_grad=False):
        """Update parameters only (after allreduce_grads)."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise AssertionError(
                "update() when parameters are updated on kvstore is not "
                "supported. Try setting `update_on_kvstore` to False when "
                "creating trainer.")
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        tel = _telemetry.enabled
        t0 = time.perf_counter() if tel else 0.0
        if not self._update_on_kvstore:
            if self._fused_update is None:
                self._fused_update = _fused.TrainerFusedUpdate(self)
            fu = self._fused_update
            if fu.eligible() and fu.step():
                if tel:
                    _fused.STEP_DISPATCH.labels(path="fused").inc()
                    _fused.STEP_TIME.observe(time.perf_counter() - t0)
                return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if self._update_on_kvstore:
                self._kvstore.pull(i, param.list_data(), priority=-i)
                continue
            for upd, arr, grad in zip(
                    self._updaters, param.list_data(), param.list_grad()):
                upd(i, grad, arr)
        if tel:
            _fused.STEP_DISPATCH.labels(path="eager").inc()
            _fused.STEP_TIME.observe(time.perf_counter() - t0)

    def save_states(self, fname):
        """Save optimizer (updater) states to a file."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        """Load optimizer (updater) states from a file."""
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._updaters[0].optimizer
            self._optimizer = self._updaters[0].optimizer


def _create_kvstore(kvstore, num_device, arg_params):
    """Create kvstore from str config (analog of model._create_kvstore)."""
    update_on_kvstore = False
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if "dist" in kvstore:
                update_on_kvstore = True
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    return kv, update_on_kvstore
