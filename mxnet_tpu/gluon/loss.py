"""Gluon losses (parity: python/mxnet/gluon/loss.py, 708 LoC:
L2/L1/SigmoidBCE/SoftmaxCE/KL/CTC/Huber/Hinge/SquaredHinge/Logistic/Triplet/
PoissonNLL).

All losses are HybridBlocks — hybridized they fuse into the surrounding XLA
program (elementwise chains ride the VPU fused with the producing matmul).
"""
from __future__ import annotations

import numpy as np

from .block import HybridBlock
from ..base import numeric_types

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss",
           "PoissonNLLLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    """Apply weighting to loss (ref loss.py:_apply_weighting)."""
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        if not isinstance(weight, numeric_types):
            raise AssertionError("weight must be a number")
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape) if F.__name__.endswith("ndarray") \
        else F.reshape_like(x, y)


class Loss(HybridBlock):
    """Base class for losses."""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def __repr__(self):
        return "{name}(batch_axis={_batch_axis}, w={_weight})".format(
            name=self.__class__.__name__, **self.__dict__)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class L2Loss(Loss):
    """L2 = 0.5 * (pred - label)^2, mean over non-batch axes."""

    def __init__(self, weight=1., batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(pred - label)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class L1Loss(Loss):
    """L1 = |pred - label|, mean over non-batch axes."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(pred - label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """BCE with optional built-in sigmoid (numerically stable log-sum-exp
    form when from_sigmoid=False)."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            loss = F.relu(pred) - pred * label + \
                F.Activation(-F.abs(pred), act_type="softrelu")
        else:
            eps = 1e-12
            loss = -(F.log(pred + eps) * label
                     + F.log(1. - pred + eps) * (1. - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """softmax + CE (ref loss.py SoftmaxCrossEntropyLoss).

    The sparse-label path lowers to ``streaming_softmax_ce`` — a fused
    logsumexp-minus-pick that never materializes the ``(N, vocab)`` f32
    log-softmax the reference's log_softmax+pick composition implies
    (measured +23% tokens/s on the LSTM LM; see ops/nn.py:streaming_ce).
    """

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if self._sparse_label and not self._from_logits:
            loss = F.streaming_softmax_ce(pred, label, axis=self._axis,
                                          keepdims=True)
            loss = _apply_weighting(F, loss, self._weight, sample_weight)
            return F.mean(loss, axis=self._batch_axis, exclude=True)
        if not self._from_logits:
            pred = F.log_softmax(pred, self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    """Kullback-Leibler divergence loss."""

    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class CTCLoss(Loss):
    """Connectionist Temporal Classification loss (Graves et al., 2006)
    (ref: loss.py CTCLoss over src/operator/contrib/ctc_loss.cc)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        if layout not in ("NTC", "TNC"):
            raise AssertionError(
                "Only 'NTC' and 'TNC' layouts for pred are supported, "
                "got: %s" % layout)
        if label_layout not in ("NT", "TN"):
            raise AssertionError(
                "Only 'NT' and 'TN' layouts for label are supported, "
                "got: %s" % label_layout)
        self._layout = layout
        self._label_layout = label_layout
        batch_axis = label_layout.find("N")
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, 0, 1)
        if self._batch_axis == 1:
            label = F.swapaxes(label, 0, 1)
        loss = F.CTCLoss(pred, label,
                         use_data_lengths=pred_lengths is not None,
                         use_label_lengths=label_lengths is not None,
                         *[a for a in (pred_lengths, label_lengths)
                           if a is not None],
                         blank_label="last")
        return _apply_weighting(F, loss, self._weight, sample_weight)


class HuberLoss(Loss):
    """Smoothed L1: quadratic within rho of 0, linear outside."""

    def __init__(self, rho=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(pred - label)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HingeLoss(Loss):
    """max(0, margin - pred*label) for SVM-style training."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SquaredHingeLoss(Loss):
    """max(0, margin - pred*label)^2."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class LogisticLoss(Loss):
    """log(1 + exp(-pred*label)); label_format binary {0,1} or signed
    {-1,1}."""

    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format
        if self._label_format not in ("signed", "binary"):
            raise ValueError(
                "label_format can only be signed or binary, recieved %s."
                % label_format)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class TripletLoss(Loss):
    """max(0, |pos-pred|^2 - |neg-pred|^2 + margin)."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred),
                     axis=self._batch_axis, exclude=True)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, None)


class PoissonNLLLoss(Loss):
    """Poisson negative log likelihood: pred - target*log(pred)."""

    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None,
                       epsilon=1e-08):
        target = _reshape_like(F, target, pred)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            stirling_factor = target * F.log(target) - target + \
                0.5 * F.log(2 * target * np.pi)
            stirling_factor = F.where(
                target > 1, stirling_factor, F.zeros_like(stirling_factor))
            loss = loss + stirling_factor
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)
