"""Gluon contrib nn layers (parity: python/mxnet/gluon/contrib/nn/).

Concurrent/HybridConcurrent (parallel branch + concat), Identity,
SparseEmbedding (dense-gather on TPU), SyncBatchNorm placeholder.
"""
from __future__ import annotations

from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential, Embedding, BatchNorm

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "SyncBatchNorm"]


class Concurrent(Sequential):
    """Feeds input to all children, concatenating their outputs on
    ``axis``."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def forward(self, x):
        from .... import ndarray
        out = []
        for block in self._children.values():
            out.append(block(x))
        return ndarray.concat(*out, dim=self.axis)


class HybridConcurrent(HybridSequential):
    """Hybridizable Concurrent."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def hybrid_forward(self, F, x):
        out = []
        for block in self._children.values():
            out.append(block(x))
        return F.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    """Identity block, useful in Concurrent branches."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(Block):
    """Embedding with row_sparse gradient semantics (ref contrib
    SparseEmbedding).  TPU note: compute is a dense XLA gather; the sparse
    grad_stype survives for the KVStore row_sparse_pull path."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype, "sparse_grad": True}
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim),
            init=weight_initializer, dtype=dtype,
            grad_stype="row_sparse", stype="row_sparse")

    def forward(self, x):
        from .... import ndarray
        weight = self.weight.data(x.context)
        return ndarray.Embedding(x, weight,
                                 input_dim=self._kwargs["input_dim"],
                                 output_dim=self._kwargs["output_dim"],
                                 dtype=self._kwargs["dtype"])

    def __repr__(self):
        return "{name}({input_dim} -> {output_dim}, {dtype})".format(
            name=self.__class__.__name__, **self._kwargs)


class SyncBatchNorm(BatchNorm):
    """Cross-device synchronized BatchNorm.

    TPU note: under pjit/shard_map the batch axis is a mesh axis and the
    moment reduction is a ``psum`` over ICI, so plain BatchNorm inside a
    sharded program IS sync-BN; this class is API parity for explicit use.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         in_channels=in_channels, **kwargs)
