"""Contrib nn layers."""
from .basic_layers import *
