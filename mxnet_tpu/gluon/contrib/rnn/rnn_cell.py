"""Contrib RNN cells (parity: python/mxnet/gluon/contrib/rnn/rnn_cell.py)."""
from __future__ import annotations

from ...rnn.rnn_cell import (HybridRecurrentCell, ModifierCell,
                             BidirectionalCell, SequentialRNNCell,
                             _format_sequence, _get_begin_state)

__all__ = ["VariationalDropoutCell"]


class VariationalDropoutCell(ModifierCell):
    """Applies Variational Dropout (Gal & Ghahramani 2016): the same
    dropout mask reused at every timestep for inputs/states/outputs."""

    def __init__(self, base_cell, drop_inputs=0., drop_states=0.,
                 drop_outputs=0.):
        assert not drop_states or not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support variational state dropout. " \
            "Please add VariationalDropoutCell to the cells underneath " \
            "instead."
        assert not drop_states or not isinstance(base_cell, SequentialRNNCell), \
            "Bidirectional SequentialRNNCell doesn't support variational " \
            "state dropout. Please add VariationalDropoutCell to the cells " \
            "underneath instead."
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _initialize_input_masks(self, F, inputs, states):
        if self.drop_states and self.drop_states_mask is None:
            self.drop_states_mask = F.Dropout(
                F.ones_like(states[0]), p=self.drop_states)
        if self.drop_inputs and self.drop_inputs_mask is None:
            self.drop_inputs_mask = F.Dropout(
                F.ones_like(inputs), p=self.drop_inputs)

    def _initialize_output_mask(self, F, output):
        if self.drop_outputs and self.drop_outputs_mask is None:
            self.drop_outputs_mask = F.Dropout(
                F.ones_like(output), p=self.drop_outputs)

    def hybrid_forward(self, F, inputs, states):
        cell = self.base_cell
        self._initialize_input_masks(F, inputs, states)
        if self.drop_states:
            states = list(states)
            states[0] = states[0] * self.drop_states_mask
        if self.drop_inputs:
            inputs = inputs * self.drop_inputs_mask
        next_output, next_states = cell(inputs, states)
        self._initialize_output_mask(F, next_output)
        if self.drop_outputs:
            next_output = next_output * self.drop_outputs_mask
        return next_output, next_states

    def __repr__(self):
        return ("{name}(p_out={drop_outputs}, p_state={drop_states}, "
                "{base_cell})").format(
            name=self.__class__.__name__, base_cell=repr(self.base_cell),
            drop_outputs=self.drop_outputs, drop_states=self.drop_states)
