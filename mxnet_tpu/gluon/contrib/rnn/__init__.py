"""Contrib rnn cells."""
from .rnn_cell import *
