"""Gluon Parameter / ParameterDict.

Reference analog: ``python/mxnet/gluon/parameter.py`` (``Parameter:43`` with
deferred init, ``_reduce:312``, grad_req handling, per-context replicas).

TPU-native notes: a parameter replica per :class:`~mxnet_tpu.context.Context`
is kept as an independent NDArray (jax.Array buffer); for sharded training the
idiomatic path is a single array laid out over a `jax.sharding.Mesh` — see
:mod:`mxnet_tpu.parallel` — but the reference's list-of-contexts API is
preserved so Trainer/KVStore code carries over unchanged.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from ..context import Context, current_context, cpu
from .. import ndarray as nd
from .. import initializer
from ..initializer import InitDesc

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Error for unfinished deferred initialization."""


class Parameter:
    """A Container holding parameters (weights) of Blocks.

    :class:`Parameter` holds a copy of the parameter on each
    :class:`Context` after it is initialized with ``initialize(...)``.
    If ``grad_req`` is not ``'null'``, it will also hold a gradient array on
    each Context.

    Parity: python/mxnet/gluon/parameter.py:43.
    """

    def __init__(self, name, grad_req="write", shape=None, dtype=np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self._var = None
        self._data = None          # OrderedDict ctx -> NDArray
        self._grad = None
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        if not differentiable:
            grad_req = "null"
        self._grad_req = None
        self.grad_req = grad_req
        if stype not in ("default", "row_sparse", "csr"):
            raise ValueError("invalid stype %r" % stype)
        self._stype = stype
        self._grad_stype = grad_stype
        self._deferred_init = ()

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (
            self.name, self.shape, np.dtype(self.dtype).name)

    # ---- properties -----------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise ValueError("grad_req must be write/add/null, got %r" % req)
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data is not None:
                for arr in self._data.values():
                    arr._grad = None
                    arr._grad_req = "null"
                    arr._ag_leaf = False
        elif self._data is not None:
            self._init_grad()

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape) if new_shape is not None else None
            return
        if new_shape is None:
            return
        unknown_ok = all(
            s1 in (0, -1) or s1 == s2
            for s1, s2 in zip(self._shape, new_shape)) \
            and len(self._shape) == len(new_shape)
        if not unknown_ok:
            raise AssertionError(
                "Expected shape %s is incompatible with given shape %s for "
                "Parameter %s" % (str(new_shape), str(self._shape), self.name))
        self._shape = tuple(new_shape)

    @property
    def stype(self):
        return self._stype

    # ---- init machinery -------------------------------------------------
    def _check_and_get(self, arr_dict, ctx):
        if arr_dict is not None:
            if ctx is list:
                return list(arr_dict.values())
            if ctx is None:
                if len(arr_dict) == 1:
                    return list(arr_dict.values())[0]
                ctx = current_context()
            if ctx in arr_dict:
                return arr_dict[ctx]
            raise RuntimeError(
                "Parameter '%s' was not initialized on context %s. It was "
                "only initialized on %s." % (
                    self.name, str(ctx), str(list(arr_dict.keys()))))
        if self._deferred_init:
            raise DeferredInitializationError(
                "Parameter '%s' has not been initialized yet because "
                "initialization was deferred. Actual initialization happens "
                "during the first forward pass. Please pass one batch of "
                "data through the network before accessing Parameters."
                % self.name)
        raise RuntimeError(
            "Parameter '%s' has not been initialized. Note that you should "
            "initialize parameters and create Trainer with Block.collect_"
            "params() instead of Block.params because the later does not "
            "include Parameters of nested child Blocks" % self.name)

    def _load_init(self, data, ctx):
        """Override init with data from load (ref parameter.py:_load_init)."""
        if self.shape:
            for self_dim, data_dim in zip(self.shape, data.shape):
                if self_dim not in (0, -1) and self_dim != data_dim:
                    raise AssertionError(
                        "Failed loading Parameter '%s' from saved params: "
                        "shape incompatible expected %s vs saved %s" % (
                            self.name, str(self.shape), str(data.shape)))
            self._shape = tuple(data.shape)
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is None:
            if self._deferred_init:
                if ctx is not None and set(ctx) != set(self._deferred_init[1]):
                    raise AssertionError(
                        "Failed to load Parameter '%s' on %s because it was "
                        "previous initialized on %s." % (
                            self.name, str(ctx), str(self.list_ctx())))
                ctx = self._deferred_init[1]
            elif ctx is None:
                ctx = [cpu()]
            self._init_impl(data, ctx)
        else:
            if ctx is not None and set(ctx) != set(self._data.keys()):
                raise AssertionError(
                    "Failed to load Parameter '%s' on %s because it was "
                    "previous initialized on %s." % (
                        self.name, str(ctx), str(self.list_ctx())))
            self.set_data(data)
        self._deferred_init = ()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init, data = self._deferred_init
        self._deferred_init = ()
        if self.shape is None or np.prod(self.shape) <= 0:
            raise ValueError(
                "Cannot initialize Parameter '%s' because it has invalid "
                "shape: %s." % (self.name, str(self.shape)))
        if data is None:
            data = nd.zeros(self.shape, dtype=self.dtype, ctx=cpu())
            init_obj = init if init is not None else (
                self.init if self.init is not None else default_init)
            if isinstance(init_obj, str):
                init_obj = initializer.create(init_obj)
            init_obj(InitDesc(self.name), data)
        self._init_impl(data, ctx)

    def _init_impl(self, data, ctx_list):
        self._data = OrderedDict()
        for ctx in ctx_list:
            self._data[ctx] = nd.array(
                data.asnumpy() if isinstance(data, nd.NDArray) else data,
                dtype=self.dtype, ctx=ctx)
        from .. import memwatch as _memwatch
        if _memwatch.enabled:
            _memwatch.tag("params", list(self._data.values()),
                          detail="gluon")
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        from .. import autograd
        self._grad = OrderedDict()
        for ctx, arr in self._data.items():
            self._grad[ctx] = nd.zeros(arr.shape, dtype=arr.dtype, ctx=ctx)
            autograd.mark_variables(arr, self._grad[ctx], self._grad_req)
        from .. import memwatch as _memwatch
        if _memwatch.enabled:
            _memwatch.tag("activations", list(self._grad.values()),
                          detail="grad")

    def _reduce(self):
        """Reduce data from multiple contexts to cpu (ref parameter.py:312)."""
        data = self.list_data()
        if len(data) == 1:
            return data[0].copyto(cpu())
        out = sum(d.asnumpy() for d in data) / len(data)
        return nd.array(out, dtype=self.dtype, ctx=cpu())

    # ---- public API -----------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Initialize parameter + gradient arrays; deferred if shape unknown."""
        if default_init is None:
            default_init = initializer.Uniform()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if init is None:
            init = default_init if self.init is None else self.init
        if self.shape is None or np.prod([s if s > 0 else 0
                                          for s in self.shape]) <= 0:
            if self.allow_deferred_init:
                self._deferred_init = (init, ctx, default_init, None)
                return
            raise ValueError(
                "Cannot initialize Parameter '%s' because it has invalid "
                "shape: %s. Please specify in_units/in_channels/etc for "
                "`Block`s." % (self.name, str(self.shape)))
        self._deferred_init = (init, ctx, default_init, None)
        self._finish_deferred_init()

    def reset_ctx(self, ctx):
        """Re-assign Parameter to other contexts."""
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data:
            data = self._reduce()
            self._init_impl(data, ctx)
        elif self._deferred_init:
            init, _, default_init, data = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)
        else:
            raise ValueError(
                "Cannot reset context for Parameter '%s' because it has not "
                "been initialized." % self.name)

    def set_data(self, data):
        """Set this parameter's value on all contexts."""
        self.shape = data.shape
        if self._data is None:
            if not self._deferred_init:
                raise AssertionError(
                    "Parameter '%s' has not been initialized" % self.name)
            self._deferred_init = self._deferred_init[:3] + (data,)
            return
        npdata = data.asnumpy() if isinstance(data, nd.NDArray) else np.asarray(data)
        for ctx, arr in self._data.items():
            arr[:] = nd.array(npdata, dtype=arr.dtype, ctx=ctx)

    def data(self, ctx=None):
        """Return a copy of this parameter on one context."""
        return self._check_and_get(self._data, ctx)

    def list_data(self) -> List[nd.NDArray]:
        return self._check_and_get(self._data, list)

    def grad(self, ctx=None):
        if self._data is not None and self._grad is None:
            raise RuntimeError(
                "Cannot get gradient array for Parameter '%s' because "
                "grad_req='null'" % self.name)
        return self._check_and_get(self._grad, ctx)

    def list_grad(self):
        if self._data is not None and self._grad is None:
            raise RuntimeError(
                "Cannot get gradient array for Parameter '%s' because "
                "grad_req='null'" % self.name)
        return self._check_and_get(self._grad, list)

    def list_ctx(self):
        if self._data is None:
            if self._deferred_init:
                return self._deferred_init[1]
            raise RuntimeError(
                "Parameter '%s' has not been initialized" % self.name)
        return list(self._data.keys())

    def zero_grad(self):
        """Set gradient buffer on all contexts to 0."""
        if self._grad is None:
            return
        for g in self._grad.values():
            g[:] = 0

    def var(self):
        """Symbol representing this parameter."""
        from .. import symbol
        if self._var is None:
            self._var = symbol.var(
                self.name, shape=self.shape, dtype=self.dtype,
                lr_mult=self.lr_mult, wd_mult=self.wd_mult, init=self.init)
        return self._var

    def cast(self, dtype):
        """Cast data and gradient of this Parameter to a new dtype."""
        self.dtype = dtype
        if self._data is None:
            return
        with_grad = self._grad is not None
        data = {ctx: arr.astype(dtype) for ctx, arr in self._data.items()}
        self._data = OrderedDict(data)
        if with_grad:
            self._init_grad()


class Constant(Parameter):
    """A constant parameter (never updated by the trainer).

    Parity: gluon/parameter.py Constant.
    """

    def __init__(self, name, value):
        if not isinstance(value, nd.NDArray):
            value = nd.array(value)
        self.value = value

        class Init(initializer.Initializer):
            def _init_weight(self2, _, arr):
                arr[:] = value

        super().__init__(
            name, grad_req="null", shape=value.shape, dtype=value.dtype,
            init=Init(), differentiable=False)


class ParameterDict:
    """A dictionary managing a set of parameters.

    Parity: gluon/parameter.py ParameterDict.
    """

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def __repr__(self):
        name = self._prefix + " " if self._prefix else ""
        return "%s(\n%s\n)" % (
            name, "\n".join("  " + repr(v) for v in self.values()))

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def __contains__(self, key):
        return key in self._params

    def __len__(self):
        return len(self._params)

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        """Retrieve or create a :class:`Parameter` named ``prefix+name``."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and v is not None and existing is not None:
                        # merge unknown dims
                        if len(v) == len(existing):
                            merged = tuple(
                                ev if sv in (0, -1) else sv
                                for sv, ev in zip(v, existing))
                            param._shape = tuple(
                                mv if ev in (0, -1) else ev
                                for mv, ev in zip(merged, existing))
                            continue
                    if k in ("lr_mult", "wd_mult", "grad_req") or v is None \
                            or v == existing:
                        if v is not None and v != existing:
                            setattr(param, k, v)
                        continue
                    raise AssertionError(
                        "Cannot retrieve Parameter '%s' because desired "
                        "attribute does not match with stored for attribute "
                        "'%s': desired '%s' vs stored '%s'." % (
                            name, k, str(v), str(getattr(param, k))))
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError(
                    "No constant named '%s'. Please specify value if you "
                    "want to create a new constant." % name)
            param = Constant(name, value)
            self._params[name] = param
        elif value is not None:
            if not isinstance(param, Constant):
                raise AssertionError(
                    "Parameter '%s' already exists but is not a constant"
                    % name)
        return param

    def update(self, other):
        """Copy all Parameters in ``other`` to self."""
        for k, v in other.items():
            if k in self._params:
                if self._params[k] is not v:
                    raise ValueError(
                        "Cannot update self with other because they have "
                        "different Parameters with the same name '%s'" % k)
            else:
                self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if init is None:
            init = initializer.Uniform()
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        """Set an attribute on all Parameters (e.g. grad_req, lr_mult)."""
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        arg_dict = {}
        for param in self.values():
            weight = param._reduce()
            if not param.name.startswith(strip_prefix):
                raise ValueError(
                    "Prefix '%s' is to be striped before saving, but "
                    "Parameter's name '%s' does not start with it." % (
                        strip_prefix, param.name))
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd.save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        if restore_prefix:
            for name in self.keys():
                if not name.startswith(restore_prefix):
                    raise AssertionError(
                        "restore_prefix is '%s' but Parameter name '%s' does "
                        "not start with it" % (restore_prefix, name))
        lprefix = len(restore_prefix)
        loaded = nd.load(filename)
        arg_dict = {}
        for k, v in loaded.items():
            k = k[4:] if k.startswith("arg:") or k.startswith("aux:") else k
            arg_dict[restore_prefix + k] = v
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise AssertionError(
                        "Parameter '%s' is missing in file '%s'" % (
                            name[lprefix:], filename))
        for name in arg_dict:
            if name not in self._params:
                if not ignore_extra:
                    raise AssertionError(
                        "Parameter '%s' loaded from file '%s' is not present "
                        "in ParameterDict" % (name[lprefix:], filename))
                continue
            self[name]._load_init(arg_dict[name], ctx)
