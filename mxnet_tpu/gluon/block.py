"""Gluon Block / HybridBlock / SymbolBlock — define-by-run with hybridization.

Reference analog: ``python/mxnet/gluon/block.py`` (``Block:126``,
``HybridBlock:669``, ``_build_cache``/CachedOp at ``:746-795``,
``SymbolBlock:950``).

TPU-native notes: ``hybridize()`` traces ``hybrid_forward`` once with Symbols
and compiles the whole subgraph with XLA via :class:`mxnet_tpu.cached_op.
CachedOp` — the analog of the reference's NNVM-graph CachedOp, except memory
planning/fusion are XLA's job.  Un-hybridized imperative calls dispatch per-op
through shape-cached XLA executables.
"""
from __future__ import annotations

import copy
import re
import threading
from collections import OrderedDict

from ..base import MXNetError
from ..context import Context, current_context
from .. import ndarray
from .. import symbol as _symbol
from ..symbol import Symbol
from ..ndarray import NDArray
from ..name import NameManager, Prefix as _PrefixScope, current_scope
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]

_naming = threading.local()


class _BlockScope:
    """Scope for child block naming + parameter sharing (ref block.py:33)."""

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def _current():
        return getattr(_naming, "scope", None)

    @staticmethod
    def create(prefix, params, hint):
        """Create prefix and params for a new Block."""
        current = _BlockScope._current()
        if current is None:
            if prefix is None:
                prefix = current_scope().get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = "%s%d_" % (hint, count)
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = _BlockScope._current()
        _naming.scope = self
        self._name_scope = _PrefixScope(self._block.prefix)
        self._name_scope.__enter__()
        return self

    def __exit__(self, *exc):
        if self._block._empty_prefix:
            return
        self._name_scope.__exit__(*exc)
        self._name_scope = None
        _naming.scope = self._old_scope


def _flatten(args, inout_str):
    if isinstance(args, NDArray):
        return [args], int(0)
    if isinstance(args, Symbol):
        length = len(args.list_outputs())
        length = length if length > 1 else 0
        return [args], int(length)
    if not isinstance(args, (list, tuple)):
        raise ValueError(
            "When hybridized, the input of HybridBlock %s must be (nested) "
            "list of Symbol or NDArray, but got %s of type %s" % (
                inout_str, str(args), str(type(args))))
    flat, fmts = [], []
    for i in args:
        arg, fmt = _flatten(i, inout_str)
        flat.extend(arg)
        fmts.append(fmt)
    return flat, fmts


def _regroup(args, fmt):
    if isinstance(fmt, int):
        if fmt == 0:
            return args[0], args[1:]
        return args[:fmt], args[fmt:]
    ret = []
    for i in fmt:
        res, args = _regroup(args, i)
        ret.append(res)
    return ret, args


class Block:
    """Base class for all neural network layers and models
    (parity: gluon/block.py:126)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            "  ({key}): {block}".format(
                key=key, block=re.sub("(?m)^", "  ", repr(block)).strip())
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        """Registers parameters and children."""
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)) and \
                    not isinstance(existing, type(value)):
                raise TypeError(
                    "Changing attribute type for %s from %s to %s is not "
                    "allowed." % (self.name, type(existing), type(value)))
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            if name in self.__dict__.get("_reg_params", {}):
                pass
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def _check_container_with_block(self):
        pass

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        """Name scope managing child naming/params (use in __init__)."""
        return self._scope

    @property
    def params(self):
        """ParameterDict of this Block only (not children)."""
        return self._params

    def collect_params(self, select=None):
        """ParameterDict of this Block AND all children.

        ``select`` regex filters by name, e.g. ``'.*weight'``.
        """
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename):
        """Save parameters to file (structure-based names; ref block.py)."""
        params = self._collect_params_with_prefix()
        arg_dict = {key: val._reduce() for key, val in params.items()}
        ndarray.save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False):
        loaded = ndarray.load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        if not any("." in i for i in loaded.keys()):
            # legacy loading: collect_params().load
            del loaded
            self.collect_params().load(
                filename, ctx, allow_missing, ignore_extra, self.prefix)
            return
        if not allow_missing:
            for name in params.keys():
                if name not in loaded:
                    raise AssertionError(
                        "Parameter '%s' is missing in file '%s'" % (
                            name, filename))
        for name in loaded:
            if not ignore_extra and name not in params:
                raise AssertionError(
                    "Parameter '%s' loaded from file '%s' is not present in "
                    "this Block" % (name, filename))
            if name in params:
                params[name]._load_init(loaded[name], ctx)

    # legacy aliases (ref block.py save_params/load_params)
    def save_params(self, filename):
        self.collect_params().save(filename, strip_prefix=self.prefix)

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        self.load_parameters(filename, ctx, allow_missing, ignore_extra)

    def register_child(self, block, name=None):
        """Register a child block for parameter collection / cascading."""
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_hook(self, hook):
        handle = _HookHandle(self._forward_hooks)
        self._forward_hooks[handle.id] = hook
        return handle

    def apply(self, fn):
        """Apply ``fn`` recursively to self and children."""
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        """Initialize all Parameters of this Block and children."""
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def hybridize(self, active=True, **kwargs):
        """Activate HybridBlocks recursively (no-op on plain Blocks)."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def cast(self, dtype):
        """Cast parameters + computation of this Block to dtype."""
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def __call__(self, *args):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):
        """Override to define the computation."""
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a summary of the Block (layer names, shapes, #params)."""
        from numpy import prod as np_prod
        summary = OrderedDict()
        hooks = []

        def _get_shape_str(args):
            flat_args, _ = _flatten(args, "output") \
                if isinstance(args, (list, tuple, NDArray)) else ([args], 0)
            shapes = [x.shape if isinstance(x, NDArray) else None
                      for x in flat_args]
            return str(shapes[0] if len(shapes) == 1 else shapes)

        def _register_summary_hook(block):
            def _summary_hook(block, _, outputs):
                class_name = block.__class__.__name__
                m_key = "%s-%i" % (class_name, len(summary))
                summary[m_key] = OrderedDict()
                summary[m_key]["output_shape"] = _get_shape_str(outputs)
                params = 0
                summary[m_key]["trainable"] = 0
                for p in block.params.values():
                    n = int(np_prod(p.shape)) if p.shape else 0
                    params += n
                    if p.grad_req != "null":
                        summary[m_key]["trainable"] += n
                summary[m_key]["n_params"] = params
            hooks.append(block.register_forward_hook(_summary_hook))

        summary["Input"] = OrderedDict()
        summary["Input"]["output_shape"] = _get_shape_str(inputs)
        summary["Input"]["n_params"] = 0
        summary["Input"]["trainable"] = 0
        summary["Input"]["shared"] = 0
        try:
            self.apply(_register_summary_hook)
            self(*inputs)
            line_format = "{:>20}  {:>42} {:>15}"
            print("-" * 80)
            print(line_format.format("Layer (type)", "Output Shape", "Param #"))
            print("=" * 80)
            total_params = 0
            for layer in summary:
                print(line_format.format(
                    layer, str(summary[layer]["output_shape"]),
                    summary[layer]["n_params"]))
                total_params += summary[layer]["n_params"]
            print("=" * 80)
            print("Total params: " + str(total_params))
            print("-" * 80)
        finally:
            for h in hooks:
                h.detach()


class _HookHandle:
    _next_id = [0]

    def __init__(self, hooks_dict):
        self.id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1
        self._hooks = hooks_dict

    def detach(self):
        self._hooks.pop(self.id, None)


class HybridBlock(Block):
    """A Block with support for hybridization (parity: gluon/block.py:669).

    Forward must be expressed as ``hybrid_forward(self, F, x, *args,
    **params)`` where ``F`` is :mod:`mxnet_tpu.ndarray` or
    :mod:`mxnet_tpu.symbol`; ``hybridize()`` switches execution to a
    whole-graph XLA-compiled :class:`CachedOp`.
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._cached_graph = ()
        self._cached_op = None
        self._out_format = None
        self._in_format = None
        self._active = False
        self._flags = []

    def __setattr__(self, name, value):
        super().__setattr__(name, value)
        if isinstance(value, HybridBlock):
            self._clear_cached_op()

    def _get_graph(self, *args):
        if not self._cached_graph:
            flat_args, self._in_format = _flatten(args, "input")
            inputs = [_symbol.var("data%d" % i) for i in
                      range(len(flat_args))] if len(flat_args) > 1 \
                else [_symbol.var("data")]
            grouped_inputs = _regroup(inputs, self._in_format)[0]
            if not isinstance(grouped_inputs, list):
                grouped_inputs = [grouped_inputs]
            params = {i: j.var() for i, j in self._reg_params.items()}
            with self.name_scope():
                out = self.hybrid_forward(_symbol, *grouped_inputs, **params)
            out, self._out_format = _flatten(out, "output")
            self._cached_graph = inputs, _symbol.Group(out)
        return self._cached_graph

    def _build_cache(self, *args):
        from ..cached_op import CachedOp
        data, out = self._get_graph(*args)
        data_names = {d.name: i for i, d in enumerate(data)}
        params = self.collect_params()
        input_names = out.list_inputs()
        param_names = set(params.keys())
        expected_names = set(input_names)
        for name in expected_names:
            if name not in param_names and name not in data_names:
                raise MXNetError(
                    "Unknown input to HybridBlock: %s" % name)
        # warn-free: unused inputs simply dropped
        self._cached_op_args = []
        for name in input_names:
            if name in data_names:
                self._cached_op_args.append((True, data_names[name]))
            else:
                self._cached_op_args.append((False, params[name]))
        self._cached_op = CachedOp(out, self._flags)

    def _deferred_infer_shape(self, *args):
        try:
            self.infer_shape(*args)
        except Exception as e:
            raise ValueError(
                "Deferred initialization failed because shape cannot be "
                "inferred: %s" % e)

    def _call_cached_op(self, *args):
        if self._cached_op is None:
            self._build_cache(*args)
        flat_args, fmt = _flatten(args, "input")
        if fmt != self._in_format:
            raise ValueError("Invalid input format")
        try:
            cargs = []
            for is_arg, item in self._cached_op_args:
                if is_arg:
                    cargs.append(flat_args[item])
                else:
                    cargs.append(item.data())
        except DeferredInitializationError:
            self._deferred_infer_shape(*args)
            cargs = []
            for is_arg, item in self._cached_op_args:
                if is_arg:
                    cargs.append(flat_args[item])
                else:
                    item._finish_deferred_init()
                    cargs.append(item.data())
        out = self._cached_op(*cargs)
        if isinstance(out, NDArray):
            out = [out]
        return _regroup(list(out), self._out_format)[0]

    def _clear_cached_op(self):
        self._cached_graph = ()
        self._cached_op = None

    def register_child(self, block, name=None):
        if not isinstance(block, HybridBlock):
            raise ValueError(
                "Children of HybridBlock must also be HybridBlock, but %s "
                "has type %s." % (str(block), str(type(block))))
        super().register_child(block, name)
        self._clear_cached_op()

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._flags = list(kwargs.items())
        self._clear_cached_op()
        super().hybridize(active, **kwargs)

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def infer_shape(self, *args):
        """Infer parameter shapes from inputs (deferred-init resolution)."""
        inputs, out = self._get_graph(*args)
        flat_args, _ = _flatten(args, "input")
        kwargs = {i.name: j.shape for i, j in zip(inputs, flat_args)}
        arg_shapes, _, aux_shapes = out.infer_shape(**kwargs)
        sdict = {i: j for i, j in zip(out.list_arguments(), arg_shapes)}
        sdict.update({i: j for i, j in zip(
            out.list_auxiliary_states(), aux_shapes)})
        for i in self.collect_params().values():
            if i.name in sdict:
                i.shape = sdict[i.name]

    def infer_type(self, *args):
        pass

    def export(self, path, epoch=0):
        """Export model graph JSON + params in reference checkpoint format
        (``path-symbol.json`` + ``path-%04d.params``)."""
        if not self._cached_graph:
            raise RuntimeError(
                "Please first call block.hybridize() and then run forward "
                "with this block at least once before calling export.")
        sym = self._cached_graph[1]
        sym.save("%s-symbol.json" % path)
        arg_names = set(sym.list_arguments())
        aux_names = set(sym.list_auxiliary_states())
        arg_dict = {}
        for name, param in self.collect_params().items():
            if name in arg_names:
                arg_dict["arg:%s" % name] = param._reduce()
            elif name in aux_names:
                arg_dict["aux:%s" % name] = param._reduce()
        ndarray.save("%s-%04d.params" % (path, epoch), arg_dict)

    def forward(self, x, *args):
        """Dispatch: NDArray → imperative/cached; Symbol → symbolic."""
        if isinstance(x, NDArray):
            if self._active:
                return self._call_cached_op(x, *args)
            # resolve the replica on the INPUT's context (reference
            # gluon/block.py semantics) — multi-device training runs one
            # forward per context over the same block
            try:
                params = {i: j.data(x.context)
                          for i, j in self._reg_params.items()}
            except DeferredInitializationError:
                self._deferred_infer_shape(x, *args)
                for _, i in self.params.items():
                    i._finish_deferred_init()
                params = {i: j.data(x.context)
                          for i, j in self._reg_params.items()}
            return self.hybrid_forward(ndarray, x, *args, **params)
        if not isinstance(x, Symbol):
            raise ValueError(
                "In HybridBlock, there must be one NDArray or one Symbol in "
                "the input. Please check the type of the args.\n")
        params = {i: j.var() for i, j in self._reg_params.items()}
        with self.name_scope():
            return self.hybrid_forward(_symbol, x, *args, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        """Override to define the computation; use ``F`` for ops."""
        raise NotImplementedError


class SymbolBlock(HybridBlock):
    """Construct a block from a Symbol (for loading exported models).

    Parity: gluon/block.py:950.
    """

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        sym = _symbol.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [_symbol.var(i) for i in input_names]
        ret = SymbolBlock(sym, inputs)
        if param_file is not None:
            ret.collect_params().load(param_file, ctx=ctx,
                                      allow_missing=False, ignore_extra=True,
                                      restore_prefix="")
        elif ctx is not None:
            ret.collect_params().reset_ctx(ctx)
        return ret

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=params)
        self._prefix = ""
        self._params = ParameterDict("", params)
        if isinstance(inputs, (Symbol,)) and len(inputs.list_outputs()) == 1:
            inputs = [inputs]
        if isinstance(outputs, (list, tuple)) and len(outputs) == 1 and \
                isinstance(outputs[0], list):
            outputs = outputs[0]
        syms, self._in_format = _flatten(inputs, "input")
        out, self._out_format = _flatten(outputs, "output")
        out = _symbol.Group(out)

        input_names = set()
        for i in syms:
            if len(i.get_internals().list_outputs()) != 1:
                raise AssertionError(
                    "Input symbols must be variable, but %s is an output of "
                    "operators" % str(i))
            input_names.add(i.name)

        for i in out.list_arguments():
            if i not in input_names:
                self.params.get(i, allow_deferred_init=True)
        for i in out.list_auxiliary_states():
            if i not in input_names:
                self.params.get(i, grad_req="null",
                                allow_deferred_init=True)
        self._cached_graph = syms, out
        self._build_cache()

    def _build_cache(self, *args):
        from ..cached_op import CachedOp
        data, out = self._cached_graph
        data_names = {d.name: i for i, d in enumerate(data)}
        params = self.collect_params()
        self._cached_op_args = []
        for name in out.list_inputs():
            if name in data_names:
                self._cached_op_args.append((True, data_names[name]))
            else:
                self._cached_op_args.append((False, params[name]))
        self._cached_op = CachedOp(out, self._flags)

    def forward(self, x, *args):
        if isinstance(x, NDArray):
            return self._call_cached_op(x, *args)
        if not isinstance(x, Symbol):
            raise ValueError(
                "In SymbolBlock, there must be one NDArray or one Symbol in "
                "the input. Please check the type of the args.\n")
        args, in_fmt = _flatten([x] + list(args), "input")
        if in_fmt != self._in_format:
            raise ValueError("Invalid input format")
        ret = copy.copy(self._cached_graph[1])
        composed = {k.name: v for k, v in zip(self._cached_graph[0], args)}
        ret._compose(**composed)
        return _regroup(list(ret), self._out_format)[0]

    def _clear_cached_op(self):
        tmp = self._cached_graph
        super()._clear_cached_op()
        self._cached_graph = tmp

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError
