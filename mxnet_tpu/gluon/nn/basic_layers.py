"""Gluon basic nn layers.

Reference analog: ``python/mxnet/gluon/nn/basic_layers.py`` (Sequential,
HybridSequential, Dense, Dropout, BatchNorm, Embedding, LayerNorm,
InstanceNorm, Flatten, Lambda, HybridLambda).
"""
from __future__ import annotations

import numpy as np

from ..block import Block, HybridBlock
from .activations import Activation
from ... import ndarray, symbol

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Embedding",
           "BatchNorm", "InstanceNorm", "LayerNorm", "Flatten", "Lambda",
           "HybridLambda"]


class Sequential(Block):
    """Stacks Blocks sequentially (ref basic_layers.py Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        """Add block(s) on top of the stack."""
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            "  ({key}): {block}".format(key=key, block=_indent(repr(block)))
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def hybridize(self, active=True, **kwargs):
        """Plain Sequential cannot be hybridized whole; cascades to
        children (use HybridSequential for whole-graph compile)."""
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stacks HybridBlocks sequentially; hybridizable whole."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            "  ({key}): {block}".format(key=key, block=_indent(repr(block)))
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)


def _indent(s):
    import re
    return re.sub("(?m)^", "  ", s).strip()


class Dense(HybridBlock):
    """Fully-connected layer: out = act(dot(x, W.T) + b).

    One MXU matmul per call (ref: gluon/nn Dense over FullyConnected,
    src/operator/nn/fully_connected.cc).
    """

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._flatten = flatten
        with self.name_scope():
            self._units = units
            self._in_units = in_units
            self.weight = self.params.get(
                "weight", shape=(units, in_units),
                init=weight_initializer, dtype=dtype,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer,
                    dtype=dtype, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def hybrid_forward(self, F, x, weight, bias=None):
        if bias is None:
            act = F.FullyConnected(x, weight, no_bias=True,
                                   num_hidden=self._units,
                                   flatten=self._flatten, name="fwd")
        else:
            act = F.FullyConnected(x, weight, bias, no_bias=False,
                                   num_hidden=self._units,
                                   flatten=self._flatten, name="fwd")
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        s = "{name}({layout}, {act})"
        shape = self.weight.shape
        return s.format(
            name=self.__class__.__name__,
            act=self.act if self.act else "linear",
            layout="{0} -> {1}".format(
                shape[1] if shape[1] else None, shape[0]))


class Dropout(HybridBlock):
    """Randomly zeroes inputs with probability ``rate`` at train time
    (ref: src/operator/nn/dropout.cc; inverted-scale convention)."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes, name="fwd")

    def __repr__(self):
        return "{name}(p = {_rate}, axes={_axes})".format(
            name=self.__class__.__name__, **self.__dict__)


class Embedding(HybridBlock):
    """Turns int indices into dense vectors — one XLA gather
    (ref: src/operator/tensor/indexing_op.cc Embedding)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype}
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim),
            init=weight_initializer, dtype=dtype,
            allow_deferred_init=True,
            grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, name="fwd", **self._kwargs)

    def __repr__(self):
        return "{name}({input_dim} -> {output_dim}, {dtype})".format(
            name=self.__class__.__name__, **self._kwargs)


class BatchNorm(HybridBlock):
    """Batch normalization with moving statistics
    (ref: gluon/nn BatchNorm over src/operator/nn/batch_norm.cc)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        if in_channels != 0:
            self.in_channels = in_channels
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True, differentiable=scale)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True, differentiable=center)
        self.running_mean = self.params.get(
            "running_mean", grad_req="null", shape=(in_channels,),
            init=running_mean_initializer, allow_deferred_init=True,
            differentiable=False)
        self.running_var = self.params.get(
            "running_var", grad_req="null", shape=(in_channels,),
            init=running_variance_initializer, allow_deferred_init=True,
            differentiable=False)

    def cast(self, dtype):
        if np.dtype(dtype).name == "float16":
            dtype = "float32"
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           name="fwd", **self._kwargs)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return "{name}({content}, in_channels={in_channels})".format(
            name=self.__class__.__name__,
            content=", ".join(
                "=".join([k, str(v)]) for k, v in self._kwargs.items()),
            in_channels=in_channels)


class InstanceNorm(HybridBlock):
    """Instance normalization (Ulyanov et al., 2016)."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"eps": epsilon, "axis": axis, "center": center,
                        "scale": scale}
        self._axis = axis
        self._epsilon = epsilon
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, name="fwd",
                                  eps=self._epsilon)
        x = x.swapaxes(1, self._axis)
        return F.InstanceNorm(x, gamma, beta, name="fwd",
                              eps=self._epsilon).swapaxes(1, self._axis)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return "{name}({content}, in_channels={in_channels})".format(
            name=self.__class__.__name__,
            content=", ".join(
                "=".join([k, str(v)]) for k, v in self._kwargs.items()),
            in_channels=in_channels)


class LayerNorm(HybridBlock):
    """Layer normalization (Ba et al., 2016)
    (ref: src/operator/nn/layer_norm.cc)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kwargs = {"eps": epsilon, "axis": axis, "center": center,
                        "scale": scale}
        self._axis = axis
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)

    def hybrid_forward(self, F, data, gamma, beta):
        return F.LayerNorm(data, gamma=gamma, beta=beta, axis=self._axis,
                           eps=self._epsilon)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return "{name}({content}, in_channels={in_channels})".format(
            name=self.__class__.__name__,
            content=", ".join(
                "=".join([k, str(v)]) for k, v in self._kwargs.items()),
            in_channels=in_channels)


class Flatten(HybridBlock):
    """Flattens input to (batch, -1)."""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return self.__class__.__name__


class Lambda(Block):
    """Wraps a function as a Block.

    ``function`` is a str naming an op in mxnet_tpu.ndarray, or a callable.
    """

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            if not hasattr(ndarray, function):
                raise AssertionError(
                    "Function name %s is not found in ndarray." % function)
            self._func_impl = getattr(ndarray, function)
        elif callable(function):
            self._func_impl = function
        else:
            raise ValueError(
                "Unrecognized function in lambda: {} of type {}".format(
                    function, type(function)))
        self._func_name = getattr(self._func_impl, "__name__", "custom")

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return "{name}({function})".format(
            name=self.__class__.__name__, function=self._func_name)


class HybridLambda(HybridBlock):
    """Wraps a function as a HybridBlock (works on both F=ndarray/symbol)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            if not (hasattr(ndarray, function) and hasattr(symbol, function)):
                raise AssertionError(
                    "Function name %s is not found in symbol/ndarray."
                    % function)
            func_dict = {symbol: getattr(symbol, function),
                         ndarray: getattr(ndarray, function)}
            self._func = lambda F, *args: func_dict[F](*args)
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = getattr(function, "__name__", "custom")
        else:
            raise ValueError(
                "Unrecognized function in lambda: {} of type {}".format(
                    function, type(function)))

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return "{name}({function})".format(
            name=self.__class__.__name__, function=self._func_name)
