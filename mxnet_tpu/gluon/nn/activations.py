"""Gluon activation blocks (parity: python/mxnet/gluon/nn/activations.py)."""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "Swish"]


class Activation(HybridBlock):
    """Applies an activation function: 'relu', 'sigmoid', 'tanh',
    'softrelu', 'softsign'."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type, name="fwd")

    def __repr__(self):
        return "{name}({_act_type})".format(
            name=self.__class__.__name__, **self.__dict__)


class LeakyReLU(HybridBlock):
    """Leaky ReLU: f(x) = x if x > 0 else alpha*x."""

    def __init__(self, alpha, **kwargs):
        if alpha < 0:
            raise ValueError(
                "alpha must be greater than or equal to 0, got %s" % alpha)
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha, name="fwd")

    def __repr__(self):
        return "{name}({alpha})".format(
            name=self.__class__.__name__, alpha=self._alpha)


class PReLU(HybridBlock):
    """Parametric leaky ReLU: learned per-channel slope."""

    def __init__(self, alpha_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer
        if alpha_initializer is None:
            alpha_initializer = initializer.Constant(0.25)
        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(1,), init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu", name="fwd")


class ELU(HybridBlock):
    """Exponential Linear Unit: f(x) = x if x > 0 else alpha*(exp(x)-1)."""

    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    """Scaled Exponential Linear Unit (Klambauer et al., 2017)."""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu", name="fwd")


class Swish(HybridBlock):
    """Swish: x * sigmoid(beta*x) (Ramachandran et al., 2017)."""

    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x, name="fwd")
