"""Gluon neural network layers (parity: python/mxnet/gluon/nn/)."""
from .activations import *
from .basic_layers import *
from .conv_layers import *
