"""Gluon: the define-by-run API with hybridization to XLA-compiled graphs.

Reference analog: ``python/mxnet/gluon/`` (SURVEY.md §2.3).
"""
from .parameter import Parameter, Constant, ParameterDict, \
    DeferredInitializationError
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import loss
from . import rnn
from . import data
from . import model_zoo
from . import contrib
from .utils import split_data, split_and_load, clip_global_norm
