"""Gluon recurrent cells (parity: python/mxnet/gluon/rnn/rnn_cell.py).

Cell zoo: RNNCell, LSTMCell, GRUCell + Sequential/Dropout/Zoneout/Residual/
Bidirectional modifiers.  ``unroll`` builds an explicit per-step graph —
hybridized, XLA fuses the steps; for long sequences prefer the fused
:class:`~mxnet_tpu.gluon.rnn.LSTM` layer (lax.scan, one compiled step body).
"""
from __future__ import annotations

from ...base import MXNetError
from ... import ndarray, symbol
from ..block import Block, HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ModifierCell",
           "ZoneoutCell", "ResidualCell", "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _get_begin_state(cell, F, begin_state, inputs, batch_size):
    if begin_state is None:
        if F is ndarray or getattr(F, "__name__", "").endswith("ndarray"):
            ctx = inputs.context if isinstance(inputs, ndarray.NDArray) \
                else inputs[0].context
            begin_state = cell.begin_state(
                func=ndarray.zeros, batch_size=batch_size, ctx=ctx)
        else:
            begin_state = cell.begin_state(
                func=symbol.zeros, batch_size=batch_size)
    return begin_state


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    assert inputs is not None, \
        "unroll(inputs=None) is only supported for HybridBlocks"
    axis = layout.find("T")
    batch_axis = layout.find("N")
    batch_size = 0
    in_axis = in_layout.find("T") if in_layout is not None else axis
    if isinstance(inputs, symbol.Symbol):
        F = symbol
        if merge is False:
            if len(inputs.list_outputs()) != 1:
                raise AssertionError(
                    "unroll doesn't allow grouped symbol as input. Please "
                    "convert to list with list(inputs) first or let unroll "
                    "handle splitting.")
            inputs = list(symbol.split(
                inputs, axis=in_axis, num_outputs=length, squeeze_axis=1))
    elif isinstance(inputs, ndarray.NDArray):
        F = ndarray
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            if length is not None and length != inputs.shape[in_axis]:
                raise AssertionError("length %s != input length %s" % (
                    length, inputs.shape[in_axis]))
            inputs = _as_list(ndarray.split(
                inputs, axis=in_axis, num_outputs=inputs.shape[in_axis],
                squeeze_axis=1))
    else:
        assert length is None or len(inputs) == length
        if isinstance(inputs[0], symbol.Symbol):
            F = symbol
        else:
            F = ndarray
            batch_size = inputs[0].shape[batch_axis]
        if merge is True:
            inputs = F.stack(*inputs, axis=axis)
            in_axis = axis
    if isinstance(inputs, (symbol.Symbol, ndarray.NDArray)) and \
            axis != in_axis:
        inputs = F.swapaxes(inputs, dim1=axis, dim2=in_axis)
    return inputs, axis, F, batch_size


def _as_list(obj):
    return obj if isinstance(obj, (list, tuple)) else [obj]


class RecurrentCell(Block):
    """Abstract base class for RNN cells (ref rnn_cell.py:RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        """Reset before re-using the cell for a new graph."""
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            cell.reset()

    def state_info(self, batch_size=0):
        """Shape and layout information of states."""
        raise NotImplementedError

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states for this cell."""
        assert not self._modified, \
            "After applying modifier cells (e.g. ZoneoutCell) the base cell " \
            "cannot be called directly. Call the modifier cell instead."
        if func is None:
            func = ndarray.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            state = func(name="%sbegin_state_%d" % (
                self._prefix, self._init_counter), **info)
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll the cell for ``length`` timesteps."""
        self.reset()
        inputs, axis, F, batch_size = _format_sequence(
            length, inputs, layout, False)
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        outputs, _, _, _ = _format_sequence(
            length, outputs, layout, merge_outputs)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        """Get activation function; convert if string."""
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def __call__(self, inputs, states):
        """One step: (input, states) -> (output, new_states)."""
        self._counter += 1
        return super().__call__(inputs, states)

    def forward(self, inputs, states):
        raise NotImplementedError


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """RecurrentCell with hybrid_forward."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, inputs, states):
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell: h' = act(i2h(x) + h2h(h))."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def _alias(self):
        return "rnn"

    def __repr__(self):
        s = "{name}({mapping}"
        if hasattr(self, "_activation"):
            s += ", {_activation}"
        s += ")"
        shape = self.i2h_weight.shape
        mapping = "{0} -> {1}".format(
            shape[1] if shape[1] else None, shape[0])
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = "t%d_" % self._counter
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size,
                               name=prefix + "i2h")
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size,
                               name=prefix + "h2h")
        output = self._get_activation(F, i2h + h2h, self._activation,
                                      name=prefix + "out")
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell (Hochreiter & Schmidhuber, 1997); gate order [i, f, g, o]
    matching the fused RNN op."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_i", "_f", "_c", "_o"]

    def _alias(self):
        return "lstm"

    def __repr__(self):
        shape = self.i2h_weight.shape
        mapping = "{0} -> {1}".format(
            shape[1] if shape[1] else None, shape[0] // 4)
        return "{name}({mapping})".format(
            name=self.__class__.__name__, mapping=mapping)

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = "t%d_" % self._counter
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size,
                               name=prefix + "i2h")
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size,
                               name=prefix + "h2h")
        gates = i2h + h2h
        slice_gates = F.SliceChannel(gates, num_outputs=4,
                                     name=prefix + "slice")
        in_gate = F.Activation(slice_gates[0], act_type="sigmoid",
                               name=prefix + "i")
        forget_gate = F.Activation(slice_gates[1], act_type="sigmoid",
                                   name=prefix + "f")
        in_transform = F.Activation(slice_gates[2], act_type="tanh",
                                    name=prefix + "c")
        out_gate = F.Activation(slice_gates[3], act_type="sigmoid",
                                name=prefix + "o")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c, act_type="tanh",
                                         name=prefix + "state")
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell (Chung et al., 2014); gate order [r, z, n] (cuDNN variant)
    matching the fused RNN op."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_r", "_z", "_o"]

    def _alias(self):
        return "gru"

    def __repr__(self):
        shape = self.i2h_weight.shape
        mapping = "{0} -> {1}".format(
            shape[1] if shape[1] else None, shape[0] // 3)
        return "{name}({mapping})".format(
            name=self.__class__.__name__, mapping=mapping)

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = "t%d_" % self._counter
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size,
                               name=prefix + "i2h")
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size,
                               name=prefix + "h2h")
        i2h_r, i2h_z, i2h = F.SliceChannel(
            i2h, num_outputs=3, name=prefix + "i2h_slice")
        h2h_r, h2h_z, h2h = F.SliceChannel(
            h2h, num_outputs=3, name=prefix + "h2h_slice")
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid",
                                  name=prefix + "r_act")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid",
                                   name=prefix + "z_act")
        next_h_tmp = F.Activation(i2h + reset_gate * h2h, act_type="tanh",
                                  name=prefix + "h_act")
        next_h = (1. - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Sequentially stacking multiple RNN cells."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        return s.format(
            name=self.__class__.__name__,
            modstr="\n".join("({i}): {m}".format(i=i, m=repr(m))
                             for i, m in self._children.items()))

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        assert all(not isinstance(cell, BidirectionalCell)
                   for cell in self._children.values())
        for cell in self._children.values():
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        num_cells = len(self._children)
        inputs, _, F, batch_size = _format_sequence(
            length, inputs, layout, None)
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)
        p = 0
        next_states = []
        for i, cell in enumerate(self._children.values()):
            n = len(cell.state_info())
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def forward(self, *args, **kwargs):
        raise NotImplementedError


class DropoutCell(HybridRecurrentCell):
    """Applies dropout on input."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        assert isinstance(rate, float)
        self._rate = rate
        self._axes = axes

    def __repr__(self):
        return "{name}(rate={_rate}, axes={_axes})".format(
            name=self.__class__.__name__, **self.__dict__)

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes,
                               name="t%d_fwd" % self._counter)
        return inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, _, F, _ = _format_sequence(length, inputs, layout,
                                           merge_outputs)
        if isinstance(inputs, (ndarray.NDArray, symbol.Symbol)):
            return self.hybrid_forward(F, inputs, begin_state or [])
        return super().unroll(
            length, inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)


class ModifierCell(HybridRecurrentCell):
    """Base class for modifier cells that wrap another cell."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified. One cell cannot be modified " \
            "twice" % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError

    def __repr__(self):
        return "{name}({base_cell})".format(
            name=self.__class__.__name__, base_cell=repr(self.base_cell))


class ZoneoutCell(ModifierCell):
    """Applies Zoneout on base cell (Krueger et al., 2016)."""

    def __init__(self, base_cell, zoneout_outputs=0., zoneout_states=0.):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. Please add " \
            "ZoneoutCell to the cells underneath instead."
        super().__init__(base_cell)
        self._zoneout_outputs = zoneout_outputs
        self._zoneout_states = zoneout_states
        self._prev_output = None

    def __repr__(self):
        return ("{name}(p_out={_zoneout_outputs}, p_state={_zoneout_states}, "
                "{base_cell})").format(
            name=self.__class__.__name__, base_cell=repr(self.base_cell),
            **{k: v for k, v in self.__dict__.items()
               if k.startswith("_zoneout")})

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell, p_outputs, p_states = (
            self.base_cell, self._zoneout_outputs, self._zoneout_states)
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: F.Dropout(
            F.ones_like(like), p=p)
        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        output = F.where(mask(p_outputs, next_output), next_output,
                         prev_output) if p_outputs != 0. else next_output
        new_states = [
            F.where(mask(p_states, new_s), new_s, old_s)
            for new_s, old_s in zip(next_states, states)] \
            if p_states != 0. else next_states
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Adds residual connection: output = base(input) + input."""

    def __init__(self, base_cell):
        super().__init__(base_cell)

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        merge_outputs = isinstance(outputs, (ndarray.NDArray, symbol.Symbol)) \
            if merge_outputs is None else merge_outputs
        inputs, _, F, _ = _format_sequence(length, inputs, layout,
                                           merge_outputs)
        if merge_outputs:
            outputs = outputs + inputs
        else:
            outputs = [o + i for o, i in zip(outputs, inputs)]
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Bidirectionally process input with two cells."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def __repr__(self):
        return ("{name}(forward={l_cell}, backward={r_cell})").format(
            name=self.__class__.__name__,
            l_cell=repr(self._children["l_cell"]),
            r_cell=repr(self._children["r_cell"]))

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis, F, batch_size = _format_sequence(
            length, inputs, layout, False)
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)
        states = begin_state
        l_cell = self._children["l_cell"]
        r_cell = self._children["r_cell"]
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info())],
            layout=layout, merge_outputs=merge_outputs)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[len(l_cell.state_info()):],
            layout=layout, merge_outputs=False)
        r_outputs = list(reversed(r_outputs))
        if merge_outputs is None:
            merge_outputs = isinstance(
                l_outputs, (ndarray.NDArray, symbol.Symbol))
        if merge_outputs:
            if not isinstance(l_outputs, (ndarray.NDArray, symbol.Symbol)):
                l_outputs = F.stack(*l_outputs, axis=axis)
            r_outputs = F.stack(*r_outputs, axis=axis)
            outputs = F.concat(l_outputs, r_outputs, dim=2)
        else:
            if isinstance(l_outputs, (ndarray.NDArray, symbol.Symbol)):
                l_outputs = list(F.split(
                    l_outputs, axis=axis, num_outputs=length,
                    squeeze_axis=1))
            outputs = [
                F.concat(l_o, r_o, dim=1)
                for l_o, r_o in zip(l_outputs, r_outputs)]
        states = l_states + r_states
        return outputs, states

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError
