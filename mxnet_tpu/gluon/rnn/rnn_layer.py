"""Gluon fused recurrent layers: RNN / LSTM / GRU.

Reference analog: ``python/mxnet/gluon/rnn/rnn_layer.py:241,335,440`` —
wrappers over the fused RNN op (``src/operator/rnn-inl.h``).  On TPU the
fused op is a ``lax.scan`` whose input projection is hoisted into one MXU
matmul per layer (see :mod:`mxnet_tpu.ops.rnn`).
"""
from __future__ import annotations

from ... import ndarray
from ... import symbol as _symbol
from ...ndarray import NDArray
from ...symbol import Symbol
from ..block import HybridBlock
from . import rnn_cell

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    """Implementation of recurrent layers (ref rnn_layer.py:_RNNLayer)."""

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            "Invalid layout %s; must be one of ['TNC' or 'NTC']" % layout
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]

        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param(
                    "{}{}_i2h_weight".format(j, i), (ng * nh, ni),
                    i2h_weight_initializer)
                self._register_param(
                    "{}{}_h2h_weight".format(j, i), (ng * nh, nh),
                    h2h_weight_initializer)
                self._register_param(
                    "{}{}_i2h_bias".format(j, i), (ng * nh,),
                    i2h_bias_initializer)
                self._register_param(
                    "{}{}_h2h_bias".format(j, i), (ng * nh,),
                    h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)
        return p

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = "{0} -> {1}".format(
            shape[1] if shape[1] else None, shape[0] // self._gates)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def _collect_params_with_prefix(self, prefix=""):
        # match reference checkpoint layout (flat per-layer names)
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def _unfuse(self):
        """Unfuse into an explicit stack of cells (ref rnn_layer.py:139)."""
        get_cell = {
            "rnn_relu": lambda **kw: rnn_cell.RNNCell(
                self._hidden_size, activation="relu", **kw),
            "rnn_tanh": lambda **kw: rnn_cell.RNNCell(
                self._hidden_size, activation="tanh", **kw),
            "lstm": lambda **kw: rnn_cell.LSTMCell(self._hidden_size, **kw),
            "gru": lambda **kw: rnn_cell.GRUCell(self._hidden_size, **kw),
        }[self._mode]
        stack = rnn_cell.SequentialRNNCell(prefix=self.prefix,
                                           params=self.params)
        with stack.name_scope():
            ni = self._input_size
            for i in range(self._num_layers):
                kwargs = {
                    "input_size": ni,
                    "i2h_weight_initializer": self._i2h_weight_initializer,
                    "h2h_weight_initializer": self._h2h_weight_initializer,
                    "i2h_bias_initializer": self._i2h_bias_initializer,
                    "h2h_bias_initializer": self._h2h_bias_initializer}
                if self._dir == 2:
                    stack.add(rnn_cell.BidirectionalCell(
                        get_cell(prefix="l%d_" % i, **kwargs),
                        get_cell(prefix="r%d_" % i, **kwargs)))
                else:
                    stack.add(get_cell(prefix="l%d_" % i, **kwargs))
                if self._dropout > 0 and i != self._num_layers - 1:
                    stack.add(rnn_cell.DropoutCell(self._dropout))
                ni = self._hidden_size * self._dir
        return stack

    def begin_state(self, batch_size=0, func=ndarray.zeros, **kwargs):
        """Initial recurrent state values."""
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            states.append(func(name="%sh0_%d" % (self.prefix, i), **info))
        return states

    def __call__(self, inputs, *states):
        if self._input_size == 0 and not isinstance(inputs, NDArray):
            raise ValueError(
                "Symbolic use of %s with unknown input size: pass "
                "input_size= at construction or run one imperative batch "
                "first to resolve deferred shapes." % type(self).__name__)
        if self._input_size == 0:
            self.params.get("l0_i2h_weight").shape = (
                self._gates * self._hidden_size, inputs.shape[2])
            if self._dir == 2:
                self.params.get("r0_i2h_weight").shape = (
                    self._gates * self._hidden_size, inputs.shape[2])
            self._input_size = inputs.shape[2]
        # deferred init resolves here, not in HybridBlock.__call__: this
        # class overrides __call__/forward, so finish explicitly once the
        # input size fixes every shape (ref rnn_layer.py:176-191)
        for p in self.params.values():
            p._finish_deferred_init()
        skip_states = states in ((), (None,))
        if skip_states:
            states = []
        if isinstance(states, tuple) and len(states) == 1 and \
                isinstance(states[0], (list, tuple)):
            states = states[0]
        states = list(states)
        if isinstance(inputs, NDArray) and not states:
            batch_size = inputs.shape[self._layout.find("N")]
            states = self.begin_state(batch_size, ctx=inputs.context)
        if isinstance(states, NDArray):
            states = [states]
        out = super().__call__(inputs, states)
        # reference contract (rnn_layer.py:198): output only when the caller
        # passed no initial state, (output, new_states) otherwise
        return out[0] if skip_states else out

    def forward(self, inputs, states=None):
        if isinstance(states, (NDArray, Symbol)):
            states = [states]
        if isinstance(inputs, Symbol):
            # symbolic (hybridize / FusedTrainer) path: shapes resolve at
            # bind time; zero states are built shape-polymorphically in
            # _forward_kernel (ref rnn_layer.py:217 F.zeros path)
            return self._forward_kernel(inputs, list(states or []))
        batch_size = inputs.shape[self._layout.find("N")]
        if states is None or len(states) == 0:
            states = self.begin_state(batch_size, ctx=inputs.context)
        for state, info in zip(states, self.state_info(batch_size)):
            if state.shape != info["shape"]:
                raise ValueError(
                    "Invalid recurrent state shape. Expecting %s, got %s." % (
                        str(info["shape"]), str(state.shape)))
        out = self._forward_kernel(inputs, states)
        # out is (output, state_list)
        return out

    def _forward_kernel(self, inputs, states):
        """Forward using the fused RNN operator (NDArray or Symbol)."""
        symbolic = isinstance(inputs, Symbol)
        F = _symbol if symbolic else ndarray
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, 0, 1)

        def flat_param(name):
            p = getattr(self, name)
            v = p.var() if symbolic else p.data(inputs.context)
            return v.reshape((-1,))

        # pack parameters in the fused-op layout: all (W, R) then all biases
        ws, bs = [], []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                ws.append(flat_param("{}{}_i2h_weight".format(j, i)))
                ws.append(flat_param("{}{}_h2h_weight".format(j, i)))
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                bs.append(flat_param("{}{}_i2h_bias".format(j, i)))
                bs.append(flat_param("{}{}_h2h_bias".format(j, i)))
        params = F.concat(*(ws + bs), dim=0)

        if symbolic and not states:
            # (L*dir, B, h) zeros with B inferred from the data symbol
            z = F.zeros_like(F.mean(inputs, axis=(0, 2), keepdims=True))
            z = F.broadcast_axis(
                z, axis=(0, 2),
                size=(self._num_layers * self._dir, self._hidden_size))
            states = [z, z] if self._mode == "lstm" else [z]

        rnn_args = [inputs, params] + states
        outputs = F.RNN(
            *rnn_args, state_size=self._hidden_size,
            num_layers=self._num_layers, bidirectional=self._dir == 2,
            p=self._dropout, state_outputs=True, mode=self._mode)
        if self._mode == "lstm":
            outputs, states = outputs[0], [outputs[1], outputs[2]]
        else:
            outputs, states = outputs[0], [outputs[1]]
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, 0, 1)
        return outputs, states


class RNN(_RNNLayer):
    """Multi-layer Elman RNN with tanh/relu (ref rnn_layer.py:241)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size),
                 "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (ref rnn_layer.py:335)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size),
                 "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size),
                 "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU (cuDNN gate variant; ref rnn_layer.py:440)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size),
                 "__layout__": "LNC"}]
