"""Pretrained model store (parity: python/mxnet/gluon/model_zoo/model_store.py).

Zero-egress environment: no downloads — pretrained weights must be staged
locally under ``root`` (default ``~/.mxnet/models``); a missing file raises
with the expected filename.
"""
from __future__ import annotations

import os

__all__ = ["get_model_file", "purge"]

_model_sha1 = {}


def get_model_file(name, root=os.path.join("~", ".mxnet", "models")):
    """Return the local path of a pretrained parameter file."""
    root = os.path.expanduser(root or os.path.join("~", ".mxnet", "models"))
    for fname in os.listdir(root) if os.path.isdir(root) else []:
        if fname.startswith(name) and fname.endswith(".params"):
            return os.path.join(root, fname)
    raise FileNotFoundError(
        "Pretrained model file for %r not found under %s. Downloads are "
        "disabled in this environment; place '%s-<hash>.params' there "
        "manually." % (name, root, name))


def purge(root=os.path.join("~", ".mxnet", "models")):
    """Remove all cached model files."""
    root = os.path.expanduser(root)
    if os.path.isdir(root):
        for f in os.listdir(root):
            if f.endswith(".params"):
                os.remove(os.path.join(root, f))
