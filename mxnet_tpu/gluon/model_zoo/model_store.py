"""Pretrained model store (parity: python/mxnet/gluon/model_zoo/model_store.py).

Zero-egress environment: no downloads — pretrained weights must be staged
locally under ``root`` (default ``~/.mxnet/models``); a missing file raises
with the expected filename.
"""
from __future__ import annotations

import os

__all__ = ["get_model_file", "purge"]

_model_sha1 = {}


def _repo_models_dir():
    """The in-repo ``models/`` artifact directory (checked as a fallback —
    this repo ships small pretrained checkpoints, e.g. digits-lenet)."""
    return os.path.normpath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..", "..", "..",
        "models"))


def get_model_file(name, root=os.path.join("~", ".mxnet", "models")):
    """Return the local path of a pretrained parameter file."""
    root = os.path.expanduser(root or os.path.join("~", ".mxnet", "models"))
    for d in (root, _repo_models_dir()):
        for fname in sorted(os.listdir(d)) if os.path.isdir(d) else []:
            if fname.startswith(name) and (fname.endswith(".params") or
                                           fname.endswith(".params.npz")):
                return os.path.join(d, fname)
    raise FileNotFoundError(
        "Pretrained model file for %r not found under %s or %s. Downloads "
        "are disabled in this environment; place '%s-<hash>.params' there "
        "manually." % (name, root, _repo_models_dir(), name))


def purge(root=os.path.join("~", ".mxnet", "models")):
    """Remove all cached model files."""
    root = os.path.expanduser(root)
    if os.path.isdir(root):
        for f in os.listdir(root):
            if f.endswith(".params"):
                os.remove(os.path.join(root, f))
