"""Gluon utility functions (parity: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import math

from .. import ndarray

__all__ = ["split_data", "split_and_load", "clip_global_norm",
           "check_sha1", "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split an NDArray into ``num_slice`` slices along ``batch_axis``
    (the gluon analog of executor_group.py:_split_input_slice)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            "data with shape %s cannot be evenly split into %d slices "
            "along axis %d. Use a batch size that's multiple of %d or set "
            "even_split=False to allow uneven partitioning of data." % (
                str(data.shape), num_slice, batch_axis, num_slice))
    step = size // num_slice
    if not even_split and size < num_slice:
        step = 1
        num_slice = size
    if batch_axis == 0:
        slices = [data[i * step:(i + 1) * step]
                  if i < num_slice - 1 else data[i * step:size]
                  for i in range(num_slice)]
    else:
        slices = [
            ndarray.slice_axis(data, batch_axis, i * step, (i + 1) * step)
            if i < num_slice - 1 else
            ndarray.slice_axis(data, batch_axis, i * step, size)
            for i in range(num_slice)]
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split data into len(ctx_list) slices and load each onto one ctx."""
    if not isinstance(data, ndarray.NDArray):
        data = ndarray.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [i.as_in_context(ctx) for i, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so that the sum of their 2-norms <= max_norm."""
    def _norm(array):
        if array.stype == "default":
            x = array.reshape((-1,))
            return ndarray.dot(x, x)
        return array.norm().square()

    assert len(arrays) > 0
    ctx = arrays[0].context
    total_norm = ndarray.add_n(
        *[_norm(arr).as_in_context(ctx) for arr in arrays])
    total_norm = ndarray.sqrt(total_norm)
    if check_isfinite:
        import numpy as np
        total_norm_val = float(total_norm.asscalar())
        if not np.isfinite(total_norm_val):
            import warnings
            warnings.warn(
                UserWarning("nan or inf is detected. Clipping results will "
                            "be undefined."), stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    scale = ndarray.minimum(scale, ndarray.ones(1, ctx=ctx))
    for arr in arrays:
        arr *= scale.as_in_context(arr.context)
    if check_isfinite:
        return total_norm_val
    return total_norm


def check_sha1(filename, sha1_hash):
    """Check whether the sha1 hash of the file content matches."""
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None):
    """Download a file (zero-egress environments: raises with guidance)."""
    raise RuntimeError(
        "download() requires network egress, which is unavailable in this "
        "environment; place the file at the target path manually")
