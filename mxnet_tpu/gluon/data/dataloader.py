"""Gluon DataLoader (parity: python/mxnet/gluon/data/dataloader.py).

TPU-native design: the reference forks worker processes and ships batches
through POSIX shared memory (``dataloader.py:26-102``, ``storage.cc:94``
kCPUShared) because Python decode holds the GIL.  Here decode/augment is
numpy/C work that releases the GIL, so workers are THREADS feeding a
bounded prefetch queue — no fork, no engine-restart-at-fork hazard
(reference ``initialize.cc:49``), and batches land directly in host memory
ready for ``device_put``.
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from ... import ndarray
from .sampler import BatchSampler, RandomSampler, SequentialSampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Collate samples into a batch (ref dataloader.py:default_batchify_fn)."""
    if isinstance(data[0], ndarray.NDArray):
        return ndarray.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = np.asarray(data)
    return ndarray.array(data, dtype=data.dtype)


class DataLoader:
    """Loads data from a Dataset, returns mini-batches.

    ``num_workers > 0`` uses a thread pool with a bounded prefetch queue
    (double buffering, the PrefetcherIter analog).
    """

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                if shuffle:
                    sampler = RandomSampler(len(dataset))
                else:
                    sampler = SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._prefetch = max(
            0, int(prefetch) if prefetch is not None else 2 * self._num_workers)
        if batchify_fn is None:
            self._batchify_fn = default_batchify_fn
        else:
            self._batchify_fn = batchify_fn

    def __iter__(self):
        if self._num_workers == 0:
            def same_process_iter():
                for batch in self._batch_sampler:
                    yield self._batchify_fn(
                        [self._dataset[idx] for idx in batch])
            return same_process_iter()
        return _MultiWorkerIter(self)

    def __len__(self):
        return len(self._batch_sampler)


class _MultiWorkerIter:
    """Thread-pool iterator with in-order result delivery."""

    def __init__(self, loader):
        self._dataset = loader._dataset
        self._batchify_fn = loader._batchify_fn
        self._batch_iter = iter(loader._batch_sampler)
        self._num_workers = loader._num_workers
        self._depth = loader._prefetch or 2 * loader._num_workers
        self._results = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._work_q = queue.Queue()
        self._sent = 0
        self._rcvd = 0
        self._exhausted = False
        self._threads = [
            threading.Thread(target=self._worker, daemon=True)
            for _ in range(self._num_workers)]
        for t in self._threads:
            t.start()
        for _ in range(self._depth):
            self._push_next()

    def _push_next(self):
        batch = next(self._batch_iter, None)
        if batch is None:
            return
        self._work_q.put((self._sent, batch))
        self._sent += 1

    def _worker(self):
        while True:
            item = self._work_q.get()
            if item is None:
                return
            idx, batch = item
            try:
                result = self._batchify_fn(
                    [self._dataset[i] for i in batch])
            except Exception as e:  # propagate to consumer
                result = e
            with self._cond:
                self._results[idx] = result
                self._cond.notify_all()

    def __iter__(self):
        return self

    def __next__(self):
        self._push_next()
        if self._rcvd == self._sent:
            self._shutdown()
            raise StopIteration
        with self._cond:
            while self._rcvd not in self._results:
                self._cond.wait()
            result = self._results.pop(self._rcvd)
        self._rcvd += 1
        if isinstance(result, Exception):
            self._shutdown()
            raise result
        return result

    def _shutdown(self):
        if not self._exhausted:
            for _ in self._threads:
                self._work_q.put(None)
            self._exhausted = True

    next = __next__
