"""Gluon vision transforms (parity: python/mxnet/gluon/data/vision/transforms.py).

Pixel transforms run on uint8 HWC numpy/NDArray data on the host (they're
part of the input pipeline, not the XLA program); ToTensor/Normalize produce
the float CHW tensors the models consume.
"""
from __future__ import annotations

import random

import numpy as np

from .... import ndarray
from ....ndarray import NDArray
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential
from .... import image as _image

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "RandomResizedCrop",
           "CenterCrop", "Resize", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomHue", "RandomColorJitter",
           "RandomLighting"]


class Compose(Sequential):
    """Sequentially composes multiple transforms."""

    def __init__(self, transforms):
        super().__init__()
        transforms.append(None)
        hybrid = []
        for i in transforms:
            if isinstance(i, HybridBlock):
                hybrid.append(i)
                continue
            elif len(hybrid) == 1:
                self.add(hybrid[0])
                hybrid = []
            elif len(hybrid) > 1:
                hblock = HybridSequential()
                for j in hybrid:
                    hblock.add(j)
                hblock.hybridize()
                self.add(hblock)
                hybrid = []
            if i is not None:
                self.add(i)


class Cast(HybridBlock):
    """Casts input to a specific dtype."""

    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.Cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """uint8 HWC (or NHWC) [0,255] image → float32 CHW (NCHW) [0,1) tensor.

    Backed by the ``_image_to_tensor`` op (reference transforms call the
    ``_image_*`` ops of image_random.cc) so the conversion has ONE
    definition for both eager and hybridized paths.
    """

    def __init__(self):
        super().__init__()

    def hybrid_forward(self, F, x):
        return F.image.to_tensor(x)


class Normalize(HybridBlock):
    """Normalizes a CHW / NCHW tensor with mean and std per channel
    (backed by the ``_image_normalize`` op)."""

    def __init__(self, mean, std):
        super().__init__()
        self._mean = tuple(np.atleast_1d(np.asarray(mean, np.float32))
                           .tolist())
        self._std = tuple(np.atleast_1d(np.asarray(std, np.float32))
                          .tolist())

    def hybrid_forward(self, F, x):
        return F.image.normalize(x, mean=self._mean, std=self._std)


class Resize(Block):
    """Resize to the given size (int = shorter side, keeping aspect when
    keep_ratio)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interpolation = interpolation

    def forward(self, x):
        if isinstance(self._size, int):
            if not self._keep:
                wsize = hsize = self._size
            else:
                h, w = x.shape[:2]
                if h > w:
                    wsize = self._size
                    hsize = int(h * wsize / w)
                else:
                    hsize = self._size
                    wsize = int(w * hsize / h)
        else:
            wsize, hsize = self._size
        return _image.imresize(x, wsize, hsize, self._interpolation)


class CenterCrop(Block):
    """Crops the center region of the given size (pads/resizes up if
    needed)."""

    def __init__(self, size, interpolation=1):
        super().__init__()
        if isinstance(size, int):
            size = (size, size)
        self._size = size
        self._interpolation = interpolation

    def forward(self, x):
        return _image.center_crop(x, self._size, self._interpolation)[0]


class RandomResizedCrop(Block):
    """Random crop with random area/aspect, resized to ``size``."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        if isinstance(size, int):
            size = (size, size)
        self._args = (size, scale, ratio, interpolation)

    def forward(self, x):
        size, scale, ratio, interp = self._args
        return _image.random_size_crop(
            x, size, scale[0], ratio, interp=interp)[0]


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if random.random() < 0.5:
            x = ndarray.array(np.ascontiguousarray(x.asnumpy()[:, ::-1, :]))
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if random.random() < 0.5:
            x = ndarray.array(np.ascontiguousarray(x.asnumpy()[::-1, :, :]))
        return x


class _RandomJitterBase(Block):
    def __init__(self, value):
        super().__init__()
        self._value = value


class RandomBrightness(_RandomJitterBase):
    def forward(self, x):
        alpha = 1.0 + random.uniform(-self._value, self._value)
        return (x.astype("float32") * alpha).clip(0, 255)


class RandomContrast(_RandomJitterBase):
    def forward(self, x):
        alpha = 1.0 + random.uniform(-self._value, self._value)
        f = x.astype("float32")
        gray = f.mean()
        return ((f - gray) * alpha + gray).clip(0, 255)


class RandomSaturation(_RandomJitterBase):
    def forward(self, x):
        alpha = 1.0 + random.uniform(-self._value, self._value)
        f = x.astype("float32")
        coef = ndarray.array(np.array([0.299, 0.587, 0.114], np.float32))
        gray = (f * coef.reshape((1, 1, 3))).sum(axis=2, keepdims=True)
        return (f * alpha + gray * (1.0 - alpha)).clip(0, 255)


class RandomHue(_RandomJitterBase):
    def forward(self, x):
        alpha = random.uniform(-self._value, self._value)
        f = x.asnumpy().astype(np.float32)
        u, w = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0],
                       [0.0, u, -w],
                       [0.0, w, u]], np.float32)
        tyiq = np.array([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]], np.float32)
        ityiq = np.array([[1.0, 0.956, 0.621],
                          [1.0, -0.272, -0.647],
                          [1.0, -1.107, 1.705]], np.float32)
        t = ityiq @ bt @ tyiq
        return ndarray.array(np.clip(f @ t.T, 0, 255))


class RandomColorJitter(Block):
    """Random brightness+contrast+saturation+hue jitter."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._transforms = []
        if brightness:
            self._transforms.append(RandomBrightness(brightness))
        if contrast:
            self._transforms.append(RandomContrast(contrast))
        if saturation:
            self._transforms.append(RandomSaturation(saturation))
        if hue:
            self._transforms.append(RandomHue(hue))

    def forward(self, x):
        ts = list(self._transforms)
        random.shuffle(ts)
        for t in ts:
            x = t(x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA noise."""

    _eigval = np.array([55.46, 4.794, 1.148], np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        a = np.random.normal(0, self._alpha, size=(3,)).astype(np.float32)
        rgb = (self._eigvec * a * self._eigval).sum(axis=1)
        return (x.astype("float32")
                + ndarray.array(rgb.reshape(1, 1, 3))).clip(0, 255)
