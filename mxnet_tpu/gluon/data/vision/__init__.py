"""Gluon vision datasets + transforms."""
from .datasets import (MNIST, FashionMNIST, CIFAR10, CIFAR100,
                       ImageRecordDataset, ImageFolderDataset)
from . import transforms
