"""Gluon vision datasets (parity: python/mxnet/gluon/data/vision/datasets.py).

MNIST/FashionMNIST parse the IDX format; CIFAR10/100 the binary batches.
Zero-egress environment: files must already exist under ``root`` (no
auto-download); a clear error names the expected files.
"""
from __future__ import annotations

import gzip
import os
import struct
import warnings

import numpy as np

from .... import ndarray
from ....recordio import unpack_img
from ..dataset import Dataset, RecordFileDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    """Base for on-disk datasets."""

    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        root = os.path.expanduser(root)
        self._root = root
        if not os.path.isdir(root):
            os.makedirs(root)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


def _open_maybe_gz(path):
    if os.path.exists(path):
        return open(path, "rb")
    if os.path.exists(path + ".gz"):
        return gzip.open(path + ".gz", "rb")
    raise IOError(
        "dataset file %s (or %s.gz) not found; downloads are disabled in "
        "this environment — place the file there manually" % (path, path))


class MNIST(_DownloadedDataset):
    """MNIST handwritten digits (IDX format files under root)."""

    _train_data = "train-images-idx3-ubyte"
    _train_label = "train-labels-idx1-ubyte"
    _test_data = "t10k-images-idx3-ubyte"
    _test_label = "t10k-labels-idx1-ubyte"

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _get_data(self):
        data_file = self._train_data if self._train else self._test_data
        label_file = self._train_label if self._train else self._test_label
        with _open_maybe_gz(os.path.join(self._root, label_file)) as fin:
            struct.unpack(">II", fin.read(8))
            label = np.frombuffer(fin.read(), dtype=np.uint8) \
                .astype(np.int32)
        with _open_maybe_gz(os.path.join(self._root, data_file)) as fin:
            struct.unpack(">IIII", fin.read(16))
            data = np.frombuffer(fin.read(), dtype=np.uint8)
            data = data.reshape(len(label), 28, 28, 1)
        self._label = label
        self._data = ndarray.array(data, dtype=np.uint8)


class FashionMNIST(MNIST):
    """FashionMNIST clothing-article images (same IDX layout as MNIST)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 32x32 color images (binary batch files under root)."""

    _train_files = ["data_batch_%d.bin" % i for i in range(1, 6)]
    _test_files = ["test_batch.bin"]
    _label_bytes = 1

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _read_batch(self, filename):
        with _open_maybe_gz(filename) as fin:
            raw = np.frombuffer(fin.read(), dtype=np.uint8)
        record = raw.reshape(-1, 3072 + self._label_bytes)
        data = record[:, self._label_bytes:].reshape(-1, 3, 32, 32)
        label = record[:, self._label_bytes - 1].astype(np.int32)
        return data.transpose(0, 2, 3, 1), label

    def _get_data(self):
        files = self._train_files if self._train else self._test_files
        data, label = zip(*[
            self._read_batch(os.path.join(self._root, f)) for f in files])
        data = np.concatenate(data)
        label = np.concatenate(label)
        self._data = ndarray.array(data, dtype=np.uint8)
        self._label = label


class CIFAR100(CIFAR10):
    """CIFAR100 (fine_label=True selects the 100-class labels)."""

    _train_files = ["train.bin"]
    _test_files = ["test.bin"]
    _label_bytes = 2

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._label_bytes = 2
        self._label_idx = 1 if fine_label else 0
        super().__init__(root, train, transform)

    def _read_batch(self, filename):
        with _open_maybe_gz(filename) as fin:
            raw = np.frombuffer(fin.read(), dtype=np.uint8)
        record = raw.reshape(-1, 3072 + self._label_bytes)
        data = record[:, self._label_bytes:].reshape(-1, 3, 32, 32)
        label = record[:, self._label_idx].astype(np.int32)
        return data.transpose(0, 2, 3, 1), label


class ImageRecordDataset(RecordFileDataset):
    """Dataset over a RecordIO file containing packed images
    (im2rec output; ref datasets.py:ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        record = super().__getitem__(idx)
        header, img = unpack_img(record, iscolor=self._flag)
        if self._transform is not None:
            return self._transform(ndarray.array(img), header.label)
        return ndarray.array(img), header.label


class ImageFolderDataset(Dataset):
    """A dataset over 'root/category/image.jpg' folder layouts."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                warnings.warn(
                    "Ignoring %s, which is not a directory." % path,
                    stacklevel=3)
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    warnings.warn(
                        "Ignoring %s of type %s. Only support %s" % (
                            filename, ext, ", ".join(self._exts)))
                    continue
                self.items.append((filename, label))

    def __getitem__(self, idx):
        import cv2
        flag = cv2.IMREAD_COLOR if self._flag else cv2.IMREAD_GRAYSCALE
        img = cv2.imread(self.items[idx][0], flag)
        if self._flag:
            img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
        else:
            img = img[..., None]
        img = ndarray.array(img)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
