"""Runtime lock-order sanitizer (``MXNET_LOCKCHECK=1``).

The graftlint lock model (GL003) is static and conservative: it sees
every ``threading.Lock``/``RLock`` construction site in the package and
the acquisition ORDER it can prove, but it cannot see locks taken
through unresolvable indirection.  This module is the dynamic half of
that contract: with ``MXNET_LOCKCHECK=1`` in the environment,
``threading.Lock`` and ``threading.RLock`` constructions *inside the
mxnet_tpu package* return instrumented locks that record, per thread,
the set of locks held at every acquisition.  That yields the observed
lock-acquisition graph, which is

- checked **live** for cycles on every new edge (an ABBA order observed
  at runtime is reported the moment the second ordering appears — no
  actual deadlock needed, the interleaving just has to exist), and
- **diffed at exit** against the static graph from
  ``python -m tools.graftlint --dump-lock-graph``.

Exit-diff failure semantics (``report()["ok"]``):

- ``cycles``       — dynamic ABBA: two locks acquired in both orders.
- ``inversions``   — an observed edge (a, b) where the static graph
  proved (b, a) and never saw (a, b): runtime contradicts the model.
- ``unknown_locks`` — a lock constructed at a source site the static
  model has no entry for: the lint's site table is incomplete.

``uncovered_edges`` (observed edges the static walk never derived) are
reported for information but are NOT a failure: the static resolver
skips unresolvable callees on purpose, so observed ⊆ static does not
hold in general — only the three contradictions above do.

Install happens in ``mxnet_tpu/__init__.py`` *before* any submodule
import so module-level locks are instrumented too.  Everything here is
stdlib-only: importing anything from mxnet_tpu at install time would
create locks before the patch is in place.

Knobs: ``MXNET_LOCKCHECK`` (enable), ``MXNET_LOCKCHECK_REPORT``
(directory; each process appends ``lockcheck-<pid>.json`` at exit —
a directory, not a file, because the chaos harness forks workers that
inherit the environment and must not clobber each other's reports),
``MXNET_LOCKCHECK_STATIC`` (path to a pre-dumped ``--dump-lock-graph``
JSON; without it the exit hook builds the static graph by importing
tools.graftlint, which costs a few seconds).
"""
from __future__ import annotations

import atexit
import json
import os
import sys
import threading

__all__ = ["install", "installed", "report", "reset"]

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_PKG_DIR)

_installed = False
_real_lock = threading.Lock
_real_rlock = threading.RLock

# the registry mutex is a REAL lock created before patching and is a
# leaf: nothing is ever acquired while holding it, so it cannot take
# part in any ordering it is policing
_mu = threading.Lock()
_held = threading.local()             # .stack: list of site keys
_sites = {}                           # site key -> {"kind", "rel", "line"}
_edges = {}                           # (a, b) -> {"thread", "count"}
_cycles = []                          # [{"chain": [...], "thread": ...}]


def _site_key(rel: str, line: int) -> str:
    return "%s:%d" % (rel, line)


def _stack():
    st = getattr(_held, "stack", None)
    if st is None:
        st = _held.stack = []
    return st


def _find_cycle(start: str) -> list:
    """DFS from ``start`` over the observed edge graph; the edge closing
    a cycle through ``start`` was just inserted."""
    adj = {}
    for a, b in _edges:
        adj.setdefault(a, []).append(b)
    path, seen = [start], {start}

    def walk(node):
        for nxt in adj.get(node, ()):
            if nxt == start:
                return True
            if nxt in seen:
                continue
            seen.add(nxt)
            path.append(nxt)
            if walk(nxt):
                return True
            path.pop()
        return False

    return path + [start] if walk(start) else []


def _on_acquired(site: str) -> None:
    st = _stack()
    new_cycle = None
    with _mu:
        for holder in st:
            if holder == site:
                continue            # re-entrant / same-site family
            edge = (holder, site)
            rec = _edges.get(edge)
            if rec is not None:
                rec["count"] += 1
                continue
            _edges[edge] = {"thread": threading.current_thread().name,
                            "count": 1}
            cyc = _find_cycle(site)
            if cyc:
                new_cycle = {"chain": cyc,
                             "thread": threading.current_thread().name}
                _cycles.append(new_cycle)
    st.append(site)
    if new_cycle is not None:
        sys.stderr.write(
            "mxnet_tpu.locksmith: lock-order cycle observed: %s "
            "(thread %s)\n" % (" -> ".join(new_cycle["chain"]),
                               new_cycle["thread"]))


def _on_released(site: str) -> None:
    st = _stack()
    # remove the LAST occurrence: release order is not enforced to be
    # stack order (hand-over-hand locking releases the outer lock first)
    for i in range(len(st) - 1, -1, -1):
        if st[i] == site:
            del st[i]
            break


class _TracedLock:
    """Order-tracking wrapper over a real Lock/RLock.  API-compatible
    with both, including the private Condition protocol so
    ``threading.Condition(traced_lock)`` keeps working."""

    __slots__ = ("_inner", "_site")

    def __init__(self, inner, site: str):
        self._inner = inner
        self._site = site

    def acquire(self, blocking=True, timeout=-1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            _on_acquired(self._site)
        return got

    def release(self):
        self._inner.release()
        _on_released(self._site)

    def locked(self):
        fn = getattr(self._inner, "locked", None)
        return fn() if fn is not None else False

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return "<locksmith %r wrapping %r>" % (self._site, self._inner)

    # -- Condition protocol ------------------------------------------
    def _release_save(self):
        saver = getattr(self._inner, "_release_save", None)
        state = saver() if saver is not None else self._inner.release()
        _on_released(self._site)
        return state

    def _acquire_restore(self, state):
        restore = getattr(self._inner, "_acquire_restore", None)
        if restore is not None:
            restore(state)
        else:
            self._inner.acquire()
        _on_acquired(self._site)

    def _is_owned(self):
        owned = getattr(self._inner, "_is_owned", None)
        if owned is not None:
            return owned()
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


def _caller_site():
    """(site key, register) for the frame constructing the lock; None
    when the construction is outside the package (stdlib internals,
    user code) and must stay untraced."""
    try:
        frame = sys._getframe(2)
    except ValueError:          # pragma: no cover - no caller frame
        return None
    fname = frame.f_code.co_filename
    try:
        apath = os.path.abspath(fname)
    except (OSError, ValueError):  # pragma: no cover
        return None
    if not apath.startswith(_PKG_DIR + os.sep) and apath != _PKG_DIR:
        return None
    rel = os.path.relpath(apath, _ROOT).replace(os.sep, "/")
    if rel.endswith("locksmith.py"):
        return None
    return _site_key(rel, frame.f_lineno)


def _traced_factory(real, kind):
    def factory(*args, **kwargs):
        inner = real(*args, **kwargs)
        site = _caller_site()
        if site is None:
            return inner
        with _mu:
            if site not in _sites:
                rel, _, line = site.rpartition(":")
                _sites[site] = {"kind": kind, "rel": rel,
                                "line": int(line)}
        return _TracedLock(inner, site)
    factory.__name__ = kind
    return factory


# -- static graph ------------------------------------------------------
def _load_static_graph():
    """The ``--dump-lock-graph`` JSON: from MXNET_LOCKCHECK_STATIC when
    set, else computed by importing the linter.  None when neither
    works (the exit diff is then skipped, not failed)."""
    path = os.environ.get("MXNET_LOCKCHECK_STATIC")
    if path:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None
    if not os.path.isdir(os.path.join(_ROOT, "tools", "graftlint")):
        return None
    try:
        if _ROOT not in sys.path:
            sys.path.insert(0, _ROOT)
        from tools.graftlint import Project
        from tools.graftlint.dataflow import lock_graph
        return lock_graph(Project(_ROOT))
    except Exception:
        return None


def _diff_static(static):
    """Contradictions between the observed graph and the static one."""
    diff = {"cycles": list(_cycles), "inversions": [],
            "unknown_locks": [], "uncovered_edges": []}
    if static is None:
        return diff, False
    static_sites = set(static.get("sites", {}))
    site_lid = dict(static.get("sites", {}))
    static_edges = {tuple(e) for e in static.get("edges", [])}
    for site in sorted(_sites):
        if site not in static_sites:
            diff["unknown_locks"].append(site)
    for a, b in sorted(_edges):
        la, lb = site_lid.get(a), site_lid.get(b)
        if la is None or lb is None or la == lb:
            continue
        if (la, lb) in static_edges:
            continue
        if (lb, la) in static_edges:
            diff["inversions"].append([la, lb])
        else:
            diff["uncovered_edges"].append([la, lb])
    return diff, True


def report():
    """Observed graph + static diff.  ``ok`` is False on any cycle,
    inversion or unknown lock site (uncovered edges are informational —
    see the module docstring for why)."""
    static = _load_static_graph()
    with _mu:
        snap_sites = {k: dict(v) for k, v in _sites.items()}
        snap_edges = [[a, b, _edges[(a, b)]["count"]]
                      for a, b in sorted(_edges)]
    diff, had_static = _diff_static(static)
    ok = not (diff["cycles"] or diff["inversions"] or
              diff["unknown_locks"])
    return {"version": 1, "pid": os.getpid(),
            "enabled": _installed, "static_graph": had_static,
            "sites": snap_sites, "edges": snap_edges,
            "diff": diff, "ok": ok}


def reset():
    """Drop all observed state (test isolation)."""
    with _mu:
        _sites.clear()
        _edges.clear()
        del _cycles[:]
    _held.stack = []


def _exit_report():   # pragma: no cover - exercised via subprocess tests
    rep = report()
    out_dir = os.environ.get("MXNET_LOCKCHECK_REPORT")
    if out_dir:
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(out_dir,
                                "lockcheck-%d.json" % os.getpid())
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(rep, fh, indent=2, sort_keys=True)
        except OSError as exc:
            sys.stderr.write("mxnet_tpu.locksmith: cannot write report: "
                             "%s\n" % exc)
    if not rep["ok"]:
        sys.stderr.write(
            "mxnet_tpu.locksmith: FAIL — %d cycle(s), %d inversion(s), "
            "%d unknown lock site(s)\n"
            % (len(rep["diff"]["cycles"]), len(rep["diff"]["inversions"]),
               len(rep["diff"]["unknown_locks"])))


def installed() -> bool:
    return _installed


def install() -> bool:
    """Patch ``threading.Lock``/``RLock`` when ``MXNET_LOCKCHECK`` is
    truthy.  Idempotent; returns whether the sanitizer is active."""
    global _installed
    if _installed:
        return True
    if os.environ.get("MXNET_LOCKCHECK", "0").lower() in \
            ("", "0", "false", "off"):
        return False
    threading.Lock = _traced_factory(_real_lock, "Lock")
    threading.RLock = _traced_factory(_real_rlock, "RLock")
    _installed = True
    atexit.register(_exit_report)
    return True
