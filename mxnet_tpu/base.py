"""Foundational utilities for the mxnet_tpu framework.

TPU-native re-design of the reference's dmlc-core foundations (logging/CHECK
macros, ``dmlc::Parameter`` typed reflection, ``dmlc::GetEnv`` config, and the
error layer behind ``MXGetLastError`` in ``src/c_api/c_api_error.cc``).  There
is no C ABI waist here: the Python frontend talks directly to the JAX/XLA
runtime, so the "C API error ring" becomes a plain exception hierarchy.
"""
from __future__ import annotations

import os
import functools
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import numpy as np

__all__ = [
    "MXNetError",
    "NotSupportedForSparseNDArray",
    "get_env",
    "AttrDict",
    "Registry",
    "string_types",
    "numeric_types",
    "integer_types",
    "classproperty",
]

string_types = (str,)
numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)


# Set by mxnet_tpu.tracing at import: called with each constructed MXNetError
# so the flight recorder can dump its ring for post-mortem context.  Must
# never interfere with raising the error itself.
_ERROR_HOOK: Optional[Callable] = None


class MXNetError(RuntimeError):
    """Top-level framework error (parity with ``mxnet.base.MXNetError``)."""

    def __init__(self, *args):
        super().__init__(*args)
        if _ERROR_HOOK is not None:
            try:
                _ERROR_HOOK(self)
            except Exception:
                pass


class NotSupportedForSparseNDArray(MXNetError):
    def __init__(self, function, alias, *args):
        extra = " ".join(repr(a) for a in args)
        super().__init__(
            "Function {} (alias {}) is not supported for SparseNDArray {}".format(
                function, alias, extra))


def get_env(name: str, default: Any = None, dtype: type = str) -> Any:
    """Typed environment config, the analog of ``dmlc::GetEnv``.

    The reference reads ~100 env knobs (SURVEY.md §5.6); we keep the same
    mechanism so e.g. ``MXNET_ENGINE_TYPE=NaiveEngine`` still works.
    """
    val = os.environ.get(name)
    if val is None:
        return default
    if dtype is bool:
        return val.lower() not in ("0", "false", "off", "")
    return dtype(val)


class AttrDict(dict):
    """A hashable, frozen-after-construction dict of op attributes.

    Op attributes must be hashable so that a ``jax.jit`` compile cache can be
    keyed on ``(op_name, attrs, input shapes/dtypes)`` — the TPU analog of the
    reference's per-op parameter structs (``dmlc::Parameter``) + cuDNN algo
    registry cache.
    Values should be scalars / strings / tuples only.
    """

    def __hash__(self):  # type: ignore[override]
        return hash(tuple(sorted(self.items())))

    def __setattr__(self, k, v):
        raise AttributeError("AttrDict is read-only")

    def __getattr__(self, k):
        try:
            return self[k]
        except KeyError as e:
            raise AttributeError(k) from e


class Registry:
    """Simple name → object registry with alias support.

    Replaces the reference's DMLC registries (``DMLC_REGISTRY_ENABLE`` used for
    ops, data iterators, optimizers, initializers, metrics).
    """

    def __init__(self, kind: str):
        self.kind = kind
        self._map: Dict[str, Any] = {}

    def register(self, name: Optional[str] = None, obj: Any = None, *,
                 aliases: Iterable[str] = ()):  # decorator or direct
        def _do(o, nm):
            key = nm.lower()
            self._map[key] = o
            for a in aliases:
                self._map[a.lower()] = o
            return o

        if obj is not None:
            return _do(obj, name or getattr(obj, "__name__", None))
        def deco(o):
            return _do(o, name or getattr(o, "__name__", None))
        return deco

    def get(self, name: str) -> Any:
        key = name.lower()
        if key not in self._map:
            raise MXNetError(
                "Cannot find %s '%s' in registry. Available: %s"
                % (self.kind, name, sorted(self._map)[:50]))
        return self._map[key]

    def find(self, name: str) -> Optional[Any]:
        return self._map.get(name.lower())

    def __contains__(self, name):
        return name.lower() in self._map

    def list(self):
        return sorted(self._map)


class classproperty:
    def __init__(self, f):
        self.f = f

    def __get__(self, obj, owner):
        return self.f(owner)


def c_array(ctype, values):  # pragma: no cover - legacy-compat shim
    """Kept for API-shape parity with ``mxnet.base``; no ctypes layer exists."""
    return list(values)


@functools.lru_cache(maxsize=None)
def _np_dtype(name_or_dtype) -> np.dtype:
    return np.dtype(name_or_dtype)
